"""Client shim for the BatchedScorer sidecar.

Plays the role the reference's in-scheduler plugin boundary plays
(Score/ScoreExtensions at ``frameworkext/framework_extender.go:216``): a
host scheduler embeds this client, syncs its cluster view (full once,
sparse deltas on warm cycles) and gets NodeScoreLists / assignments back.
"""

from __future__ import annotations

import itertools
import os
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import grpc

from koordinator_tpu.bridge.codegen import method_path, pb2
from koordinator_tpu.bridge.state import numpy_to_tensor
from koordinator_tpu.obs.export import SpanExporter, resolve_export_dir
from koordinator_tpu.obs.lockwitness import witness_lock, witness_rlock
from koordinator_tpu.obs.spans import ClientTraceOp
from koordinator_tpu.replication.retry import BackoffPolicy

# channel-level failures: the RPC may or may not have reached the
# server, but the CLIENT state is intact — retryable through the shared
# backoff policy, and NEVER a reason to null the delta baseline (the
# generation-continuity check catches an ambiguous apply on the next
# acked Sync; ISSUE 11)
_TRANSIENT_CODES = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)

# every stub call carries a transport deadline so a hung daemon can
# never hang the caller forever (the koordlint unbounded-wait rule's
# client half); generous by default — cold compiles are minutes on a
# slow host — and tightened per-call by the propagated deadline budget
DEFAULT_RPC_TIMEOUT_MS = 300_000.0

_RETRY_AFTER_RE = re.compile(r"retry_after_ms=(\d+(?:\.\d+)?)")


def _is_transient(exc: BaseException) -> bool:
    return (
        isinstance(exc, grpc.RpcError)
        and exc.code() in _TRANSIENT_CODES
    )


def _is_shed(exc: BaseException) -> bool:
    """An admission-gate shed (RESOURCE_EXHAUSTED + retry-after hint):
    transient BY CONTRACT — the server is healthy and said when to come
    back — and never a reason to touch the delta baseline."""
    return (
        isinstance(exc, grpc.RpcError)
        and exc.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
    )


def retry_after_ms(exc: BaseException) -> Optional[float]:
    """The machine-parsable ``retry_after_ms=<n>`` hint a shed or
    breaker-open reply carries, or None."""
    if not isinstance(exc, grpc.RpcError):
        return None
    m = _RETRY_AFTER_RE.search(exc.details() or "")
    return float(m.group(1)) if m else None


def _is_not_leader(exc: BaseException) -> bool:
    """The follower's Sync refusal (replication/follower.py): a probe
    result, not an error — the promoted leader is elsewhere."""
    return (
        isinstance(exc, grpc.RpcError)
        and exc.code() == grpc.StatusCode.FAILED_PRECONDITION
        and "one writer" in (exc.details() or "")
    )


def parse_snapshot_id(snapshot_id: str) -> Tuple[str, int]:
    """Server snapshot ids are "s<epoch>-<generation>" (bridge/server.py;
    the epoch is a per-boot nonce).  Legacy epoch-less "s<generation>" ids
    parse with an empty epoch; malformed ids yield generation -1, which
    never satisfies a continuity check."""
    body = snapshot_id[1:] if snapshot_id.startswith("s") else snapshot_id
    epoch, sep, gen = body.rpartition("-")
    if not sep:
        epoch, gen = "", body
    try:
        return epoch, int(gen)
    except ValueError:
        return epoch, -1


def parse_follower_target(target: str) -> Tuple[str, int]:
    """Split a follower target's optional relay-tree depth annotation
    (ISSUE 18): ``"unix:///f.sock@2"`` -> ``("unix:///f.sock", 2)``.
    Un-annotated targets are depth 1 (a direct follower — the flat-tier
    shape), and a trailing ``@<non-int>`` is treated as part of the
    address, not an annotation (abstract sockets and IPv6 hosts may
    legitimately contain ``@``)."""
    addr, sep, depth = target.rpartition("@")
    if sep:
        try:
            return addr, max(1, int(depth))
        except ValueError:
            pass
    return target, 1


class _ChannelPool:
    """Round-robin pool of independent gRPC channels (ISSUE 6).

    One grpc-python channel multiplexes every in-flight RPC onto ONE
    HTTP/2 connection, so a 16–64-way Score worker burst serializes on
    a single socket's flow control and wire ordering long before it
    reaches the coalescer — the raw-UDS shims (one socket per worker)
    never had this funnel.  Worse, gRPC core keeps a GLOBAL subchannel
    pool: two channels to the same target with identical channel args
    silently share one TCP/UDS connection, so naively creating N
    channels buys nothing.  Each pool slot therefore carries a distinct
    ``koord.pool_slot`` channel arg — distinct args key distinct
    subchannels, giving the burst ``size`` real parallel connections.
    Callers round-robin over ``channels`` themselves
    (``ScorerClient._slot`` builds one stub per channel up front and
    picks per call): cheap, and per-RPC affinity does not matter for
    unary calls."""

    def __init__(self, target: str, size: int):
        self.channels = [
            grpc.insecure_channel(
                target,
                # unbounded frames to match make_server: a sparse-scale
                # full Sync (ISSUE 16) is far past the 4 MB default
                options=(
                    ("koord.pool_slot", i),
                    ("grpc.max_receive_message_length", -1),
                    ("grpc.max_send_message_length", -1),
                ),
            )
            for i in range(max(1, int(size)))
        ]

    def close(self) -> None:
        for ch in self.channels:
            ch.close()


class ScorerClient:
    def __init__(self, target: str, channels: int = 1,
                 followers: Sequence[str] = (),
                 retry_policy: Optional[BackoffPolicy] = None,
                 band: str = "",
                 deadline_ms: Optional[float] = None,
                 rpc_timeout_ms: Optional[float] = None,
                 trace_export: Optional[str] = None):
        """``target``: "unix:///path.sock" or host:port.

        ``channels``: size of the connection pool Score/Assign calls
        round-robin over (default 1 keeps the single-channel behavior).
        Size it to the caller's worker parallelism (the reference
        scheduler runs 16 Score workers) so a burst reaches the
        coalescer concurrently instead of serializing on one HTTP/2
        connection.  Sync stays PINNED to the first channel: delta
        frames are order-sensitive against the acked baseline, and one
        connection preserves their wire order for free.

        ``followers`` (ISSUE 8, the replicated serving tier): targets
        of read-replica daemons.  Sync keeps going to ``target`` (the
        LEADER — the tier's one writer), Score round-robins over the
        followers, and a follower still catching up (its
        FAILED_PRECONDITION means "that generation has not replicated
        here yet", not "your baseline is wrong") falls back to the
        leader for that one call — replication lag degrades to leader
        reads, never to a failed cycle or a spurious full re-sync.
        Assign stays on the leader, whose snapshot is never behind.

        Tree-aware discovery (ISSUE 18, the relay tree): a follower
        target may carry a depth annotation — ``"unix:///f.sock@2"``
        means hop 2, i.e. behind one relay.  Score then round-robins
        over the DEEPEST layer only (the leaves): interior relays
        spend their bandwidth fanning the stream out to children, and
        the leaf layer is where aggregate read capacity multiplies.
        Un-annotated targets default to depth 1, so a flat follower
        list behaves exactly as before; writer failover probes still
        visit every follower regardless of depth (a promotion can land
        anywhere in the tree).

        ``retry_policy`` (ISSUE 11): the shared jittered-exponential
        backoff/deadline budget (``replication.retry.BackoffPolicy``;
        default from the ``KOORD_RETRY_*`` envs) that paces every
        channel-level retry.  Transient UDS/channel errors
        (``UNAVAILABLE``/``DEADLINE_EXCEEDED``) retry WITHOUT touching
        the delta baseline — the generation-continuity check on the
        next acked reply is what guards an ambiguous apply, so a
        replayed delta can never silently double-apply — and when
        ``followers`` are configured the Sync/Assign retries PROBE
        them for a promoted leader (a follower's "one writer" refusal
        means "not me, keep looking"), so a SIGUSR2/admin-RPC
        promotion fails over without reconfiguring the client.

        ``band`` (ISSUE 13): this client's priority band
        (koord-prod|mid|batch|free; empty = legacy, prod treatment),
        stamped on every Score/Assign so the daemon's admission gate
        sheds on the band ladder — free absorbs overload first, prod
        last.

        ``deadline_ms`` (ISSUE 13 deadline propagation): per-RPC
        deadline budget stamped onto the wire (``deadline_ms`` request
        field) AND set as the gRPC transport deadline for Score/Assign;
        the server evicts a request whose budget ran out before it
        occupies a launch slot.  Default from ``KOORD_DEADLINE_MS``
        (unset/empty = no propagated deadline).  Shed replies
        (RESOURCE_EXHAUSTED) and breaker fast-fails (UNAVAILABLE) carry
        a ``retry_after_ms`` hint; retries sleep the HINT in place of
        the backoff delay — one pause per attempt, never both, so the
        hint cannot double-count against the retry budget.

        ``rpc_timeout_ms``: transport deadline applied to EVERY stub
        call (``KOORD_RPC_TIMEOUT_MS``, default 300 s) so a hung daemon
        can never hang the caller forever; ``deadline_ms`` tightens it
        per call when set.

        ``trace_export`` (ISSUE 14, distributed tracing): directory
        this client appends its OWN completed spans to as OTLP-shaped
        JSON lines (default from ``KOORD_TRACE_EXPORT``; None/unset =
        tracing off, zero cost).  When on, every logical RPC mints ONE
        trace id and a root op span, every ATTEMPT — retries, failover
        probes, the Sync full-resend — gets a child span whose id is
        stamped as the wire ``parent_span``, and the server's echoed
        ``server_span`` is recorded on the attempt.  A retried-then-
        shed-then-served request therefore assembles into one tree
        with one span per attempt (``python -m
        koordinator_tpu.obs.assemble`` over the export dirs)."""
        self._pool = _ChannelPool(target, channels)
        self.band = band or ""
        # `or`: empty env value means unset (the KOORD_* convention)
        if deadline_ms is None:
            env = os.environ.get("KOORD_DEADLINE_MS") or ""
            deadline_ms = float(env) if env else 0.0
        self._deadline_ms = max(0.0, float(deadline_ms))
        if rpc_timeout_ms is None:
            rpc_timeout_ms = float(
                os.environ.get("KOORD_RPC_TIMEOUT_MS")
                or DEFAULT_RPC_TIMEOUT_MS
            )
        self._rpc_timeout_ms = max(1.0, float(rpc_timeout_ms))
        self._channel = self._pool.channels[0]  # Sync's pinned channel
        self._retry = retry_policy or BackoffPolicy.from_env()

        def unary(channel, method, reply_cls):
            return channel.unary_unary(
                method_path(method),
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=reply_cls.FromString,
            )

        self._sync = unary(self._channel, "Sync", pb2.SyncReply)
        self._scores = [
            unary(ch, "Score", pb2.ScoreReply) for ch in self._pool.channels
        ]
        self._assigns = [
            unary(ch, "Assign", pb2.AssignReply)
            for ch in self._pool.channels
        ]
        parsed = [parse_follower_target(t) for t in followers]
        self._follower_depths = [d for _, d in parsed]
        self._follower_pools = [
            _ChannelPool(t, 1) for t, _ in parsed
        ]
        # the leaf layer: indices at the tree's maximum depth — the
        # Score round-robin set (see the docstring's tree-aware
        # discovery contract); every index stays in the writer probe
        # order
        max_depth = max(self._follower_depths, default=0)
        self._leaf_indices = [
            i for i, d in enumerate(self._follower_depths)
            if d == max_depth
        ]
        self._follower_scores = [
            unary(p.channels[0], "Score", pb2.ScoreReply)
            for p in self._follower_pools
        ]
        self._follower_syncs = [
            unary(p.channels[0], "Sync", pb2.SyncReply)
            for p in self._follower_pools
        ]
        self._follower_assigns = [
            unary(p.channels[0], "Assign", pb2.AssignReply)
            for p in self._follower_pools
        ]
        # which target currently holds the writer role: -1 = the
        # configured leader; 0..N-1 = follower i, promoted (discovered
        # by the Sync probe's failover).  Writes move with it; Score
        # keeps its follower round-robin either way.
        self._leader_idx = -1
        self._rr = itertools.count()
        self._rr_lock = witness_lock("bridge.client.ScorerClient._rr_lock")
        # previous-ACKED-sync mirrors (tensor + scalar columns) for delta
        # encoding and full re-sync.  New values are staged per request and
        # promoted only after the server confirms the Sync, so a failed RPC
        # can never desync the delta baseline.  _baseline_lock makes the
        # whole sync() read-encode-promote sequence atomic against
        # _invalidate() running on a pooled worker thread (a concurrent
        # Score's FAILED_PRECONDITION): an unlocked clear mid-sync would
        # both corrupt the delta encode and null _generation, silently
        # disabling the displaced-baseline continuity check.
        self._baseline_lock = witness_rlock(
            "bridge.client.ScorerClient._baseline_lock")
        self._prev: Dict[str, np.ndarray] = {}
        self._prev_scalars: Dict[str, tuple] = {}
        self._generation: Optional[int] = None
        self._epoch: Optional[str] = None
        self.snapshot_id: Optional[str] = None
        # whether the last flat Score reply carried the brownout
        # degraded flag (ISSUE 13)
        self.last_degraded = False
        # distributed tracing (ISSUE 14): the client's own span
        # exporter; None = tracing off (the default)
        self._exporter: Optional[SpanExporter] = None
        export_to = resolve_export_dir(trace_export)
        if export_to is not None:
            self._exporter = SpanExporter(
                export_to, service="scorer-client"
            )

    def close(self) -> None:
        self._pool.close()
        for p in self._follower_pools:
            p.close()
        if self._exporter is not None:
            self._exporter.close()

    # -- distributed tracing (ISSUE 14) --
    def _trace_op(self, name: str) -> Optional[ClientTraceOp]:
        """One logical RPC's trace (root op span + per-attempt child
        spans), or None when tracing is off."""
        if self._exporter is None:
            return None
        return ClientTraceOp(name, sink=self._exporter.export)

    def _traced_call(self, op: Optional[ClientTraceOp], stub, request,
                     timeout: float):
        """One ATTEMPT: stamp the op's trace context on the request
        (each attempt re-stamps its own span id as ``parent_span``),
        invoke, record the server's echoed span id, end — or abort
        with the error so sheds/deadline/transport failures stay
        visible per attempt in the assembled tree."""
        if op is None:
            return stub(request, timeout=timeout)
        span = op.attempt()
        request.trace_id = op.trace_id
        request.parent_span = span.span_id
        try:
            reply = stub(request, timeout=timeout)
        except BaseException as exc:
            span.abort(exc)
            raise
        server_span = getattr(reply, "server_span", "") or ""
        if server_span:
            span.set_attr("server_span", server_span)
        span.end()
        return reply

    def _slot(self) -> int:
        with self._rr_lock:
            return next(self._rr) % len(self._scores)

    # -- writer routing + failover (ISSUE 11) --
    def _writer_stubs(self, kind: str):
        """``(idx, stub)`` probe order for a write-side RPC: the target
        last seen holding the writer role first, then every other
        candidate (the configured leader, then each follower) — a
        probe pass visits the whole tier once."""
        leader_stub = (
            self._sync if kind == "sync"
            else self._assigns[self._slot()]
        )
        table = [(-1, leader_stub)] + list(enumerate(
            self._follower_syncs if kind == "sync"
            else self._follower_assigns
        ))
        active = self._leader_idx
        table.sort(key=lambda e: 0 if e[0] == active else 1)
        return table

    def _timeout_s(self) -> float:
        """Transport deadline for one stub call: the client-wide cap,
        tightened by the propagated per-RPC budget when one is set."""
        t = self._rpc_timeout_ms
        if self._deadline_ms > 0.0:
            t = min(t, self._deadline_ms)
        return t / 1000.0

    def _pause_ms(self, delays, last: Optional[BaseException]):
        """The ONE pause before the next retry attempt, or None when
        the budget is spent: a server retry-after hint (shed/breaker
        replies) REPLACES the backoff delay for this attempt — the
        attempt still consumes its slot in the policy's deadline
        budget, so a hint can never double-count against it."""
        d_ms = next(delays, None)
        if d_ms is None:
            return None
        hint = retry_after_ms(last) if last is not None else None
        if hint is not None:
            # cap at the policy's backoff ceiling: a 30 s free-band
            # hint must not park a caller past its own retry budget
            return min(hint, self._retry.cap_ms)
        return d_ms

    def _call_writer(self, kind: str, request, op=None):
        """Invoke a writer-side RPC (Sync/Assign) against the active
        leader, failing over through the shared backoff policy:
        transient channel errors retry, "one writer" refusals probe
        the next candidate, admission sheds retry after the server's
        hint, anything else surfaces immediately (it is the SERVER's
        answer, and the caller's protocol logic — e.g. sync()'s
        full-resend fallback — owns it).  The delta baseline is never
        touched here: an ambiguous apply is caught by the continuity
        check on the next acked reply."""
        delays = self._retry.delays()
        timeout = self._timeout_s()
        while True:
            last: Optional[BaseException] = None
            for idx, stub in self._writer_stubs(kind):
                try:
                    reply = self._traced_call(op, stub, request, timeout)
                    self._leader_idx = idx
                    return reply
                except grpc.RpcError as exc:
                    if (
                        _is_not_leader(exc) or _is_transient(exc)
                        or _is_shed(exc)
                    ):
                        last = exc
                        continue
                    raise
            pause = self._pause_ms(delays, last)
            if pause is None:
                raise last
            time.sleep(pause / 1000.0)

    def _score_stub(self):
        """Score's routing: round-robin over the LEAF-layer follower
        replicas when configured (the deepest annotated depth — with a
        flat follower list that is every follower), else over the
        leader's own channel pool.  Returns ``(stub, is_follower)``."""
        if self._follower_scores:
            with self._rr_lock:
                i = self._leaf_indices[
                    next(self._rr) % len(self._leaf_indices)
                ]
            return self._follower_scores[i], True
        return self._scores[self._slot()], False

    def _leader_score_stub(self):
        """The active writer's Score stub — the lag-fallback target
        (after a promotion the configured leader may be DEAD; the
        fallback must follow the role, not the config)."""
        if 0 <= self._leader_idx < len(self._follower_scores):
            return self._follower_scores[self._leader_idx]
        return self._scores[self._slot()]

    def _call_score(self, request, op=None):
        """Reads retry FREELY (ISSUE 11): they are idempotent against a
        named snapshot, so a transient channel error just moves to the
        next replica under the shared backoff budget.  A shed
        (RESOURCE_EXHAUSTED) retries too, paced by the server's
        retry-after hint in place of the backoff delay (ISSUE 13)."""
        delays = self._retry.delays()
        timeout = self._timeout_s()
        while True:
            stub, on_follower = self._score_stub()
            if on_follower:
                try:
                    return self._traced_call(op, stub, request, timeout)
                except grpc.RpcError as e:
                    if _is_transient(e) or _is_shed(e):
                        pause = self._pause_ms(delays, e)
                        if pause is None:
                            raise
                        time.sleep(pause / 1000.0)
                        continue  # next replica round-robin
                    if e.code() != grpc.StatusCode.FAILED_PRECONDITION:
                        raise
                    # the follower has not applied this generation yet
                    # (replication lag) — the LEADER certified the id,
                    # so the baseline is fine: serve this call there
                    # instead of invalidating anything
            try:
                return self._call(self._leader_score_stub(), request, op=op)
            except grpc.RpcError as e:
                if not (_is_transient(e) or _is_shed(e)):
                    raise
                pause = self._pause_ms(delays, e)
                if pause is None:
                    raise
                time.sleep(pause / 1000.0)

    def _invalidate(self) -> None:
        with self._baseline_lock:
            self._prev.clear()
            self._prev_scalars.clear()
            self._generation = None
            self._epoch = None
            self.snapshot_id = None

    def _with_op(self, name: str, fn):
        """Run one logical RPC under a client trace op (ISSUE 14):
        ``fn(op)`` gets the op (or None with tracing off) to thread
        into the retrying call helpers; the root span ends — with the
        escaping error attached, or clean — on every exit."""
        op = self._trace_op(name)
        if op is None:
            return fn(None)
        try:
            result = fn(op)
        except BaseException as exc:
            op.finish(error=exc)
            raise
        op.finish()
        return result

    def sync(self, **kwargs) -> "pb2.SyncReply":
        """One logical Sync (delta-encoded against the acked baseline;
        see :meth:`_sync_op` for the keyword surface).  Traced as ONE
        op: the delta attempt, any failover probes and the full-resend
        fallback are sibling attempt spans of the same trace."""
        return self._with_op(
            "sync", lambda op: self._sync_op(op, **kwargs)
        )

    def _sync_op(
        self,
        op=None,
        *,
        node_allocatable: Optional[np.ndarray] = None,
        node_requested: Optional[np.ndarray] = None,
        node_usage: Optional[np.ndarray] = None,
        node_names: Sequence[str] = (),
        metric_fresh: Optional[Sequence[bool]] = None,
        pod_requests: Optional[np.ndarray] = None,
        pod_estimated: Optional[np.ndarray] = None,
        pod_names: Sequence[str] = (),
        priority: Optional[Sequence[int]] = None,
        gang_id: Optional[Sequence[int]] = None,
        quota_id: Optional[Sequence[int]] = None,
        gang_min_member: Sequence[int] = (),
        quota_runtime: Optional[np.ndarray] = None,
        quota_used: Optional[np.ndarray] = None,
        quota_limited: Optional[np.ndarray] = None,
        node_bucket: int = 0,
        pod_bucket: int = 0,
        node_accel_type: Optional[Sequence[int]] = None,
        workload_class: Optional[Sequence[int]] = None,
        pod_sensitivity: Optional[np.ndarray] = None,
        throughput: Optional[np.ndarray] = None,
    ) -> "pb2.SyncReply":
        tensors = {
            "nalloc": node_allocatable,
            "nreq": node_requested,
            "nuse": node_usage,
            "preq": pod_requests,
            "pest": pod_estimated,
            "qrt": quota_runtime,
            "quse": quota_used,
            "qlim": quota_limited,
            # fused-term tensors (ISSUE 15): the Synergy sensitivity
            # profile and the Gavel throughput matrix ride the same
            # delta-encoding path as every snapshot tensor
            "psens": pod_sensitivity,
            "tput": throughput,
        }
        scalars = {
            "node_names": tuple(node_names),
            "metric_fresh": (
                tuple(bool(b) for b in metric_fresh)
                if metric_fresh is not None
                else None
            ),
            "pod_names": tuple(pod_names),
            "priority": tuple(priority) if priority is not None else None,
            "gang_id": tuple(gang_id) if gang_id is not None else None,
            "quota_id": tuple(quota_id) if quota_id is not None else None,
            "gang_min": tuple(gang_min_member),
            "accel_type": (
                tuple(int(v) for v in node_accel_type)
                if node_accel_type is not None
                else None
            ),
            "workload_class": (
                tuple(int(v) for v in workload_class)
                if workload_class is not None
                else None
            ),
        }

        staged: Dict[str, np.ndarray] = {}
        staged_scalars: Dict[str, tuple] = {}

        def build(baseline: Dict[str, np.ndarray], full: bool):
            staged.clear()
            staged_scalars.clear()

            def tensor(key):
                arr = tensors[key]
                if full and arr is None:
                    arr = baseline.get(key)  # resend last acked state
                if arr is None:
                    return pb2.Tensor()
                a = np.ascontiguousarray(arr, np.int64)
                t = numpy_to_tensor(a, None if full else baseline.get(key))
                staged[key] = a
                return t

            def scalar(key):
                val = scalars[key]
                if (val is None or val == ()) and full:
                    val = self._prev_scalars.get(key)
                # the server treats empty repeated fields as "unchanged",
                # so only non-empty values become the acked baseline
                if val:
                    staged_scalars[key] = val
                return val

            req = pb2.SyncRequest(node_bucket=node_bucket, pod_bucket=pod_bucket)
            req.nodes.allocatable.CopyFrom(tensor("nalloc"))
            req.nodes.requested.CopyFrom(tensor("nreq"))
            req.nodes.usage.CopyFrom(tensor("nuse"))
            req.nodes.names.extend(scalar("node_names") or ())
            fresh = scalar("metric_fresh")
            if fresh is not None:
                req.nodes.metric_fresh.extend(fresh)
            req.pods.requests.CopyFrom(tensor("preq"))
            req.pods.estimated.CopyFrom(tensor("pest"))
            req.pods.names.extend(scalar("pod_names") or ())
            prio = scalar("priority")
            if prio is not None:
                req.pods.priority.extend(int(v) for v in prio)
            gang = scalar("gang_id")
            if gang is not None:
                req.pods.gang_id.extend(int(v) for v in gang)
            quota = scalar("quota_id")
            if quota is not None:
                req.pods.quota_id.extend(int(v) for v in quota)
            req.gangs.min_member.extend(int(v) for v in scalar("gang_min") or ())
            req.quotas.runtime.CopyFrom(tensor("qrt"))
            req.quotas.used.CopyFrom(tensor("quse"))
            req.quotas.limited.CopyFrom(tensor("qlim"))
            accel = scalar("accel_type")
            if accel is not None:
                req.nodes.accel_type.extend(accel)
            wclass = scalar("workload_class")
            if wclass is not None:
                req.pods.workload_class.extend(wclass)
            req.pods.sensitivity.CopyFrom(tensor("psens"))
            req.terms.throughput.CopyFrom(tensor("tput"))
            return req

        # the lock is held across the RPCs: a pooled Score thread's
        # _invalidate (FAILED_PRECONDITION on displacement) must not
        # clear the dict build() is delta-encoding from, nor null
        # _generation between the reply and the continuity check below
        # — it waits, then wipes the fresh baseline, and the NEXT sync
        # ships full state (a re-encode, never silent corruption)
        with self._baseline_lock:
            baseline = self._prev
            sent_full = False
            try:
                reply = self._call_writer(
                    "sync", build(baseline, full=False), op=op
                )
            except grpc.RpcError as exc:
                if _is_transient(exc) or _is_not_leader(exc):
                    # channel-level failure that outlived the whole
                    # retry/probe budget: the BASELINE IS KEPT (ISSUE
                    # 11 satellite) — nothing verifiably applied, so
                    # nulling _generation here would silently force a
                    # full resync on every transient blip; the next
                    # sync retries the delta and the continuity check
                    # below guards the ambiguous-apply case
                    raise
                if not baseline:
                    # nothing was delta-encoded; the failure is not
                    # recoverable by resending full state
                    self._invalidate()
                    raise
                # a restarted sidecar lost its resident tensors and refused
                # the delta frame — recoverable within the same cycle with
                # one full re-sync (ADVICE r5); a second failure is surfaced
                try:
                    reply = self._call_writer(
                        "sync", build(baseline, full=True), op=op
                    )
                    sent_full = True
                except grpc.RpcError:
                    self._invalidate()
                    raise
            epoch, gen = parse_snapshot_id(reply.snapshot_id)
            if self._generation is not None and not sent_full and (
                epoch != self._epoch or gen != self._generation + 1
            ):
                # another client synced in between, or the server restarted
                # (fresh epoch — the bare generation can coincidentally line
                # up after a restart, so the epoch check is load-bearing):
                # our deltas were applied onto a base we never saw.  Re-sync
                # full tensors — from the pre-clear baseline, so fields
                # omitted this cycle still resend their last acked state.
                try:
                    reply = self._call_writer(
                        "sync", build(baseline, full=True), op=op
                    )
                except grpc.RpcError:
                    # the server may have applied the full sync before
                    # failing; treat the baseline as unknown
                    self._invalidate()
                    raise
                epoch, gen = parse_snapshot_id(reply.snapshot_id)
            self._prev = dict(baseline, **staged)
            self._prev_scalars.update(staged_scalars)
            self._generation = gen
            self._epoch = epoch
            self.snapshot_id = reply.snapshot_id
            return reply

    # -- score / assign --
    def _call(self, stub, request, op=None):
        """Invoke Score/Assign; on FAILED_PRECONDITION (our snapshot was
        displaced by another client's Sync) invalidate the baseline so the
        caller's next sync() ships full state, then surface the error."""
        try:
            return self._traced_call(op, stub, request, self._timeout_s())
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.FAILED_PRECONDITION:
                self._invalidate()
            raise

    def _score_request(self, top_k: int, flat: bool = False):
        """One Score request with the propagated deadline budget and
        this client's band stamped on (ISSUE 13)."""
        return pb2.ScoreRequest(
            snapshot_id=self.snapshot_id or "", top_k=top_k, flat=flat,
            deadline_ms=int(self._deadline_ms), band=self.band,
        )

    def score(self, top_k: int = 0) -> List[List[Tuple[int, int]]]:
        reply = self._with_op(
            "score",
            lambda op: self._call_score(self._score_request(top_k), op=op),
        )
        return [
            list(zip(entry.node_index, entry.score)) for entry in reply.pods
        ]

    def score_flat(
        self, top_k: int = 0
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Flat top-k layout: (pod_index, counts, node_index, score) numpy
        arrays decoded straight from the packed reply bytes — the O(1)
        assembly path on both ends (round-3 review #8).  Entry group g
        (pod pod_index[g]) covers counts[g] consecutive entries."""
        reply = self._with_op(
            "score",
            lambda op: self._call_score(
                self._score_request(top_k, flat=True), op=op
            ),
        )
        # degraded visibility (ISSUE 13): True when the LAST flat Score
        # was served stale from the daemon's brownout cache while its
        # breaker was open — callers alarm on it instead of discovering
        # staleness in a placement graph
        self.last_degraded = bool(reply.degraded)
        if not reply.HasField("flat"):
            # a pre-flat server ignores the unknown request flag and sends
            # legacy lists; empty arrays here would read as "no feasible
            # node for any pod" — fail loudly instead
            raise RuntimeError(
                "scorer did not return the flat layout (server too old?); "
                "use score() for the legacy per-pod lists"
            )
        # .copy(): frombuffer over proto bytes is read-only; callers get
        # writable arrays like assign() returns
        return (
            np.frombuffer(reply.flat.pod_index, "<i4").copy(),
            np.frombuffer(reply.flat.counts, "<i4").copy(),
            np.frombuffer(reply.flat.node_index, "<i4").copy(),
            np.frombuffer(reply.flat.score, "<i8").copy(),
        )

    def assign(self) -> Tuple[np.ndarray, np.ndarray, float, str]:
        """Returns (assignment, status, cycle_ms, path); ``path`` names the
        device program that ran ("pallas"/"scan"/"shard") so callers can
        alarm on a degraded-path cycle instead of discovering it in a
        latency graph."""
        return self._with_op("assign", self._assign_op)

    def _assign_op(self, op=None):
        try:
            reply = self._call_writer(
                "assign",
                pb2.AssignRequest(
                    snapshot_id=self.snapshot_id or "",
                    deadline_ms=int(self._deadline_ms),
                    band=self.band,
                ),
                op=op,
            )
        except grpc.RpcError as e:
            # displaced snapshot (stale-id FAILED_PRECONDITION): the
            # baseline is gone — next sync ships full state.  The
            # "one writer" flavor CAN escape the probe when no replica
            # accepts writes inside the retry budget (leader dead,
            # nothing promoted yet) — that baseline is fine and must
            # survive, like the sync() transient path.
            if (
                e.code() == grpc.StatusCode.FAILED_PRECONDITION
                and not _is_not_leader(e)
            ):
                self._invalidate()
            raise
        return (
            np.asarray(reply.assignment, np.int32),
            np.asarray(reply.status, np.int32),
            reply.cycle_ms,
            reply.path,
        )
