"""Client shim for the BatchedScorer sidecar.

Plays the role the reference's in-scheduler plugin boundary plays
(Score/ScoreExtensions at ``frameworkext/framework_extender.go:216``): a
host scheduler embeds this client, syncs its cluster view (full once,
sparse deltas on warm cycles) and gets NodeScoreLists / assignments back.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import grpc

from koordinator_tpu.bridge.codegen import method_path, pb2
from koordinator_tpu.bridge.state import numpy_to_tensor


class ScorerClient:
    def __init__(self, target: str):
        """``target``: "unix:///path.sock" or host:port."""
        self._channel = grpc.insecure_channel(target)
        self._sync = self._channel.unary_unary(
            method_path("Sync"),
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb2.SyncReply.FromString,
        )
        self._score = self._channel.unary_unary(
            method_path("Score"),
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb2.ScoreReply.FromString,
        )
        self._assign = self._channel.unary_unary(
            method_path("Assign"),
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb2.AssignReply.FromString,
        )
        # previous-sync mirrors for delta encoding
        self._prev: Dict[str, np.ndarray] = {}
        self.snapshot_id: Optional[str] = None

    def close(self) -> None:
        self._channel.close()

    # -- sync --
    def _tensor(self, key: str, arr: Optional[np.ndarray]) -> "pb2.Tensor":
        if arr is None:
            return pb2.Tensor()
        arr = np.ascontiguousarray(arr, np.int64)
        t = numpy_to_tensor(arr, self._prev.get(key))
        self._prev[key] = arr
        return t

    def sync(
        self,
        *,
        node_allocatable: Optional[np.ndarray] = None,
        node_requested: Optional[np.ndarray] = None,
        node_usage: Optional[np.ndarray] = None,
        node_names: Sequence[str] = (),
        metric_fresh: Optional[Sequence[bool]] = None,
        pod_requests: Optional[np.ndarray] = None,
        pod_estimated: Optional[np.ndarray] = None,
        pod_names: Sequence[str] = (),
        priority: Optional[Sequence[int]] = None,
        gang_id: Optional[Sequence[int]] = None,
        quota_id: Optional[Sequence[int]] = None,
        gang_min_member: Sequence[int] = (),
        quota_runtime: Optional[np.ndarray] = None,
        quota_used: Optional[np.ndarray] = None,
        quota_limited: Optional[np.ndarray] = None,
        node_bucket: int = 0,
        pod_bucket: int = 0,
    ) -> "pb2.SyncReply":
        req = pb2.SyncRequest(node_bucket=node_bucket, pod_bucket=pod_bucket)
        req.nodes.allocatable.CopyFrom(self._tensor("nalloc", node_allocatable))
        req.nodes.requested.CopyFrom(self._tensor("nreq", node_requested))
        req.nodes.usage.CopyFrom(self._tensor("nuse", node_usage))
        req.nodes.names.extend(node_names)
        if metric_fresh is not None:
            req.nodes.metric_fresh.extend(bool(b) for b in metric_fresh)
        req.pods.requests.CopyFrom(self._tensor("preq", pod_requests))
        req.pods.estimated.CopyFrom(self._tensor("pest", pod_estimated))
        req.pods.names.extend(pod_names)
        if priority is not None:
            req.pods.priority.extend(int(v) for v in priority)
        if gang_id is not None:
            req.pods.gang_id.extend(int(v) for v in gang_id)
        if quota_id is not None:
            req.pods.quota_id.extend(int(v) for v in quota_id)
        req.gangs.min_member.extend(int(v) for v in gang_min_member)
        req.quotas.runtime.CopyFrom(self._tensor("qrt", quota_runtime))
        req.quotas.used.CopyFrom(self._tensor("quse", quota_used))
        req.quotas.limited.CopyFrom(self._tensor("qlim", quota_limited))
        reply = self._sync(req)
        self.snapshot_id = reply.snapshot_id
        return reply

    # -- score / assign --
    def score(self, top_k: int = 0) -> List[List[Tuple[int, int]]]:
        reply = self._score(
            pb2.ScoreRequest(snapshot_id=self.snapshot_id or "", top_k=top_k)
        )
        return [
            list(zip(entry.node_index, entry.score)) for entry in reply.pods
        ]

    def assign(self) -> Tuple[np.ndarray, np.ndarray, float]:
        reply = self._assign(pb2.AssignRequest(snapshot_id=self.snapshot_id or ""))
        return (
            np.asarray(reply.assignment, np.int32),
            np.asarray(reply.status, np.int32),
            reply.cycle_ms,
        )
