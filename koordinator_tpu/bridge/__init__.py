"""The sidecar boundary: host scheduler plugins <-> the JAX/TPU scorer.

SURVEY §7.5: the reference proves the seam at the scheduler framework's
Score boundary (reference
``pkg/scheduler/frameworkext/framework_extender.go:216``); its process
fabric is gRPC over UDS (reference
``pkg/runtimeproxy/server/cri/criserver.go:93``, proto
``apis/runtime/v1alpha1/api.proto:148``).  Here the same shape: a
``BatchedScorer`` gRPC service (scorer.proto) holding the cluster snapshot
resident on device, with sparse-delta refresh for warm cycles
(native/koordnative.cpp codec) so the host->device boundary ships only
what changed.
"""

from koordinator_tpu.bridge.codegen import pb2  # noqa: F401
from koordinator_tpu.bridge.client import ScorerClient  # noqa: F401
from koordinator_tpu.bridge.server import serve_uds  # noqa: F401
