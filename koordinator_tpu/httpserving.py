"""Shared lifecycle for the daemons' HTTP servers.

``BaseServer.shutdown()`` blocks on an event only ``serve_forever()``'s
``finally`` sets, so calling it when the serving loop never ran deadlocks
— and checking a started-inside-the-thread flag instead is a TOCTOU race
(stop() between ``thread.start()`` and the loop's first iteration would
``server_close()`` a socket ``serve_forever()`` is about to use).  The
flag here flips BEFORE ``thread.start()``: once the thread is started,
``serve_forever()`` is guaranteed to run eventually and release
``shutdown()``.
"""

from __future__ import annotations

import sys
import threading
import traceback
from socketserver import BaseServer


def format_thread_stacks() -> str:
    """Live stack dump of every thread — the per-binary net/http/pprof
    analog (reference serves pprof on each daemon,
    cmd/koord-scheduler/app/server.go:287 etc.)."""
    lines = []
    for tid, frame in sys._current_frames().items():
        lines.append(f"Thread {tid}:\n")
        lines.extend(traceback.format_stack(frame))
    return "".join(lines)


def reply_text(handler, body: str, code: int = 200) -> None:
    data = body.encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "text/plain")
    handler.send_header("Content-Length", str(len(data)))
    handler.end_headers()
    handler.wfile.write(data)


class HTTPLifecycle:
    """Owns the serve thread + safe shutdown for one http.server."""

    def __init__(self, httpd: BaseServer):
        self.httpd = httpd
        self._started = False
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    def start(self) -> None:
        self._started = True  # before thread.start(): shutdown() may block
        self._thread.start()

    def stop(self) -> None:
        if self._started:
            self.httpd.shutdown()
        self.httpd.server_close()
