"""Cycle configuration: plugin args + weights, mirroring the reference's
component-config (reference ``pkg/scheduler/apis/config/types.go:30-205``,
defaults ``v1beta2/defaults.go:33-48``)."""

from __future__ import annotations

import dataclasses
from typing import Mapping, Tuple, Union

import jax.numpy as jnp

from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import (
    DEFAULT_ESTIMATED_SCALING_FACTORS,
    DEFAULT_RESOURCE_WEIGHTS,
    DEFAULT_USAGE_THRESHOLDS,
    PERCENTILES,
)

LEAST_ALLOCATED = "LeastAllocated"
MOST_ALLOCATED = "MostAllocated"

# Configs are passed to jax.jit as static arguments, so every field must be
# hashable: mappings are stored as sorted (name, value) tuples.
ResMap = Union[Mapping[str, int], Tuple[Tuple[str, int], ...]]


def _freeze(m: ResMap) -> Tuple[Tuple[str, int], ...]:
    if isinstance(m, tuple):
        return m
    return tuple(sorted((k, int(v)) for k, v in m.items()))


@dataclasses.dataclass(frozen=True)
class AggregatedArgs:
    """reference config.LoadAwareSchedulingAggregatedArgs (types.go:66):
    filter/score against an aggregated usage percentile instead of the
    instantaneous NodeUsage.  Durations are a host-side concern (the
    snapshot carries one aggregation window's percentiles)."""

    usage_thresholds: ResMap = ()
    usage_aggregation_type: str = "p99"
    score_aggregation_type: str = ""  # "" = score on plain NodeUsage

    def __post_init__(self):
        object.__setattr__(self, "usage_thresholds", _freeze(self.usage_thresholds))
        # either half may be disabled: empty usage type = plain-usage
        # filtering (score-only profile), empty score type = plain-usage
        # scoring — but a configured filter (thresholds) needs a percentile
        if dict(self.usage_thresholds) and self.usage_aggregation_type not in PERCENTILES:
            raise ValueError(
                "aggregated usage_thresholds need a valid "
                f"usage_aggregation_type, got {self.usage_aggregation_type!r}"
            )
        for t in (self.usage_aggregation_type, self.score_aggregation_type):
            if t and t not in PERCENTILES:
                raise ValueError(f"unknown aggregation type {t!r}")


@dataclasses.dataclass(frozen=True)
class LoadAwareArgs:
    """reference config.LoadAwareSchedulingArgs (types.go:30)."""

    resource_weights: ResMap = _freeze(DEFAULT_RESOURCE_WEIGHTS)
    usage_thresholds: ResMap = _freeze(DEFAULT_USAGE_THRESHOLDS)
    estimated_scaling_factors: ResMap = _freeze(DEFAULT_ESTIMATED_SCALING_FACTORS)
    filter_expired_node_metrics: bool = True
    node_metric_expiration_seconds: int = 180
    # aggregated-percentile profile (load_aware.go:150-224 filter path,
    # :311 scoreWithAggregation); None = plain instantaneous usage
    aggregated: "AggregatedArgs | None" = None
    # prod-pod usage thresholds: PriorityProd pods filter against the sum
    # of prod pods' usage instead of whole-node usage (:226 filterProdUsage)
    prod_usage_thresholds: ResMap = ()
    # PriorityProd pods score against prod-pods usage (:291)
    score_according_prod_usage: bool = False

    def __post_init__(self):
        object.__setattr__(self, "resource_weights", _freeze(self.resource_weights))
        object.__setattr__(self, "usage_thresholds", _freeze(self.usage_thresholds))
        object.__setattr__(
            self, "estimated_scaling_factors", _freeze(self.estimated_scaling_factors)
        )
        object.__setattr__(
            self, "prod_usage_thresholds", _freeze(self.prod_usage_thresholds)
        )


# ---------------------------------------------------------------------------
# Fused scoring-term configs (ISSUE 15).  Each term is a cellwise
# (pod row, node row) contribution fused into the ONE score_cycle launch
# (solver/terms.py holds the registry + math; docs/KERNEL.md "Scoring
# terms" has the contract).  Term configs ride CycleConfig as STATIC jit
# arguments, so every field must be hashable and every mapping must go
# through ``_freeze`` — the koordlint retrace-hazard rule checks this
# statically (an unhashable term-config field would raise at the first
# jit call; a mutable one would silently key the cache on object id).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HeterogeneityTermArgs:
    """Gavel-style heterogeneity-aware scoring (PAPERS.md 2008.09213):
    a per-(workload class, accelerator type) throughput matrix rides the
    snapshot (``SyncRequest.terms.throughput``, [C, A] i64 normalized to
    [0, MAX_NODE_SCORE]); the term gathers
    ``throughput[workload_class[p], accel_type[n]]`` as a cellwise score
    so pods land where their job class runs fastest.  Device values are
    clamped to [0, MAX_NODE_SCORE] so the term's bound stays a CONFIG
    property (``weight * MAX_NODE_SCORE``) — the f32-exact serving
    top-k fast path depends on that (solver/topk.py)."""

    weight: int = 1


@dataclasses.dataclass(frozen=True)
class SensitivityTermArgs:
    """Synergy-style resource-sensitivity scoring (PAPERS.md
    2110.06073): per-pod CPU/mem sensitivity profiles
    (``PodTable.sensitivity``, [P, R] i64 in [0, 100]) replace
    GPU-proportional shares — a pod's score on a node drops with the
    node's occupancy on exactly the resources the pod is sensitive to:
    ``score = weight * (MAX_NODE_SCORE - sum_r(sens*occ)//sum_r(sens))``
    with occupancy in [0, 100] permille-free integer math.  Clamped to
    [0, weight * MAX_NODE_SCORE]."""

    weight: int = 1


@dataclasses.dataclass(frozen=True)
class PackingTermArgs:
    """Constraint-based bin packing (PAPERS.md 2511.08373): a
    MostAllocated-style objective over post-placement utilization
    (prefer filling nodes) plus an optional feasibility mask —
    ``headroom`` maps resource name -> max post-placement utilization
    PERCENT; a placement pushing a listed resource past its headroom is
    masked infeasible.  Both halves are cellwise in (pod, node): the
    mask reads only (requested[n] + req[p]) vs allocatable[n]."""

    weight: int = 1
    resource_weights: ResMap = _freeze({res.CPU: 1, res.MEMORY: 1})
    headroom: ResMap = ()  # resource -> max utilization percent; () = no mask

    def __post_init__(self):
        object.__setattr__(
            self, "resource_weights", _freeze(self.resource_weights)
        )
        object.__setattr__(self, "headroom", _freeze(self.headroom))

    def weights_arr(self) -> jnp.ndarray:
        return jnp.asarray(
            res.weights_vector(dict(self.resource_weights)), jnp.int64
        )

    def headroom_arr(self) -> jnp.ndarray:
        """Per-resource headroom percent; 0 = unconstrained dimension."""
        return jnp.asarray(
            res.weights_vector(dict(self.headroom)), jnp.int64
        )


@dataclasses.dataclass(frozen=True)
class CycleConfig:
    """One scheduling cycle's plugin set and weights.

    Plugin score weights mirror the k8s framework's per-plugin weight
    multiplier applied when summing plugin scores.

    ``wave``/``top_m`` select the wave-batched single-chip cycle
    (solver/wave.py, docs/KERNEL.md "Wave batching"): each sequential
    round scores ``wave`` pods at once, freezes their top-``top_m``
    candidate keys, and commits the certified prefix — bit-identical
    placements with ~wave pods per round instead of one.  ``wave=1``
    (the default) keeps the per-pod scan/kernel paths.  Both ride the
    config as STATIC jit arguments; passing them traced at any jit
    boundary is a silent per-cycle retrace (the koordlint
    ``retrace-hazard`` rule rejects that shape statically).
    """

    loadaware: LoadAwareArgs = LoadAwareArgs()
    fit_scoring_strategy: str = LEAST_ALLOCATED
    fit_resource_weights: ResMap = _freeze({res.CPU: 1, res.MEMORY: 1})
    fit_plugin_weight: int = 1
    loadaware_plugin_weight: int = 1
    enable_loadaware: bool = True
    enable_fit_score: bool = True
    wave: int = 1
    top_m: int = 4
    # fused scoring terms (ISSUE 15; solver/terms.py registry): None =
    # term disabled.  Frozen hashable dataclasses — the configs are
    # static jit arguments, and the registry derives each term's score
    # upper bound from them (solver/topk.py score_upper_bound), so the
    # jit cache and the serving top-k path never key on data.
    heterogeneity: "HeterogeneityTermArgs | None" = None
    sensitivity: "SensitivityTermArgs | None" = None
    packing: "PackingTermArgs | None" = None
    # Sparse candidate-set scoring (ISSUE 16; solver/candidates.py).
    # ``candidate_width`` > 0 turns the sparse [P, C] serving path on:
    # each pod is scored only against its C-wide candidate list instead
    # of every node.  The width is a POWER OF TWO and rides the config
    # as a static jit argument — the candidate list is padded to C, so
    # C never crosses a jit boundary traced (the koordlint
    # retrace-hazard rule shape 6 rejects traced candidate counts).
    # 0 = dense engines only.  256 is the recommended serving width.
    candidate_width: int = 0
    # How many exact lazy merge-refreshes a candidate residency may
    # accumulate before the engine forces a full rebuild (refresh
    # reason "stale" on koord_scorer_candidate_refresh_total).  Bounds
    # merge-chain length so a long warm stream cannot degrade into an
    # unbounded sequence of incremental sorts.
    candidate_max_stale: int = 8

    def __post_init__(self):
        object.__setattr__(
            self, "fit_resource_weights", _freeze(self.fit_resource_weights)
        )
        cw = int(self.candidate_width)
        if cw < 0 or (cw & (cw - 1)) != 0:
            raise ValueError(
                "candidate_width must be 0 (sparse off) or a power of "
                f"two, got {self.candidate_width!r}"
            )
        if int(self.candidate_max_stale) < 1:
            raise ValueError(
                "candidate_max_stale must be >= 1, got "
                f"{self.candidate_max_stale!r}"
            )

    # Dense device-side encodings (constant-folded under jit)
    def loadaware_weights_arr(self) -> jnp.ndarray:
        return jnp.asarray(
            res.weights_vector(dict(self.loadaware.resource_weights)), jnp.int64
        )

    def loadaware_thresholds_arr(self) -> jnp.ndarray:
        """Filter thresholds: the aggregated profile's when configured
        (load_aware.go:157-162), else the plain usage thresholds."""
        agg = self.loadaware.aggregated
        if agg is not None and agg.usage_thresholds:
            src = agg.usage_thresholds
        else:
            src = self.loadaware.usage_thresholds
        return jnp.asarray(res.weights_vector(dict(src)), jnp.int64)

    def prod_thresholds_arr(self) -> jnp.ndarray:
        return jnp.asarray(
            res.weights_vector(dict(self.loadaware.prod_usage_thresholds)),
            jnp.int64,
        )

    def fit_weights_arr(self) -> jnp.ndarray:
        return jnp.asarray(
            res.weights_vector(dict(self.fit_resource_weights)), jnp.int64
        )


DEFAULT_CYCLE_CONFIG = CycleConfig()
