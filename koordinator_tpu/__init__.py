"""koordinator_tpu — a TPU-native batched scheduling framework.

A ground-up rebuild of the capabilities of koordinator (QoS-based co-location
scheduling; reference at /root/reference) around a JAX/XLA core: the
scheduler-framework Score phase (NodeResourcesFit, LoadAwareScheduling,
NodeNUMAResource) is computed as one dense ``pods x nodes`` cost tensor on
TPU, with Coscheduling gang constraints and ElasticQuota hierarchical caps
encoded as masks, and a batched assignment solver replacing the per-pod
sequential scheduling cycle.

Design notes
------------
* All scoring arithmetic is exact int64 integer math so that score output is
  bit-identical with the reference's Go scorers (which use int64 division,
  e.g. ``leastRequestedScore`` at
  reference ``pkg/scheduler/plugins/loadaware/load_aware.go:388``).
  This requires ``jax_enable_x64``; importing this package enables it.
* Shapes are static: snapshots are padded to shape buckets so that XLA
  compiles each bucket once (see ``koordinator_tpu.model.snapshot``).
* Multi-chip scale-out shards the pod axis (data-parallel analog) and the
  node axis (model-parallel analog) of the cost tensor over a
  ``jax.sharding.Mesh`` (see ``koordinator_tpu.parallel.mesh``).
"""

import os

import jax

# Exact int64 score parity with the reference's Go integer math requires x64.
# Elementwise i64 is emulated on TPU but the score tensors are small compared
# to HBM bandwidth, so this costs little; the f32 fast path in ops/ avoids it
# where parity is not required.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the cycle kernels take 10-20s to compile
# per shape bucket (16.5s measured for the dense TPU kernel, BENCH_r03), but
# a scheduler must be ready at informer-sync speed (reference analog:
# cmd/koord-scheduler/app/server.go:206-220).  With the cache a restarted
# sidecar reuses the compiled executable and the first cycle runs in well
# under a second.  Opt out with KOORD_XLA_CACHE=0 or point KOORD_XLA_CACHE
# at a different directory; daemons re-point it under their --state-dir via
# configure_compilation_cache (scheduler/server.py).


def configure_compilation_cache(path, min_compile_seconds: float = 1.0,
                                force: bool = False) -> None:
    """Point JAX's persistent compilation cache at ``path``.

    Must run before the first compile — the cache is initialized lazily on
    first use and later re-pointing does not move already-initialized
    state.  ``path=None`` or ``""`` disables the cache.  The
    ``KOORD_XLA_CACHE`` env var takes precedence over programmatic calls
    (an operator override must win over a daemon default) — except under
    ``force=True``, the seam for an EXPLICIT ``--xla-cache`` flag, which
    outranks the env default exactly because the operator typed it.
    """
    env = os.environ.get("KOORD_XLA_CACHE", "")
    if env and not force:
        return  # import-time wiring below already honored the override
    if not path:
        jax.config.update("jax_compilation_cache_dir", None)
        return
    jax.config.update("jax_compilation_cache_dir", path)
    # cache every compile that costs more than min_compile_seconds; keep
    # tiny jits out (caching them would churn small files for no win)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_seconds)
    )


_cache = os.environ.get("KOORD_XLA_CACHE", "")
if _cache != "0":
    jax.config.update(
        "jax_compilation_cache_dir",
        _cache or os.path.expanduser("~/.cache/koordinator_tpu/xla"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

__version__ = "0.1.0"
