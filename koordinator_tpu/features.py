"""Feature gates for every binary.

Reference: ``pkg/features`` — per-binary mutable feature gates with
alpha/beta defaults (``koordlet_features.go:146``, ``features.go:28-63``,
``scheduler_features.go``), parsed from ``--feature-gates`` style
``Name=true,Other=false`` strings, plus the NodeSLO-driven disable check
(``IsFeatureDisabled``).
"""

from __future__ import annotations

import threading
from typing import Dict, Mapping, Optional

ALPHA = "Alpha"
BETA = "Beta"
GA = "GA"


class FeatureGate:
    """Mutable feature gate (k8s component-base featuregate semantics)."""

    def __init__(self, defaults: Mapping[str, tuple]):
        # name -> (default_enabled, prerelease)
        self._specs: Dict[str, tuple] = dict(defaults)
        self._overrides: Dict[str, bool] = {}
        self._lock = threading.RLock()

    def enabled(self, feature: str) -> bool:
        with self._lock:
            if feature in self._overrides:
                return self._overrides[feature]
            spec = self._specs.get(feature)
            return bool(spec and spec[0])

    def set(self, feature: str, value: bool) -> None:
        with self._lock:
            if feature not in self._specs:
                raise KeyError(f"unknown feature gate {feature}")
            self._overrides[feature] = value

    def set_from_map(self, m: Mapping[str, bool]) -> None:
        for k, v in m.items():
            self.set(k, bool(v))

    def parse(self, spec: str) -> None:
        """'A=true,B=false' (the --feature-gates flag format).

        Unparseable values raise, matching component-base's strict boolean
        parsing — a typo must not silently flip a gate."""
        parsed = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, eq, val = part.partition("=")
            val = val.strip().lower()
            if not eq or val in ("true", "1"):
                # bare "Name" means enable, like upstream's map form
                parsed[name.strip()] = True
            elif val in ("false", "0"):
                parsed[name.strip()] = False
            else:
                raise ValueError(
                    f"invalid feature gate value {part!r}: want Name=true|false"
                )
        # apply only after the whole spec parsed AND validated: an error
        # must not leave a half-applied gate set
        unknown = [n for n in parsed if n not in self._specs]
        if unknown:
            raise ValueError(f"unknown feature gates: {', '.join(sorted(unknown))}")
        self.set_from_map(parsed)

    def known(self) -> Dict[str, bool]:
        with self._lock:
            return {k: self.enabled(k) for k in self._specs}


# koordlet gates (koordlet_features.go:146-164)
KOORDLET_FEATURES = {
    "AuditEvents": (False, ALPHA),
    "AuditEventsHTTPHandler": (False, ALPHA),
    "BECPUSuppress": (True, BETA),
    "BECPUManager": (False, ALPHA),
    "BECPUEvict": (False, ALPHA),
    "BEMemoryEvict": (False, ALPHA),
    "CPUBurst": (True, BETA),
    "SystemConfig": (False, ALPHA),
    "RdtResctrl": (True, BETA),
    "CgroupReconcile": (False, ALPHA),
    "NodeTopologyReport": (True, BETA),
    "Accelerators": (False, ALPHA),
    "CPICollector": (False, ALPHA),
    "Libpfm4": (False, ALPHA),
    "PSICollector": (False, ALPHA),
    "BlkIOReconcile": (False, ALPHA),
    "ColdPageCollector": (False, ALPHA),
}

# manager/webhook gates (features.go:28-63)
MANAGER_FEATURES = {
    "PodMutatingWebhook": (True, BETA),
    "PodValidatingWebhook": (True, BETA),
    "ElasticMutatingWebhook": (False, ALPHA),
    "ElasticValidatingWebhook": (False, ALPHA),
    "NodeValidatingWebhook": (False, ALPHA),
    "ConfigMapValidatingWebhook": (False, ALPHA),
    "ColocationProfileSkipMutatingResources": (False, ALPHA),
    "WebhookFramework": (True, BETA),
    "MultiQuotaTree": (False, ALPHA),
    "ElasticQuotaIgnorePodOverhead": (False, ALPHA),
    "ElasticQuotaGuaranteeUsage": (False, ALPHA),
    "DisableDefaultQuota": (False, ALPHA),
    "DisablePVCReservation": (False, ALPHA),
}

# scheduler gates (scheduler_features.go)
SCHEDULER_FEATURES = {
    "CompatibleCSIStorageCapacity": (False, ALPHA),
    "DisableCSIStorageCapacityInformer": (False, ALPHA),
    "CompatiblePodDisruptionBudget": (False, ALPHA),
    "DisablePodDisruptionBudgetInformer": (False, ALPHA),
    "ResizePod": (False, ALPHA),
}

default_koordlet_gate = FeatureGate(KOORDLET_FEATURES)
default_manager_gate = FeatureGate(MANAGER_FEATURES)
default_scheduler_gate = FeatureGate(SCHEDULER_FEATURES)

# qos strategy <-> NodeSLO spec field (IsFeatureDisabled,
# koordlet_features.go:168)
_FEATURE_SLO_FIELD = {
    "BECPUSuppress": "resourceUsedThresholdWithBE",
    "BECPUEvict": "resourceUsedThresholdWithBE",
    "BEMemoryEvict": "resourceUsedThresholdWithBE",
}


def is_feature_disabled(node_slo: Optional[Mapping], feature: str) -> bool:
    """NodeSLO-level disable: the strategy's enable flag wins over the
    gate (koordlet_features.go IsFeatureDisabled)."""
    if not node_slo:
        return True
    field = _FEATURE_SLO_FIELD.get(feature)
    if field is None:
        return False
    cfg = node_slo.get(field) or {}
    return not bool(cfg.get("enable", False))
