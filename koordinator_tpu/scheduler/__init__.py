"""Host-side scheduler layer.

The batched TPU kernels (``koordinator_tpu.ops``) replace the reference's
per-(pod, node) Filter/Score loops; everything that is inherently
sequential, stateful control flow — cpuset accumulation at Reserve,
topology-hint merging, the plugin pipeline itself — stays on the host in
this package (reference ``pkg/scheduler/plugins/*`` and
``pkg/scheduler/frameworkext``).
"""

from koordinator_tpu.scheduler.cpu_accumulator import (  # noqa: F401
    CPUAllocation,
    CPUBindPolicy,
    CPUExclusivePolicy,
    NUMAAllocateStrategy,
    take_cpus,
    take_preferred_cpus,
)
from koordinator_tpu.scheduler.topologymanager import (  # noqa: F401
    NUMATopologyHint,
    NUMATopologyPolicy,
    merge_hints,
)
