"""Built-in extended plugins: NUMA, Reservation, DeviceShare adapters.

Each adapter exposes a subsystem's batched kernels through the
``TensorPlugin`` boundary (reference plugin registrations at
``cmd/koord-scheduler/main.go:45-53``) and settles exact per-pod allocation
host-side at Reserve, mirroring the reference's Reserve-phase caches
(``nodenumaresource/plugin.go Reserve``, ``deviceshare/plugin.go Reserve``).

Context extras consumed:
* ``zones``: ZoneBatch, ``numa_policy``: i32[N] — NodeNUMAResourcePlugin
* ``reservations``: ReservationTable — ReservationPlugin
* ``devices``: DeviceBatch — DeviceSharePlugin
* ``cpu_topologies``: {node_idx: CPUTopology}, ``available_cpus``:
  {node_idx: set[int]} — cpuset accumulation at Reserve
* ``device_minors``: {node_idx: [minor dicts]} — minor selection at Reserve
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model import resources as res
from koordinator_tpu.model.device import (
    DEVICE_GPU,
    DEVICE_RESOURCE_AXIS,
    DEVICE_RESOURCE_INDEX,
    DEVICE_TYPE_CODE_TO_NAME,
    DEVICE_TYPE_NAMES,
    DEVICE_TYPE_RESOURCES,
)
from koordinator_tpu.ops.deviceshare import (
    allocate_joint,
    device_fit_mask,
    deviceshare_scores,
    gpu_card_total_memory,
    minor_dicts_from_batch,
    normalize_gpu_requests,
    partition_fit_mask,
    pod_device_requests,
    split_per_card,
)
from koordinator_tpu.ops.numa import numa_admit_mask, numa_zone_scores
from koordinator_tpu.ops.reservation import (
    nominate_reservations,
    reservation_affinity_mask,
)
from koordinator_tpu.scheduler.cpu_accumulator import (
    CPUBindPolicy,
    NUMAAllocateStrategy,
    take_cpus,
)
from koordinator_tpu.scheduler.framework import CycleContext, TensorPlugin

_CPU_IDX = res.RESOURCE_INDEX[res.CPU]


class NodeNUMAResourcePlugin(TensorPlugin):
    """Zone admission + zone scoring; cpuset accumulation at Reserve.

    reference pkg/scheduler/plugins/nodenumaresource (PreFilter/Filter/
    Score plugin.go:210,266, scoring.go:55; Reserve allocates the cpuset).
    """

    name = "NodeNUMAResource"

    def __init__(
        self,
        *,
        most_allocated: bool = False,
        bind_policy: CPUBindPolicy = CPUBindPolicy.FULL_PCPUS,
        strategy: NUMAAllocateStrategy = NUMAAllocateStrategy.LEAST_ALLOCATED,
    ):
        self.most_allocated = most_allocated
        self.bind_policy = bind_policy
        self.strategy = strategy

    def filter_mask(self, ctx: CycleContext) -> Optional[jnp.ndarray]:
        zones = ctx.extras.get("zones")
        policy = ctx.extras.get("numa_policy")
        if zones is None or policy is None:
            return None
        pods = ctx.snapshot.pods
        return numa_admit_mask(
            pods.requests, zones.allocatable, zones.requested, zones.valid, policy
        )

    def score(self, ctx: CycleContext) -> Optional[jnp.ndarray]:
        zones = ctx.extras.get("zones")
        if zones is None:
            return None
        pods = ctx.snapshot.pods
        weights = ctx.cfg.fit_weights_arr()
        return numa_zone_scores(
            pods.requests,
            zones.allocatable,
            zones.requested,
            zones.valid,
            weights,
            most_allocated=self.most_allocated,
        )

    def reserve(self, ctx: CycleContext, pod_idx: int, node_idx: int) -> None:
        """LSE/LSR pods get an exact cpuset on the chosen node (the
        reference runs this same accumulator; plugin.go Reserve)."""
        topo = (ctx.extras.get("cpu_topologies") or {}).get(node_idx)
        if topo is None:
            return
        qos = int(np.asarray(ctx.snapshot.pods.qos[pod_idx]))
        if qos > 1:  # only LSE(0)/LSR(1) bind cpus
            return
        milli = int(np.asarray(ctx.snapshot.pods.requests[pod_idx, _CPU_IDX]))
        num_cpus = milli // 1000
        if num_cpus <= 0:
            return
        available = ctx.extras.setdefault("available_cpus", {}).setdefault(
            node_idx, set(topo.details)
        )
        cpus = take_cpus(
            topo,
            available,
            num_cpus,
            bind_policy=self.bind_policy,
            strategy=self.strategy,
        )
        available -= set(cpus)
        ctx.state.setdefault("cpuset_allocations", {})[pod_idx] = sorted(cpus)

    def unreserve(self, ctx: CycleContext, pod_idx: int, node_idx: int) -> None:
        cpus = ctx.state.get("cpuset_allocations", {}).pop(pod_idx, None)
        if cpus:
            avail = ctx.extras.get("available_cpus", {}).get(node_idx)
            if avail is not None:
                avail |= set(cpus)

    def pre_bind(self, ctx, pod_idx, node_idx) -> Optional[Mapping]:
        cpus = ctx.state.get("cpuset_allocations", {}).get(pod_idx)
        if not cpus:
            return None
        # reference apis/extension ResourceStatus annotation
        return {
            "annotations": {
                "scheduling.koordinator.sh/resource-status": {
                    "cpuset": ",".join(map(str, cpus))
                }
            }
        }


class ReservationPlugin(TensorPlugin):
    """Reservation nomination + scoring (reference
    pkg/scheduler/plugins/reservation scoring.go; restore runs as a
    BeforePreFilter transformer upstream of this plugin)."""

    name = "Reservation"

    def filter_mask(self, ctx: CycleContext) -> Optional[jnp.ndarray]:
        """Required reservation affinity: a pod carrying the
        reservation-affinity annotation is admitted only onto nodes with
        a matched reservation (reference plugin.go:238)."""
        rsv = ctx.extras.get("reservations")
        if rsv is None:
            return None
        return reservation_affinity_mask(rsv, ctx.snapshot.nodes.capacity)

    def score(self, ctx: CycleContext) -> Optional[jnp.ndarray]:
        rsv = ctx.extras.get("reservations")
        if rsv is None:
            return None
        pods = ctx.snapshot.pods
        num_nodes = ctx.snapshot.nodes.capacity
        node_scores, nominated = nominate_reservations(pods.requests, rsv, num_nodes)
        ctx.state["nominated_reservations"] = nominated
        return node_scores

    def pre_bind(self, ctx, pod_idx, node_idx) -> Optional[Mapping]:
        nominated = ctx.state.get("nominated_reservations")
        if nominated is None:
            return None
        v = int(np.asarray(nominated[pod_idx, node_idx]))
        if v < 0:
            return None
        rsv = ctx.extras["reservations"]
        name = rsv.names[v] if v < len(rsv.names) else str(v)
        # reference SetReservationAllocated writes {"name", "uid"}
        # (apis/extension/reservation.go:86-97); uid omitted when the CR
        # uid is unknown to the table
        allocated = {"name": name}
        uid = rsv.uids[v] if v < len(rsv.uids) else ""
        if uid:
            allocated["uid"] = uid
        return {
            "annotations": {
                "scheduling.koordinator.sh/reservation-allocated": allocated
            }
        }


class DeviceSharePlugin(TensorPlugin):
    """Device fit + scoring; minor selection at Reserve (reference
    pkg/scheduler/plugins/deviceshare plugin.go:146,284,450)."""

    name = "DeviceShare"

    def __init__(self, *, most_allocated: bool = False):
        self.most_allocated = most_allocated

    def filter_mask(self, ctx: CycleContext) -> Optional[jnp.ndarray]:
        devices = ctx.extras.get("devices")
        if devices is None:
            return None
        mask = device_fit_mask(ctx.snapshot.pods.requests, devices)
        partitions = ctx.extras.get("device_partitions")
        if partitions:
            # partition tables constrain which minor GROUPS co-allocate:
            # the count-based tensor fit overcounts minors no single
            # group contains, so refine with the host-side group check
            # (normalization computed once here, not re-derived inside)
            dev_req = pod_device_requests(ctx.snapshot.pods.requests)
            norm = normalize_gpu_requests(
                dev_req, gpu_card_total_memory(devices)
            )
            per_card_t, wanted_t = split_per_card(norm)
            mask = mask & jnp.asarray(
                partition_fit_mask(
                    ctx.snapshot.pods.requests,
                    devices,
                    partitions,
                    per_card=np.asarray(per_card_t),
                    wanted=np.asarray(wanted_t),
                )
            )
        return mask

    def score(self, ctx: CycleContext) -> Optional[jnp.ndarray]:
        devices = ctx.extras.get("devices")
        if devices is None:
            return None
        return deviceshare_scores(
            ctx.snapshot.pods.requests, devices, most_allocated=self.most_allocated
        )

    def reserve(self, ctx: CycleContext, pod_idx: int, node_idx: int) -> None:
        devices = ctx.extras.get("devices")
        if devices is None:
            return
        minors = (ctx.extras.get("device_minors") or {}).get(node_idx)
        if minors is None:
            # derive the host-side minor view from the tensor extras.
            # Minors carry the CR device id from devices.minor (the dense
            # slot index only as fallback when devices.minor is absent);
            # device_partitions / preferred / required sets must be
            # authored in that minor-id space, never in slot space.
            minors = minor_dicts_from_batch(devices, node_idx)
            ctx.extras.setdefault("device_minors", {})[node_idx] = minors
        dev_req = pod_device_requests(ctx.snapshot.pods.requests[pod_idx : pod_idx + 1])
        if not bool(np.asarray(dev_req).any()):
            return
        card_mem = gpu_card_total_memory(devices)
        norm = normalize_gpu_requests(dev_req, card_mem)
        per_card_t, wanted_t = split_per_card(norm)
        # split_per_card divides the GPU dims by wanted; non-GPU dims keep
        # their full quantity, so per_card_vec is per-minor for EVERY type
        per_card_vec = np.asarray(per_card_t)[0, node_idx]
        wanted = int(np.asarray(wanted_t)[0, node_idx])

        # split the request per device type and allocate JOINTLY
        # (tryAllocateDevice loops the requested types; NUMA affinity
        # aligns later types with the first's minors)
        per_card_by_type = {}
        wanted_by_type = {}
        for code, type_resources in DEVICE_TYPE_RESOURCES.items():
            pc = {
                name: int(per_card_vec[DEVICE_RESOURCE_INDEX[name]])
                for name in type_resources
                if per_card_vec[DEVICE_RESOURCE_INDEX[name]] > 0
            }
            if pc:
                per_card_by_type[code] = pc
                # multi-card spanning applies to GPU ratio requests; other
                # types allocate one minor carrying the full quantity
                wanted_by_type[code] = wanted if code == DEVICE_GPU else 1
        partitions = (ctx.extras.get("device_partitions") or {}).get(node_idx)
        chosen_by_type = allocate_joint(
            minors,
            per_card_by_type,
            wanted_by_type,
            partitions=partitions,
            most_allocated=self.most_allocated,
        )

        def code_of(m):
            return DEVICE_TYPE_NAMES.get(str(m.get("type", "gpu")).lower(), 0)

        for m in minors:
            if m["minor"] in chosen_by_type.get(code_of(m), ()):
                per_card = per_card_by_type.get(code_of(m), {})
                free = m.setdefault("free", dict(m.get("total", {})))
                for dim, q in per_card.items():
                    left = int(res.parse_quantity(free.get(dim, 0), dim)) - q
                    # write back a form parse_quantity round-trips exactly
                    free[dim] = res.format_quantity(left, dim)
        # the reference's DeviceAllocations annotation payload
        # (apis/extension/device_share.go:56-66: type name -> entries of
        # {"minor", "resources"}), written at PreBind and consumed by the
        # koordlet gpu hook (runtimehooks/hooks/gpu) — exact keys so a
        # reference koordlet could read a rebuild scheduler's allocations
        # and vice versa
        allocations = {}
        for code, chosen in chosen_by_type.items():
            per_card = per_card_by_type.get(code, {})
            allocations[DEVICE_TYPE_CODE_TO_NAME[code]] = [
                {
                    "minor": int(m),
                    "resources": {
                        dim: res.format_quantity(int(q), dim)
                        for dim, q in per_card.items()
                    },
                }
                for m in sorted(chosen)
            ]
        ctx.state.setdefault("device_allocations", {})[pod_idx] = allocations

    def pre_bind(self, ctx, pod_idx, node_idx) -> Optional[Mapping]:
        alloc = ctx.state.get("device_allocations", {}).get(pod_idx)
        if not alloc:
            return None
        return {
            "annotations": {
                "scheduling.koordinator.sh/device-allocated": alloc
            }
        }
