"""CPUSet accumulator: pick logical CPUs for an LSE/LSR pod on its chosen node.

Behavior parity with the reference's accumulator (reference
``pkg/scheduler/plugins/nodenumaresource/cpu_accumulator.go``): the same
decision ladder (full free cores in one NUMA node -> one socket ->
most-free-socket spill -> per-core chunks; spread-by-pcpus variants; final
one-at-a-time fill), the same sort keys (NUMA allocate strategy
most/least-allocated, socket-affinity-with-result, ref counts, stable id
tiebreaks), and the same exclusive-policy filters.

This runs host-side once per pod on the *selected* node (Reserve phase).
The reference instead runs a full Allocate per (pod, node) inside Score
(``scoring.go:86``) — the TPU rebuild moves that cost into the batched zone
kernel (``koordinator_tpu.ops.numa``) and keeps this exact algorithm only
for the final placement, which is what makes the cycle O(1) device programs
instead of O(nodes) host allocations.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from koordinator_tpu.model.topology import CPUTopology


class CPUBindPolicy(str, enum.Enum):
    """reference apis/extension/numa_aware.go CPUBindPolicy."""

    DEFAULT = "Default"
    FULL_PCPUS = "FullPCPUs"
    SPREAD_BY_PCPUS = "SpreadByPCPUs"
    CONSTRAINED_BURST = "ConstrainedBurst"


class CPUExclusivePolicy(str, enum.Enum):
    """reference apis/extension/numa_aware.go CPUExclusivePolicy."""

    NONE = "None"
    PCPU_LEVEL = "PCPULevel"
    NUMA_NODE_LEVEL = "NUMANodeLevel"


class NUMAAllocateStrategy(str, enum.Enum):
    """reference apis/extension/numa_aware.go NUMAAllocateStrategy."""

    MOST_ALLOCATED = "MostAllocated"
    LEAST_ALLOCATED = "LeastAllocated"


@dataclasses.dataclass
class CPUAllocation:
    """Per-CPU allocation bookkeeping on one node (reference
    ``cpu_accumulator.go CPUDetails`` ref counts + exclusive marks)."""

    ref_count: Dict[int, int] = dataclasses.field(default_factory=dict)
    exclusive_policy: Dict[int, CPUExclusivePolicy] = dataclasses.field(
        default_factory=dict
    )

    def exclusive_cores(self, topology: CPUTopology) -> Set[int]:
        return {
            topology.details[c].core
            for c, p in self.exclusive_policy.items()
            if p == CPUExclusivePolicy.PCPU_LEVEL
        }

    def exclusive_numa_nodes(self, topology: CPUTopology) -> Set[int]:
        return {
            topology.details[c].node
            for c, p in self.exclusive_policy.items()
            if p == CPUExclusivePolicy.NUMA_NODE_LEVEL
        }


class _Accumulator:
    """Mutable take state (reference cpu_accumulator.go:238 cpuAccumulator)."""

    def __init__(
        self,
        topology: CPUTopology,
        max_ref_count: int,
        available: Iterable[int],
        allocated: CPUAllocation,
        num_needed: int,
        exclusive_policy: CPUExclusivePolicy,
        strategy: NUMAAllocateStrategy,
    ):
        self.topology = topology
        self.max_ref_count = max_ref_count
        self.allocatable: Dict[int, int] = {}  # cpu -> ref count
        for cpu in available:
            if cpu in topology.details:
                self.allocatable[cpu] = (
                    allocated.ref_count.get(cpu, 0) if max_ref_count > 1 else 0
                )
        self.exclusive_in_cores = allocated.exclusive_cores(topology)
        self.exclusive_in_nodes = allocated.exclusive_numa_nodes(topology)
        self.exclusive_policy = exclusive_policy
        self.exclusive = exclusive_policy in (
            CPUExclusivePolicy.PCPU_LEVEL,
            CPUExclusivePolicy.NUMA_NODE_LEVEL,
        )
        self.strategy = strategy
        self.num_needed = num_needed
        self.result: List[int] = []

    # -- state predicates (cpu_accumulator.go:306-316) --

    def needs(self, n: int) -> bool:
        return self.num_needed >= n

    def satisfied(self) -> bool:
        return self.num_needed < 1

    def failed(self) -> bool:
        return self.num_needed > len(self.allocatable)

    def take(self, cpus: Sequence[int]) -> None:
        for cpu in cpus:
            self.result.append(cpu)
            self.allocatable.pop(cpu, None)
            if self.exclusive:
                info = self.topology.details[cpu]
                if self.exclusive_policy == CPUExclusivePolicy.PCPU_LEVEL:
                    self.exclusive_in_cores.add(info.core)
                elif self.exclusive_policy == CPUExclusivePolicy.NUMA_NODE_LEVEL:
                    self.exclusive_in_nodes.add(info.node)
        self.num_needed -= len(cpus)

    # -- exclusive filters (cpu_accumulator.go:318-330) --

    def _excl_pcpu(self, cpu: int) -> bool:
        return (
            self.exclusive_policy == CPUExclusivePolicy.PCPU_LEVEL
            and self.topology.details[cpu].core in self.exclusive_in_cores
        )

    def _excl_numa(self, cpu: int) -> bool:
        return (
            self.exclusive_policy == CPUExclusivePolicy.NUMA_NODE_LEVEL
            and self.topology.details[cpu].node in self.exclusive_in_nodes
        )

    # -- sort helpers --

    def _strategy_key(self, free_score: int) -> int:
        """MostAllocated prefers fewer free, LeastAllocated more free
        (cpu_accumulator.go:433-439 and peers)."""
        if self.strategy == NUMAAllocateStrategy.MOST_ALLOCATED:
            return free_score
        return -free_score

    def _core_ref_count(self, core: int) -> int:
        return sum(
            rc
            for cpu, rc in self.allocatable.items()
            if self.topology.details[cpu].core == core
        )

    def _sorted_core_cpus(self, cpus: List[int]) -> List[int]:
        cpus = sorted(cpus)
        if self.max_ref_count > 1:
            cpus.sort(key=lambda c: (self.allocatable.get(c, 0), c))
        return cpus

    def _sort_cores(
        self, cores: List[int], cpus_in_cores: Dict[int, List[int]]
    ) -> List[int]:
        """Fuller-free cores first, then ref count, then id
        (cpu_accumulator.go:345 sortCores)."""

        def key(core: int):
            k = [-len(cpus_in_cores[core])]
            if self.max_ref_count > 1:
                k.append(self._core_ref_count(core))
            k.append(core)
            return tuple(k)

        return sorted(cores, key=key)

    def _group(
        self, filter_exclusive_numa: bool = False, filter_exclusive_both: bool = False
    ):
        """Group allocatable cpus by core, with free-score tallies."""
        cpus_in_cores: Dict[int, List[int]] = {}
        node_free: Dict[int, int] = {}
        socket_free: Dict[int, int] = {}
        for cpu in self.allocatable:
            if filter_exclusive_numa and self._excl_numa(cpu):
                continue
            if filter_exclusive_both and (self._excl_pcpu(cpu) or self._excl_numa(cpu)):
                continue
            info = self.topology.details[cpu]
            cpus_in_cores.setdefault(info.core, []).append(cpu)
            node_free[info.node] = node_free.get(info.node, 0) + 1
            socket_free[info.socket] = socket_free.get(info.socket, 0) + 1
        return cpus_in_cores, node_free, socket_free

    # -- candidate listings (cpu_accumulator.go:371,464,530,608,666) --

    def free_cores_in_node(
        self, full_free_only: bool, filter_exclusive: bool
    ) -> List[List[int]]:
        cpus_in_cores, _, socket_free = self._group(
            filter_exclusive_numa=filter_exclusive
        )
        per_core = self.topology.cpus_per_core()
        cores_in_nodes: Dict[int, List[int]] = {}
        for core, cpus in cpus_in_cores.items():
            if full_free_only and len(cpus) != per_core:
                continue
            node = self.topology.details[cpus[0]].node
            cores_in_nodes.setdefault(node, []).append(core)

        cpus_in_nodes: Dict[int, List[int]] = {}
        for node, cores in cores_in_nodes.items():
            ordered = self._sort_cores(cores, cpus_in_cores)
            cpus_in_nodes[node] = [
                c for core in ordered for c in sorted(cpus_in_cores[core])
            ]

        def node_key(node: int):
            some_cpu = cpus_in_nodes[node][0]
            socket = self.topology.details[some_cpu].socket
            return (
                self._strategy_key(len(cpus_in_nodes[node])),
                self._strategy_key(socket_free.get(socket, 0)),
                node,
            )

        return [cpus_in_nodes[n] for n in sorted(cpus_in_nodes, key=node_key)]

    def free_cores_in_socket(self, full_free_only: bool) -> List[List[int]]:
        cpus_in_cores, _, _ = self._group()
        per_core = self.topology.cpus_per_core()
        cores_in_sockets: Dict[int, List[int]] = {}
        for core, cpus in cpus_in_cores.items():
            if full_free_only and len(cpus) != per_core:
                continue
            socket = self.topology.details[cpus[0]].socket
            cores_in_sockets.setdefault(socket, []).append(core)

        cpus_in_sockets: Dict[int, List[int]] = {}
        for socket, cores in cores_in_sockets.items():
            ordered = self._sort_cores(cores, cpus_in_cores)
            cpus_in_sockets[socket] = [
                c for core in ordered for c in sorted(cpus_in_cores[core])
            ]

        def socket_key(socket: int):
            return (self._strategy_key(len(cpus_in_sockets[socket])), socket)

        return [cpus_in_sockets[s] for s in sorted(cpus_in_sockets, key=socket_key)]

    def _extract_one_per_core(self, cpus: List[int]) -> List[int]:
        seen: Set[int] = set()
        out = []
        for c in cpus:
            core = self.topology.details[c].core
            if core not in seen:
                seen.add(core)
                out.append(c)
        return out

    def free_cpus_in_node(self, filter_exclusive: bool) -> List[List[int]]:
        cpus_in_nodes: Dict[int, List[int]] = {}
        node_free: Dict[int, int] = {}
        socket_free: Dict[int, int] = {}
        for cpu in self.allocatable:
            if filter_exclusive and (self._excl_pcpu(cpu) or self._excl_numa(cpu)):
                continue
            info = self.topology.details[cpu]
            cpus_in_nodes.setdefault(info.node, []).append(cpu)
            node_free[info.node] = node_free.get(info.node, 0) + 1
            socket_free[info.socket] = socket_free.get(info.socket, 0) + 1

        for node, cpus in cpus_in_nodes.items():
            cpus = self._sorted_core_cpus(cpus)
            if filter_exclusive:
                cpus = self._extract_one_per_core(cpus)
            cpus_in_nodes[node] = cpus

        def node_key(node: int):
            socket = self.topology.details[cpus_in_nodes[node][0]].socket
            return (
                self._strategy_key(node_free.get(node, 0)),
                self._strategy_key(socket_free.get(socket, 0)),
                node,
            )

        return [cpus_in_nodes[n] for n in sorted(cpus_in_nodes, key=node_key)]

    def free_cpus_in_socket(self, filter_exclusive: bool) -> List[List[int]]:
        cpus_in_sockets: Dict[int, List[int]] = {}
        for cpu in self.allocatable:
            if filter_exclusive and self._excl_pcpu(cpu):
                continue
            info = self.topology.details[cpu]
            cpus_in_sockets.setdefault(info.socket, []).append(cpu)

        for socket, cpus in cpus_in_sockets.items():
            cpus = self._sorted_core_cpus(cpus)
            if filter_exclusive:
                cpus = self._extract_one_per_core(cpus)
            cpus_in_sockets[socket] = cpus

        def socket_key(socket: int):
            return (self._strategy_key(len(cpus_in_sockets[socket])), socket)

        return [cpus_in_sockets[s] for s in sorted(cpus_in_sockets, key=socket_key)]

    def free_cpus(self, filter_exclusive: bool) -> List[int]:
        """Global ordering (cpu_accumulator.go:666 freeCPUs): socket affinity
        with already-taken cpus, then strategy free scores, then fuller
        cores last, stable ids."""
        cpus_in_cores, node_free, socket_free = self._group(
            filter_exclusive_both=filter_exclusive
        )
        result_sockets: Dict[int, int] = {}
        for cpu in self.result:
            s = self.topology.details[cpu].socket
            result_sockets[s] = result_sockets.get(s, 0) + 1

        def core_key(core: int):
            some_cpu = cpus_in_cores[core][0]
            info = self.topology.details[some_cpu]
            k = [
                -result_sockets.get(info.socket, 0),
                self._strategy_key(socket_free.get(info.socket, 0)),
                self._strategy_key(node_free.get(info.node, 0)),
                len(cpus_in_cores[core]),
                info.socket,
            ]
            if self.max_ref_count > 1:
                k.append(self._core_ref_count(core))
            k.append(core)
            return tuple(k)

        out: List[int] = []
        for core in sorted(cpus_in_cores, key=core_key):
            out.extend(self._sorted_core_cpus(cpus_in_cores[core]))
        return out

    def spread(self, cpus: List[int]) -> List[int]:
        """Round-robin one cpu per core per pass (cpu_accumulator.go:798)."""
        if len(cpus) <= self.topology.cpus_per_core():
            return cpus
        out: List[int] = []
        pending = list(cpus)
        while pending:
            seen: Set[int] = set()
            reserved: List[int] = []
            for c in pending:
                core = self.topology.details[c].core
                if core in seen:
                    reserved.append(c)
                else:
                    seen.add(core)
                    out.append(c)
            pending = reserved
        return out


class CPUAllocationError(Exception):
    pass


def take_cpus(
    topology: CPUTopology,
    available: Iterable[int],
    num_needed: int,
    *,
    allocated: Optional[CPUAllocation] = None,
    max_ref_count: int = 1,
    bind_policy: CPUBindPolicy = CPUBindPolicy.FULL_PCPUS,
    exclusive_policy: CPUExclusivePolicy = CPUExclusivePolicy.NONE,
    strategy: NUMAAllocateStrategy = NUMAAllocateStrategy.LEAST_ALLOCATED,
) -> List[int]:
    """Pick ``num_needed`` logical CPUs (reference cpu_accumulator.go:88 takeCPUs)."""
    acc = _Accumulator(
        topology,
        max_ref_count,
        available,
        allocated or CPUAllocation(),
        num_needed,
        exclusive_policy,
        strategy,
    )
    if acc.satisfied():
        return acc.result
    if acc.failed():
        raise CPUAllocationError("not enough cpus available to satisfy request")

    full_pcpus = bind_policy == CPUBindPolicy.FULL_PCPUS
    if full_pcpus or topology.cpus_per_core() == 1:
        # whole free cores inside one NUMA node (go:107-121)
        if acc.num_needed <= topology.cpus_per_node():
            for filter_exclusive in (True, False):
                for cpus in acc.free_cores_in_node(True, filter_exclusive):
                    if len(cpus) >= acc.num_needed:
                        acc.take(cpus[: acc.num_needed])
                        return acc.result
        # whole free cores inside one socket (go:126-134)
        if acc.num_needed <= topology.cpus_per_socket():
            for cpus in acc.free_cores_in_socket(True):
                if len(cpus) >= acc.num_needed:
                    acc.take(cpus[: acc.num_needed])
                    return acc.result
        # spill: most-free sockets whole, leftovers from least-free in
        # per-core chunks (go:141-177)
        free = acc.free_cores_in_socket(True)
        free.sort(key=len, reverse=True)
        unsatisfied = []
        for cpus in free:
            if not acc.needs(len(cpus)):
                unsatisfied.append(cpus)
            else:
                acc.take(cpus)
                if acc.satisfied():
                    return acc.result
        if acc.needs(topology.cpus_per_core()):
            unsatisfied.sort(key=len)
            per_core = topology.cpus_per_core()
            for cpus in unsatisfied:
                for i in range(0, len(cpus), per_core):
                    acc.take(cpus[i : i + per_core])
                    if acc.satisfied():
                        return acc.result
                    if not acc.needs(per_core):
                        break

    if not full_pcpus:
        # spread inside one NUMA node, then one socket (go:185-216)
        if acc.num_needed <= topology.cpus_per_node():
            for filter_exclusive in (True, False):
                for cpus in acc.free_cpus_in_node(filter_exclusive):
                    if len(cpus) >= acc.num_needed:
                        acc.take(acc.spread(cpus)[: acc.num_needed])
                        return acc.result
        if acc.num_needed <= topology.cpus_per_socket():
            for filter_exclusive in (True, False):
                for cpus in acc.free_cpus_in_socket(filter_exclusive):
                    if len(cpus) >= acc.num_needed:
                        acc.take(acc.spread(cpus)[: acc.num_needed])
                        return acc.result

    # final one-at-a-time fill near already-taken cpus (go:220-232)
    for filter_exclusive in (True, False):
        for c in acc.spread(acc.free_cpus(filter_exclusive)):
            if acc.needs(1):
                acc.take([c])
            if acc.satisfied():
                return acc.result

    raise CPUAllocationError("failed to allocate cpus")


def take_preferred_cpus(
    topology: CPUTopology,
    available: Iterable[int],
    preferred: Iterable[int],
    num_needed: int,
    **kwargs,
) -> List[int]:
    """Prefer reusable (e.g. reservation-owned) cpus first
    (reference cpu_accumulator.go:30 takePreferredCPUs)."""
    available = set(available)
    preferred = available & set(preferred)
    result: List[int] = []
    if preferred:
        needed = min(num_needed, len(preferred))
        result = take_cpus(topology, preferred, needed, **kwargs)
        num_needed -= len(result)
        available -= preferred
    if num_needed > 0:
        result = result + take_cpus(topology, available, num_needed, **kwargs)
    return result
