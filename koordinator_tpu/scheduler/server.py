"""koord-scheduler app/server: CLI, leader election, serving, the seam.

Mirrors ``cmd/koord-scheduler/app/server.go``:

* ``NewSchedulerCommand`` (:79) -> ``build_arg_parser``/``main``: flags
  for the component config, lease path/identity, sockets and ports.
* ``Setup`` (:331) -> ``SchedulerServer``: loads the component config
  (scheduler/config_api.py), builds the scorer servicer (the device-side
  scheduling seam) and the REST service API.
* ``Run`` (:155) -> ``start``/``run_forever``: healthz + /metrics +
  services API over HTTP, the bridge scorer on UDS (gRPC + raw framing
  for native clients), all gated by **leader election** (:225): only the
  leader serves Assign — followers answer Score/healthz but refuse to
  place pods, exactly the split the reference gets by only running the
  scheduling loop on the elected leader.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from koordinator_tpu.bridge.server import ScorerServicer, make_server
from koordinator_tpu.httpserving import (
    HTTPLifecycle,
    format_thread_stacks,
    reply_text,
)
from koordinator_tpu.bridge.udsserver import RawUdsServer
from koordinator_tpu.config import DEFAULT_CYCLE_CONFIG
from koordinator_tpu.leaderelection import LeaderElector
from koordinator_tpu.obs.lockwitness import witness_lock
from koordinator_tpu.scheduler.config_api import load_config
from koordinator_tpu.scheduler.services import APIService
from koordinator_tpu.solver import pallas_demotions


def default_state_dir() -> str:
    """Per-user daemon state dir (XDG state home).  NOT a fixed /tmp
    path: the state dir holds the persistent XLA compile cache, whose
    entries are deserialized executables — a world-writable shared
    location would let another local user pre-plant cache entries the
    scheduler then loads."""
    base = os.environ.get("XDG_STATE_HOME") or os.path.join(
        os.path.expanduser("~"), ".local", "state"
    )
    return os.path.join(base, "koord-scheduler")


class _LeaderGatedServicer(ScorerServicer):
    """Assign requires leadership; Score/Sync serve on any replica (they
    are read-only against the resident snapshot)."""

    def __init__(self, cfg, is_leader, **kwargs):
        super().__init__(cfg, **kwargs)
        self._is_leader = is_leader

    def assign(self, req, ctx=None):
        if not self._is_leader():
            raise PermissionError(
                "not the leader: this replica does not place pods"
            )
        return super().assign(req, ctx)


class SchedulerServer:
    def __init__(
        self,
        *,
        config_path: Optional[str] = None,
        lease_path: str = "/tmp/koord-scheduler/leader.lease",
        identity: Optional[str] = None,
        uds_path: str = "/tmp/koord-scheduler/scorer.sock",
        http_host: str = "127.0.0.1",
        http_port: int = 0,
        enable_grpc: bool = True,
        shard: bool = False,
        state_dir: Optional[str] = None,
        mesh_devices: Optional[str] = None,
        pipeline_depth: Optional[int] = None,
        coalesce_cap_ms: Optional[float] = None,
        max_inflight: Optional[int] = None,
        replicate_from: Optional[str] = None,
        relay_from: Optional[str] = None,
        tree_depth: Optional[int] = None,
        repl_batch_bytes: Optional[int] = None,
        repl_compress: bool = True,
        autoscale: bool = False,
        autoscale_min: Optional[int] = None,
        autoscale_max: Optional[int] = None,
        read_slo_p99_ms: Optional[float] = None,
        autoscale_interval_s: Optional[float] = None,
        score_incr_max_ratio: Optional[float] = None,
        candidate_width: Optional[int] = None,
        journal: bool = False,
        journal_compact_every: Optional[int] = None,
        journal_fsync: bool = False,
        breaker_threshold: Optional[int] = None,
        breaker_cooldown_ms: Optional[float] = None,
        brownout_max_lag: Optional[int] = None,
        trace_export: Optional[str] = None,
        shed_fractions: Optional[dict] = None,
        devprof_sample: Optional[int] = None,
        xla_cache: Optional[str] = None,
        prewarm: bool = False,
    ):
        # persistent compile cache under the daemon's state dir: a
        # restarted sidecar skips the multi-second (16.5s on TPU,
        # BENCH_r03) cycle-kernel compile and is serving warm cycles at
        # informer-sync speed.  Must happen before the first compile;
        # KOORD_XLA_CACHE (operator override) wins if set.
        if state_dir is None:
            state_dir = default_state_dir()
        self.state_dir = state_dir
        if state_dir:
            import koordinator_tpu

            try:
                os.makedirs(state_dir, exist_ok=True)
            except OSError as exc:
                # the compile cache is an optimization: an unwritable
                # default state dir (readOnlyRootFilesystem, no HOME)
                # must cost the restart-compile, not the daemon
                import logging

                logging.getLogger(__name__).warning(
                    "state dir %s unavailable (%s); persistent compile "
                    "cache disabled for this run",
                    state_dir,
                    exc,
                )
            else:
                koordinator_tpu.configure_compilation_cache(
                    os.path.join(state_dir, "xla-cache")
                )
        if xla_cache is not None:
            # an EXPLICIT --xla-cache outranks both the state-dir
            # default above and the KOORD_XLA_CACHE env (force=True):
            # the operator typed it.  "" / "0" disables the cache.
            import koordinator_tpu

            koordinator_tpu.configure_compilation_cache(
                None if xla_cache in ("", "0") else xla_cache,
                force=True,
            )
        self.xla_cache = xla_cache
        cfg = DEFAULT_CYCLE_CONFIG
        self.profiles = []
        if config_path:
            with open(config_path) as fh:
                self.profiles = load_config(fh.read())
            if self.profiles:
                cfg = self.profiles[0].cycle
        if candidate_width is not None:
            # sparse candidate engine (ISSUE 16): the width rides the
            # CycleConfig (a static jit argument), so the override must
            # land before any servicer compiles — CycleConfig validates
            # the power-of-two contract at construction
            import dataclasses

            cfg = dataclasses.replace(
                cfg, candidate_width=int(candidate_width)
            )
        self.cfg = cfg
        self.elector = LeaderElector(
            lease_path,
            identity or f"{socket.gethostname()}-{os.getpid()}",
        )
        mesh = None
        mesh_resident = False
        if mesh_devices:
            # MESH-RESIDENT serving (ISSUE 7): the snapshot itself lives
            # sharded over the 1-D cluster mesh — node tensors split,
            # pod/quota rows replicate, warm deltas scatter into the
            # owning shard, Assign runs the round-based multi-chip cycle
            import jax

            from koordinator_tpu.parallel import (
                cluster_mesh,
                pow2_device_count,
            )

            devices = jax.devices()
            if mesh_devices == "auto":
                want = len(devices)
            else:
                try:
                    want = int(mesh_devices)
                except ValueError:
                    raise ValueError(
                        f"--mesh must be a device count or 'auto', got "
                        f"{mesh_devices!r}"
                    ) from None
            # node buckets are powers of two: a non-power-of-two mesh
            # would never divide any geometry, silently leaving the
            # operator on single-chip capacity — round DOWN so the mesh
            # always activates
            n = pow2_device_count(min(max(1, want), len(devices)))
            if n != want:
                import logging

                logging.getLogger(__name__).warning(
                    "--mesh %s rounded down to %d devices (largest "
                    "power of two <= visible %d: node buckets are "
                    "powers of two, so only a power-of-two mesh "
                    "divides every geometry)",
                    mesh_devices, n, len(devices),
                )
            mesh = cluster_mesh(devices[:n])
            mesh_resident = True
        elif shard:
            # serve the round-based sharded cycle over every visible
            # device (parallel/shard_assign.py; Assign replies
            # path="shard", bit-identical with single-chip).  The
            # snapshot stays single-chip-resident; --mesh supersedes
            # this when the cluster outgrows one device's memory
            import jax

            from koordinator_tpu.parallel import make_mesh

            mesh = make_mesh(jax.devices())
        servicer_kw = {}
        if pipeline_depth is not None:
            servicer_kw["pipeline_depth"] = int(pipeline_depth)
        if coalesce_cap_ms is not None:
            servicer_kw["coalesce_cap_ms"] = float(coalesce_cap_ms)
        if max_inflight is not None:
            servicer_kw["max_inflight"] = int(max_inflight)
        if score_incr_max_ratio is not None:
            servicer_kw["score_incr_max_ratio"] = float(score_incr_max_ratio)
        # degradation ladder knobs (ISSUE 13, docs/REPLICATION.md
        # "Degradation ladder"): breaker trip/cooldown + brownout
        # staleness bound
        if breaker_threshold is not None:
            servicer_kw["breaker_threshold"] = int(breaker_threshold)
        if breaker_cooldown_ms is not None:
            servicer_kw["breaker_cooldown_ms"] = float(breaker_cooldown_ms)
        if brownout_max_lag is not None:
            servicer_kw["brownout_max_lag"] = int(brownout_max_lag)
        # distributed tracing (ISSUE 14): --trace-export turns on the
        # span exporter (OTLP-shaped JSON lines; bare flag / "1" =
        # <state-dir>/traces).  Shed-fraction overrides validate at
        # construction — a bad ladder fails the daemon at startup.
        if trace_export is not None:
            servicer_kw["trace_export"] = trace_export
        if shed_fractions is not None:
            servicer_kw["shed_fractions"] = shed_fractions
        # device-time truth (ISSUE 19): --devprof-sample wires the XLA
        # launch ledger — compile/cost attribution at every registered
        # jit boundary plus 1-in-N device-time sampling.  Default off:
        # the serving path stays bit-inert (reply-byte parity, zero jit
        # cache misses) unless the operator opts in.
        if devprof_sample is not None:
            servicer_kw["devprof_sample"] = int(devprof_sample)
        # cold-path kill (ISSUE 20, docs/KERNEL.md "Cold path"):
        # --prewarm turns on the launch ledger's CAPTURE mode — every
        # boundary launch records its abstract signature into
        # <state-dir>/prewarm.pkl — and, at start()/promote(), replays
        # the PREVIOUS incarnation's set through the AOT seam
        # (fn.lower(...).compile()) on a background thread while the
        # transports already serve.  Default off: with the flag unset
        # the boundary wrapper keeps its bit-inert fast path.
        self._prewarm_enabled = bool(prewarm) and bool(state_dir)
        self._prewarm_runner = None
        if prewarm and not self._prewarm_enabled:
            import logging

            logging.getLogger(__name__).warning(
                "--prewarm needs a writable --state-dir for the "
                "signature set; prewarm disabled for this run"
            )
        if self._prewarm_enabled:
            from koordinator_tpu.obs import devprof

            devprof.configure(capture=True, state_dir=state_dir)
        # replication role (ISSUE 8, koordinator_tpu/replication/):
        # --replicate-from makes this daemon a READ FOLLOWER — it
        # subscribes to the named leader's replication socket, applies
        # the streamed frames onto its own device-resident snapshot,
        # serves Score/Assign locally and refuses client Syncs.  The
        # default role is leader: every committed Sync streams out on
        # <uds>.repl for any follower that dials it.
        #
        # --relay-from (ISSUE 18, the relay tree) is the follower role
        # PLUS re-publication: the value is this daemon's ANCESTOR
        # ladder (parent.repl first, then grandparent, ... root) — it
        # subscribes to the first entry with the rest as failover
        # fallbacks, forwards every applied delta's exact wire bytes on
        # its own <uds>.repl, and answers descendant hello/resume from
        # an in-memory frame cache — so fan-out bandwidth multiplies
        # with tree width and an interior relay's death re-parents its
        # children onto a surviving ancestor with zero full resyncs.
        self.relay_from = relay_from
        self._ancestors: tuple = ()
        self._relay = False
        if relay_from and not replicate_from:
            parts = [p.strip() for p in relay_from.split(",") if p.strip()]
            if not parts:
                raise ValueError(
                    "--relay-from needs at least one ancestor socket path"
                )
            replicate_from = parts[0]
            self._ancestors = tuple(parts[1:])
            self._relay = True
        # hop = distance from the tree's root leader (0 = the root
        # itself); --tree-depth pins it for topologies the ladder
        # length cannot infer (e.g. a relay dialed through one shared
        # ancestor path)
        if tree_depth is not None:
            self.hop = max(0, int(tree_depth))
        elif self._relay:
            self.hop = 1 + len(self._ancestors)
        else:
            self.hop = 1 if replicate_from else 0
        self.replicate_from = replicate_from
        self.repl_path = uds_path + ".repl"
        self.repl_batch_bytes = repl_batch_bytes
        self.repl_compress = bool(repl_compress)
        self._relay_cache = None
        # elastic tier (ISSUE 18, replication/autoscale.py): the
        # control loop runs in-daemon against this registry's read
        # signals; the capacity LEVERS are injectable — an orchestrator
        # (or the trace harness) overrides autoscale_spawn/drain before
        # start(), the defaults just log the decision
        self._autoscale_enabled = bool(autoscale)
        self._autoscale_min = autoscale_min
        self._autoscale_max = autoscale_max
        self._read_slo_p99_ms = read_slo_p99_ms
        self._autoscale_interval_s = autoscale_interval_s
        self._autoscaler = None
        self.autoscale_spawn = self._default_scale_lever("spawn")
        self.autoscale_drain = self._default_scale_lever("drain")
        self._publisher = None
        self._subscriber = None
        self.applier = None
        # crash tolerance (ISSUE 11): --journal appends every committed
        # frame to a CRC'd journal under --state-dir; on boot the
        # journal replays through the stage/commit seam and the daemon
        # resumes the SAME s<epoch>-<gen> chain (no client/follower
        # resync storm).  A follower opens its own journal at
        # promotion.
        self.journal = None
        self.journal_replay: Optional[dict] = None
        self._journal_enabled = bool(journal)
        self._journal_compact_every = journal_compact_every
        self._journal_fsync = bool(journal_fsync)
        self._promote_lock = witness_lock(
            "scheduler.server.SchedulerServer._promote_lock")
        self._promoted = False
        if self._journal_enabled and not state_dir:
            import logging

            logging.getLogger(__name__).warning(
                "--journal needs a writable --state-dir; journaling "
                "disabled for this run"
            )
            self._journal_enabled = False
        if replicate_from:
            from koordinator_tpu.replication.follower import (
                FollowerServicer,
            )

            self.servicer = FollowerServicer(
                cfg, leader=replicate_from, mesh=mesh,
                mesh_resident=mesh_resident, state_dir=state_dir,
                **servicer_kw,
            )
        else:
            self.servicer = _LeaderGatedServicer(
                cfg, lambda: self.elector.is_leader, mesh=mesh,
                mesh_resident=mesh_resident,
                # flight-recorder dumps (obs/flight.py) land under
                # <state-dir>/flight on cycle error/demotion/SIGUSR1
                state_dir=state_dir,
                **servicer_kw,
            )
        self.api = APIService()
        # /healthz slo block (ISSUE 12): last-window p50/p99 per
        # cycle-latency series (path/wave labels), from the SAME
        # obs/slo.py estimator the trace-replay SLO gate judges with —
        # operators read the identical numbers.  One window per
        # /healthz request.
        from koordinator_tpu.obs.slo import SloWindow
        from koordinator_tpu.obs.scorer_metrics import CYCLE_LATENCY

        self._slo_window = SloWindow(families=(CYCLE_LATENCY,))
        self._slo_lock = witness_lock(
            "scheduler.server.SchedulerServer._slo_lock")
        self.uds_path = uds_path
        self.enable_grpc = enable_grpc
        self._raw_server: Optional[RawUdsServer] = None
        self._grpc_server = None
        self._elector_thread: Optional[threading.Thread] = None

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/healthz":
                    # demoted kernel shape-buckets ride health (round-3
                    # review: demotion must be visible beyond a log line)
                    demoted = {
                        "/".join(map(str, k)): v
                        for k, v in pallas_demotions().items()
                    }
                    self._reply(
                        200,
                        {
                            "ok": True,
                            "leader": outer.elector.is_leader,
                            "kernel_demotions": demoted,
                            # warm-cycle visibility: whether the last Sync
                            # landed on the resident device tensors
                            # ("warm") or dropped residency ("cold")
                            "last_sync_path": outer.servicer.state.last_sync_path,
                            # replication tier visibility (ISSUE 8)
                            "replica": outer.replica_health(),
                            # degradation ladder visibility (ISSUE 13):
                            # breaker state, per-band sheds, degraded
                            # replies served from the brownout cache
                            "degrade": outer.degrade_health(),
                            # SLO visibility (ISSUE 12): last-window
                            # per-series quantiles from the gate's
                            # own estimator
                            "slo": outer.slo_health(),
                            # device-time truth (ISSUE 19): backend
                            # platform, compile ledger summary, top
                            # boundaries by cumulative device time
                            "device": outer.device_health(),
                            # cold-path kill (ISSUE 20): AOT signature
                            # prewarm progress — replay state, counts,
                            # cumulative compile time
                            "prewarm": outer.prewarm_health(),
                        },
                    )
                    return
                if self.path == "/debug/stacks":
                    reply_text(self, format_thread_stacks())
                    return
                if self.path == "/metrics":
                    # the scorer families (koord_scorer_* cycle latency
                    # histogram, rounds, sync delta/full, jit cache
                    # misses, UDS counters — obs/scorer_metrics.py) plus
                    # the daemon gauges, all through the ONE registry so
                    # every family renders exactly once.
                    # MetricsRegistry.wsgi_app serves the same body for
                    # WSGI embedders.
                    registry = outer.servicer.telemetry.registry
                    registry.gauge_set(
                        "koord_scheduler_leader",
                        int(outer.elector.is_leader),
                    )
                    registry.gauge_set(
                        "koord_scheduler_kernel_demotions",
                        len(pallas_demotions()),
                    )
                    reply_text(self, registry.render())
                    return
                path, _, query = self.path.partition("?")
                q = dict(
                    kv.split("=", 1) for kv in query.split("&") if "=" in kv
                )
                code, doc = outer.api.dispatch(path, q)
                self._reply(code, doc)

            def _reply(self, code, doc):
                data = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((http_host, http_port), Handler)
        self._http = HTTPLifecycle(self._httpd)

    @property
    def http_port(self) -> int:
        return self._httpd.server_address[1]

    @staticmethod
    def _default_scale_lever(action: str):
        """The no-op capacity lever: the in-daemon autoscaler DECIDES;
        starting/stopping replica processes is the orchestrator's job
        (the trace harness injects real levers).  Logging keeps a
        lever-less deployment's decisions visible."""
        import logging

        def lever():
            logging.getLogger(__name__).warning(
                "autoscale %s decided but no capacity lever is wired "
                "(set server.autoscale_%s before start())",
                action, action,
            )

        return lever

    def replica_health(self) -> dict:
        """The /healthz replication block: role, chain position, the
        journal's durable position/compaction stamp and replay outcome
        (ISSUE 11), follower lag or the leader's live subscriber count,
        and the promotion flag — the fields the failover runbooks in
        docs/REPLICATION.md key off."""
        role = "leader"
        if self.replicate_from and not self._promoted:
            role = "follower"
        out = {
            "role": role,
            "promoted": self._promoted,
            "snapshot_id": self.servicer.snapshot_id(),
            "shed": self.servicer.admission.stats()["shed"],
        }
        if self.applier is not None:
            out["applied_frames"] = self.applier.applied
            out["resyncs"] = self.applier.resyncs
            out["lag_ms"] = self.applier.last_lag_ms
        if self._subscriber is not None:
            out["redials"] = self._subscriber.redials
        if self._publisher is not None:
            out["followers"] = self._publisher.follower_count()
            out["resumed_subscriptions"] = (
                self._publisher.resumed_subscriptions
            )
            out["publish"] = self._publisher.stats()
        if self._relay:
            out["relay"] = {
                "hop": self.hop,
                "ancestors": list(self._ancestors),
                "active_path": (
                    self._subscriber.active_path
                    if self._subscriber is not None else None
                ),
                "ancestor_switches": (
                    self._subscriber.ancestor_switches
                    if self._subscriber is not None else 0
                ),
                "cache": (
                    self._relay_cache.stats()
                    if self._relay_cache is not None else None
                ),
            }
        if self._autoscaler is not None:
            out["autoscale"] = self._autoscaler.stats()
        if self.journal is not None:
            st = self.journal.stats()
            out["journal"] = {
                "position": st["generation"],
                "bytes": st["bytes"],
                "appends": st["appends"],
                "compactions": st["compactions"],
                "truncations": st["truncations"],
                "last_compaction_us": st["last_compaction_us"],
                "compact_every": st["compact_every"],
            }
            if self.journal_replay is not None:
                out["journal"]["replayed_frames"] = (
                    self.journal_replay["replayed_frames"]
                )
                out["journal"]["replay_ms"] = (
                    self.journal_replay["replay_ms"]
                )
        return out

    def slo_health(self) -> dict:
        """The /healthz ``slo`` block: per-series p50/p99 of the cycle
        latency histogram over the window since the LAST /healthz
        request (first request: since boot), estimated by the same
        ``obs/slo.py`` bucket quantiles the trace-replay SLO gate
        uses (docs/OBSERVABILITY.md "The SLO gate")."""
        with self._slo_lock:
            window = self._slo_window.advance(
                self.servicer.telemetry.registry
            )
        return {"window": window}

    def degrade_health(self) -> dict:
        """The /healthz ``degrade`` block (ISSUE 13): where on the
        degradation ladder this daemon currently sits — breaker state
        (any non-closed state in the prod path is page-worthy), the
        admission gate's per-band shed counts, degraded (brownout)
        replies served, and deadline-expired evictions."""
        sv = self.servicer
        out = {
            "breaker": sv.breaker.stats(),
            "admission": sv.admission.stats(),
            "degraded_replies": sv.degraded_replies,
            "deadline_evicted": sv.dispatch.deadline_evicted,
            "brownout_max_lag": sv._brownout_max_lag,
        }
        return out

    def prewarm_health(self) -> dict:
        """The /healthz ``prewarm`` block (ISSUE 20): whether the AOT
        signature prewarm is enabled and, once the runner started, its
        replay progress — state (loading/importing/replaying/done),
        signature counts by outcome, cumulative compile milliseconds.
        A request arriving before its signature replays just compiles
        inline, so "pending > 0" is a boot-latency note, never an
        availability problem."""
        out: dict = {"enabled": self._prewarm_enabled}
        runner = self._prewarm_runner
        if runner is not None:
            out.update(runner.stats())
        return out

    def _start_prewarm(self) -> None:
        """Kick the background AOT replay of the persisted signature
        set.  Runs while the transports already serve: a request whose
        signature has not compiled yet compiles inline exactly as
        today (the persistent disk cache still catches repeats).
        promote() re-kicks it so a promoted follower also warms the
        leader-path boundaries; already-compiled signatures are ledger
        hits and cost microseconds."""
        from koordinator_tpu.obs.prewarm import PrewarmRunner

        if self._prewarm_runner is not None:
            self._prewarm_runner.stop()
        self._prewarm_runner = PrewarmRunner(
            self.state_dir, metrics=self.servicer.telemetry.metrics
        ).start()

    def device_health(self) -> dict:
        """The /healthz ``device`` block (ISSUE 19): backend platform
        and device count, the launch ledger's compile summary (compiles,
        cumulative compile wall-time, attributed retraces), and the top
        boundaries by cumulative sampled device time.  With the ledger
        off (``--devprof-sample`` unset/0) the block still reports the
        platform so operators can tell CPU-leg from TPU-leg daemons."""
        from koordinator_tpu.obs import devprof

        return devprof.health_block()

    # -- crash tolerance (ISSUE 11) --
    def _journal_path(self) -> str:
        return os.path.join(self.state_dir, "journal.krj")

    def _open_journal(self):
        from koordinator_tpu.replication.journal import FrameJournal

        kw = {}
        if self._journal_compact_every is not None:
            kw["compact_every"] = int(self._journal_compact_every)
        return FrameJournal(
            self._journal_path(), fsync=self._journal_fsync, **kw
        )

    def _boot_journal(self) -> None:
        """Leader boot: replay the journal through the stage/commit
        seam BEFORE any transport serves, so the first client RPC
        already sees the resumed ``s<epoch>-<gen>`` chain."""
        journal = self._open_journal()
        stats = journal.recover(self.servicer)
        journal.attach(self.servicer)
        # koordlint: disable=unguarded-shared-state(reason: leader boot runs before any transport or elector thread starts; the competing locked writer is promote, which cannot run yet)
        self.journal = journal
        self.journal_replay = stats
        if stats["replayed_frames"]:
            self.servicer.telemetry.metrics.count_failover("warm_restart")
            import logging

            logging.getLogger(__name__).warning(
                "journal warm-restart: replayed %d frame(s) in %.1f ms, "
                "resumed %s (truncated tail: %s)",
                stats["replayed_frames"], stats["replay_ms"],
                stats["resumed_id"], stats["truncated"],
            )

    def promote(self) -> str:
        """Promote this follower daemon to the tier's leader (ISSUE 11;
        SIGUSR2 and the raw-UDS admin RPC both land here): stop the
        subscription, bump the epoch on the servicer (clients
        full-resync ONCE on the epoch fence; reads never stop), open
        this daemon's own journal seeded with a full-state frame, and
        start publishing on its own ``<uds>.repl``.  Idempotent;
        raises on a daemon that is already the leader role."""
        if not self.replicate_from:
            raise RuntimeError(
                "promote: this daemon is already the leader role"
            )
        with self._promote_lock:
            if self._promoted:
                return self.servicer.snapshot_id()
            if self._subscriber is not None:
                self._subscriber.stop()
                self._subscriber = None
            sid = self.servicer.promote()
            if self._journal_enabled:
                journal = self._open_journal()
                epoch, gen, payload = (
                    self.servicer.export_replication_snapshot()
                )
                journal.write_base(epoch, gen, payload)
                journal.attach(self.servicer)
                self.journal = journal
            from koordinator_tpu.replication.leader import (
                ReplicationPublisher,
            )

            if self._publisher is not None:
                # a promoted RELAY already publishes on its own .repl:
                # hook the local Sync commit path into it and point the
                # hello/resume seam at the durable journal (the relay
                # cache's window ended with the parent's chain)
                self._publisher.journal = self.journal
                self._publisher.attach()
            else:
                self._publisher = ReplicationPublisher(
                    self.servicer, self.repl_path, journal=self.journal
                ).attach().start()
            self._promoted = True
            if self._prewarm_enabled:
                try:
                    self._start_prewarm()
                except Exception:  # prewarm is an accelerant: a failed re-kick must not fail the promotion that clients are waiting on
                    import logging

                    logging.getLogger(__name__).exception(
                        "post-promotion prewarm re-kick failed"
                    )
            return sid

    def _install_sigusr2(self) -> None:
        """SIGUSR2 = promote (main thread only, like the flight
        recorder's SIGUSR1; a no-op on leaders so a fat-fingered
        signal cannot hurt)."""
        import logging
        import signal

        def _handler(signum, frame):
            def run():
                try:
                    self.promote()
                except Exception:  # a failed promotion must be logged, never kill the daemon from a signal handler thread
                    logging.getLogger(__name__).exception(
                        "SIGUSR2 promotion failed"
                    )

            if self.replicate_from:
                # off the signal frame: promotion joins threads and
                # takes servicer locks, neither safe in a handler
                threading.Thread(target=run, daemon=True).start()

        try:
            signal.signal(signal.SIGUSR2, _handler)
        except ValueError:
            pass  # not the main thread (embedded/test use)

    def start(self) -> "SchedulerServer":
        os.makedirs(os.path.dirname(self.uds_path) or ".", exist_ok=True)
        # operator seam: `kill -USR1 <pid>` dumps the last K cycles'
        # spans under <state-dir>/flight (no-op off the main thread);
        # SIGUSR2 promotes a follower (ISSUE 11)
        self.servicer.telemetry.flight.install_sigusr1()
        self._install_sigusr2()
        # journal replay BEFORE any transport binds: the first RPC a
        # reconnecting client lands must already see the resumed chain
        if self._journal_enabled and not self.replicate_from:
            self._boot_journal()
        from koordinator_tpu.bridge.udsserver import (
            METHOD_PROFILE,
            METHOD_PROMOTE,
        )

        def _promote_admin(payload: bytes) -> bytes:
            return self.promote().encode()

        def _profile_admin(payload: bytes) -> bytes:
            # on-demand device profile capture (ISSUE 19): payload is an
            # optional ASCII window in milliseconds; the reply is the
            # capture directory under --state-dir.  jax.profiler stops
            # on a background thread so the admin RPC returns
            # immediately — the operator polls the directory.
            from koordinator_tpu.obs import devprof

            window_ms = 1000
            if payload.strip():
                window_ms = int(payload.strip().decode("ascii"))
            return devprof.capture_profile(
                self.state_dir, window_ms=window_ms
            ).encode()

        self._raw_server = RawUdsServer(
            self.uds_path + ".raw", servicer=self.servicer,
            admin_handlers={
                METHOD_PROMOTE: _promote_admin,
                METHOD_PROFILE: _profile_admin,
            },
        ).start()
        if self.enable_grpc:
            self._grpc_server = make_server(servicer=self.servicer)
            self._grpc_server.add_insecure_port(f"unix://{self.uds_path}")
            self._grpc_server.start()
        repl_kw = {}
        if self.repl_batch_bytes is not None:
            repl_kw["max_batch_bytes"] = int(self.repl_batch_bytes)
        repl_kw["compress_full"] = self.repl_compress
        metrics = self.servicer.telemetry.metrics
        if self.replicate_from:
            from koordinator_tpu.replication.follower import (
                APPLIED,
                ReplicaApplier,
                ReplicationSubscriber,
            )

            self.applier = ReplicaApplier(self.servicer, hop=self.hop)
            on_raw = None
            if self._relay:
                # relay role (ISSUE 18): re-publish the applied stream
                # on this daemon's own .repl.  The publisher is NOT
                # attach()ed — there is no local Sync commit to hook;
                # frames arrive through the on_raw forwarding seam as
                # the exact wire bytes the parent sent, and descendant
                # hello/resume is answered from the in-memory cache
                from koordinator_tpu.replication import codec
                from koordinator_tpu.replication.journal import (
                    RelayFrameCache,
                )
                from koordinator_tpu.replication.leader import (
                    ReplicationPublisher,
                )

                self._relay_cache = RelayFrameCache()
                # koordlint: disable=unguarded-shared-state(reason: boot runs before the elector/HTTP threads exist; promote, the locked writer, cannot race it)
                self._publisher = ReplicationPublisher(
                    self.servicer, self.repl_path,
                    journal=self._relay_cache, **repl_kw,
                ).start()
                publisher = self._publisher
                cache = self._relay_cache

                def on_raw(result, frame, raw):
                    if result != APPLIED:
                        return
                    if frame.kind == codec.KIND_DELTA:
                        # forward-then-cache would race a descendant's
                        # hello between the two; cache-first keeps
                        # frames_since ahead of the fan-out
                        cache.add_delta(
                            frame.epoch, frame.generation, raw
                        )
                        publisher.publish_frame(raw)
                        metrics.count_relay_forwarded()
                    else:
                        # an applied full rebases this relay's chain;
                        # descendants are never forwarded the full —
                        # each relay serves opens from its OWN export
                        cache.note_full(frame.epoch, frame.generation)

            # koordlint: disable=unguarded-shared-state(reason: boot runs before the elector/HTTP threads exist; promote, the locked writer, cannot race it)
            self._subscriber = ReplicationSubscriber(
                self.replicate_from, self.applier,
                fallbacks=self._ancestors, on_raw=on_raw,
            ).start()
        else:
            from koordinator_tpu.replication.leader import (
                ReplicationPublisher,
            )

            # koordlint: disable=unguarded-shared-state(reason: boot runs before the elector/HTTP threads exist; promote, the locked writer, cannot race it)
            self._publisher = ReplicationPublisher(
                self.servicer, self.repl_path, journal=self.journal,
                **repl_kw,
            ).attach().start()
        metrics.set_relay_position(self.hop)
        if self._autoscale_enabled:
            from koordinator_tpu.replication.autoscale import (
                AutoscalePolicy,
                RegistrySignals,
                ReplicaAutoscaler,
            )

            policy_kw = {}
            if self._autoscale_min is not None:
                policy_kw["min_replicas"] = int(self._autoscale_min)
            if self._autoscale_max is not None:
                policy_kw["max_replicas"] = int(self._autoscale_max)
            if self._read_slo_p99_ms is not None:
                policy_kw["p99_high_ms"] = float(self._read_slo_p99_ms)
            signals = RegistrySignals(self.servicer.telemetry.registry)
            self._autoscaler = ReplicaAutoscaler(
                AutoscalePolicy(**policy_kw),
                signals.collect,
                spawn=lambda: self.autoscale_spawn(),
                drain=lambda: self.autoscale_drain(),
                metrics=metrics,
                interval_s=(
                    float(self._autoscale_interval_s)
                    if self._autoscale_interval_s is not None else 1.0
                ),
            ).start()
        self._http.start()
        self._elector_thread = threading.Thread(
            target=self.elector.run, daemon=True
        )
        self._elector_thread.start()
        # AOT signature prewarm LAST (ISSUE 20): every transport above
        # is already accepting, so the background replay overlaps real
        # serving — exactly the contract (an unreplayed signature
        # compiles inline, the breaker/brownout ladder is untouched)
        if self._prewarm_enabled:
            self._start_prewarm()
        return self

    def stop(self):
        if self._prewarm_runner is not None:
            self._prewarm_runner.stop()
        if self._prewarm_enabled:
            # final dump so signatures first seen after the last
            # incremental flush still make the next boot's replay set
            from koordinator_tpu.obs import devprof

            devprof.dump_prewarm(self.state_dir)
        self.elector.stop()
        if self._elector_thread:
            self._elector_thread.join(timeout=5)
        if self._autoscaler:
            self._autoscaler.stop()
        if self._subscriber:
            self._subscriber.stop()
        if self._publisher:
            self._publisher.stop()
        if self._raw_server:
            self._raw_server.stop()
        if self._grpc_server:
            self._grpc_server.stop(0)
        if self.journal is not None:
            self.journal.close()
        self._http.stop()


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="koord-scheduler")
    ap.add_argument("--config", help="component config YAML", default=None)
    ap.add_argument(
        "--lease", default="/tmp/koord-scheduler/leader.lease",
        help="leader-election lease file (shared dir across replicas)",
    )
    ap.add_argument("--identity", default=None)
    ap.add_argument(
        "--uds", default="/tmp/koord-scheduler/scorer.sock",
        help="scorer UDS path (gRPC; <path>.raw serves the native framing)",
    )
    ap.add_argument("--http-host", default="127.0.0.1")
    ap.add_argument("--http-port", type=int, default=10251)
    ap.add_argument(
        "--shard", action="store_true",
        help="serve the round-based multi-chip Assign over every visible "
        "device (jax.sharding.Mesh; placements stay bit-identical); the "
        "snapshot stays single-chip-resident — see --mesh for true "
        "capacity scaling",
    )
    ap.add_argument(
        "--mesh", dest="mesh_devices",
        default=os.environ.get("KOORD_MESH_DEVICES") or None,
        help="serve the MESH-RESIDENT snapshot: shard the cluster's node "
        "tensors over N devices ('auto' = all visible; the combined HBM "
        "is the capacity), replicate pod/quota rows, scatter warm deltas "
        "into the owning shard only; placements stay bit-identical to "
        "single-chip (env: KOORD_MESH_DEVICES)",
    )
    ap.add_argument(
        "--pipeline-depth", type=int,
        default=(
            int(os.environ["KOORD_PIPELINE_DEPTH"])
            if os.environ.get("KOORD_PIPELINE_DEPTH") else None
        ),
        help="launched-but-unread device batches allowed in flight "
        "(default 2 = double buffering; 1 = serial readbacks, the bench "
        "baseline; env: KOORD_PIPELINE_DEPTH) — a TPU tuning knob, no "
        "code edit needed (docs/PIPELINE.md)",
    )
    ap.add_argument(
        "--coalesce-cap-ms", type=float,
        default=(
            float(os.environ["KOORD_COALESCE_CAP_MS"])
            if os.environ.get("KOORD_COALESCE_CAP_MS") else None
        ),
        help="clamp of the adaptive gather window's straggler wait "
        "(default 5.0 ms; env: KOORD_COALESCE_CAP_MS) — bounds the "
        "latency tax a burst-gathering leader may pay (docs/PIPELINE.md)",
    )
    ap.add_argument(
        "--max-inflight", type=int,
        default=(
            int(os.environ["KOORD_MAX_INFLIGHT"])
            if os.environ.get("KOORD_MAX_INFLIGHT") else None
        ),
        help="admission control (docs/REPLICATION.md): read RPCs "
        "(Score/Assign) admitted-but-unfinished before new ones shed "
        "with RESOURCE_EXHAUSTED + a retry-after hint; 0 = unlimited "
        "(default; env: KOORD_MAX_INFLIGHT).  Sync is never shed",
    )
    ap.add_argument(
        "--replicate-from", dest="replicate_from",
        default=os.environ.get("KOORD_REPLICATE_FROM") or None,
        help="run as a READ FOLLOWER of the leader daemon whose "
        "replication socket is at this path (the leader serves it at "
        "<uds>.repl): apply the streamed Sync frames onto a local "
        "device-resident snapshot copy, serve Score/Assign locally, "
        "refuse client Syncs (env: KOORD_REPLICATE_FROM; "
        "docs/REPLICATION.md)",
    )
    ap.add_argument(
        "--relay-from", dest="relay_from",
        default=os.environ.get("KOORD_RELAY_FROM") or None,
        help="run as a RELAY follower (docs/REPLICATION.md \"Relay "
        "tree & autoscaling\"): comma-separated ancestor ladder of "
        "replication sockets, nearest parent first (e.g. "
        "'relay1.sock.repl,root.sock.repl').  Subscribes to the first "
        "entry with the rest as failover fallbacks, re-publishes every "
        "applied delta frame byte-identically on this daemon's own "
        "<uds>.repl for its children, and answers their hello/resume "
        "from an in-memory frame cache — fan-out bandwidth multiplies "
        "with tree width (env: KOORD_RELAY_FROM)",
    )
    ap.add_argument(
        "--tree-depth", type=int, dest="tree_depth",
        default=(
            int(os.environ["KOORD_TREE_DEPTH"])
            if os.environ.get("KOORD_TREE_DEPTH") else None
        ),
        help="pin this daemon's hop distance from the relay tree's "
        "root (labels the per-hop lag gauge); default inferred from "
        "the --relay-from ladder length (env: KOORD_TREE_DEPTH)",
    )
    ap.add_argument(
        "--repl-batch-bytes", type=int, dest="repl_batch_bytes",
        default=(
            int(os.environ["KOORD_REPL_BATCH_BYTES"])
            if os.environ.get("KOORD_REPL_BATCH_BYTES") else None
        ),
        help="byte bound of the replication sender's frame coalescing: "
        "consecutive queued frames concatenate into ONE send syscall "
        "up to this many bytes per wakeup (default 1 MiB; frames-per-"
        "wakeup publishes on koord_scorer_repl_send_batch_frames; "
        "env: KOORD_REPL_BATCH_BYTES)",
    )
    ap.add_argument(
        "--repl-no-compress", action="store_true",
        default=bool(os.environ.get("KOORD_REPL_NO_COMPRESS")),
        help="disable zlib compression of full replication frames on "
        "the wire (compression is negotiated per subscriber in the "
        "hello handshake and never touches journal bytes or delta "
        "frames; env: KOORD_REPL_NO_COMPRESS=1)",
    )
    ap.add_argument(
        "--autoscale", action="store_true",
        default=bool(os.environ.get("KOORD_AUTOSCALE")),
        help="run the SLO-driven elastic-tier control loop "
        "(docs/REPLICATION.md \"Relay tree & autoscaling\"): watch the "
        "windowed read p99, replication lag and admission sheds, and "
        "call the wired spawn/drain capacity levers to hold "
        "--read-slo-p99-ms; decisions publish on "
        "koord_scorer_autoscale_* either way (env: KOORD_AUTOSCALE=1)",
    )
    ap.add_argument(
        "--autoscale-min", type=int, dest="autoscale_min",
        default=(
            int(os.environ["KOORD_AUTOSCALE_MIN"])
            if os.environ.get("KOORD_AUTOSCALE_MIN") else None
        ),
        help="floor of the autoscaler's follower count (default 1; "
        "env: KOORD_AUTOSCALE_MIN)",
    )
    ap.add_argument(
        "--autoscale-max", type=int, dest="autoscale_max",
        default=(
            int(os.environ["KOORD_AUTOSCALE_MAX"])
            if os.environ.get("KOORD_AUTOSCALE_MAX") else None
        ),
        help="ceiling of the autoscaler's follower count (default 8; "
        "env: KOORD_AUTOSCALE_MAX)",
    )
    ap.add_argument(
        "--read-slo-p99-ms", type=float, dest="read_slo_p99_ms",
        default=(
            float(os.environ["KOORD_READ_SLO_P99_MS"])
            if os.environ.get("KOORD_READ_SLO_P99_MS") else None
        ),
        help="the declared read SLO the autoscaler defends: windowed "
        "read p99 above this scales up (after the hysteresis streak), "
        "comfortably below scales down (default 50.0; env: "
        "KOORD_READ_SLO_P99_MS)",
    )
    ap.add_argument(
        "--autoscale-interval-s", type=float, dest="autoscale_interval_s",
        default=(
            float(os.environ["KOORD_AUTOSCALE_INTERVAL_S"])
            if os.environ.get("KOORD_AUTOSCALE_INTERVAL_S") else None
        ),
        help="seconds between autoscaler ticks (default 1.0; the "
        "hysteresis streaks and cooldown are counted in ticks, so "
        "this also scales the tier's reaction time; env: "
        "KOORD_AUTOSCALE_INTERVAL_S)",
    )
    ap.add_argument(
        "--score-incr-max-ratio", type=float,
        dest="score_incr_max_ratio",
        default=(
            float(os.environ["KOORD_SCORE_INCR_MAX_RATIO"])
            if os.environ.get("KOORD_SCORE_INCR_MAX_RATIO") else None
        ),
        help="incremental score engine's fallback gate (docs/KERNEL.md "
        "\"Incremental scoring\"): dirty-cost fraction "
        "(dirty_nodes/N + dirty_pods/P) above which a warm Score "
        "full-rescores instead of advancing the resident [P, N] score "
        "tensor column-wise (default 0.5, tuned by the trace-harness "
        "sweep — the measured crossover is ~0.6; env: "
        "KOORD_SCORE_INCR_MAX_RATIO)",
    )
    ap.add_argument(
        "--candidate-width", type=int,
        dest="candidate_width",
        default=(
            int(os.environ["KOORD_CANDIDATE_WIDTH"])
            if os.environ.get("KOORD_CANDIDATE_WIDTH") else None
        ),
        help="sparse candidate-set scoring (docs/KERNEL.md \"Sparse "
        "candidate scoring\"): score each pod against only its C "
        "lowest-indexed feasible nodes ([P, C] cells instead of the "
        "dense [P, N] wall).  Power of two; 0 (default) keeps the "
        "dense engines; 256 is the recommended serving width.  A pod "
        "whose exact feasible fan-out exceeds C makes Score refuse "
        "with FAILED_PRECONDITION rather than serve a truncated list "
        "(env: KOORD_CANDIDATE_WIDTH)",
    )
    ap.add_argument(
        "--journal", action="store_true",
        default=bool(os.environ.get("KOORD_JOURNAL")),
        help="crash tolerance (docs/REPLICATION.md): append every "
        "committed Sync's encoded frame to a CRC'd journal at "
        "<state-dir>/journal.krj and replay it on boot, resuming the "
        "same s<epoch>-<gen> chain — reconnecting clients/followers "
        "see no full resync; a torn tail truncates to the last valid "
        "frame (env: KOORD_JOURNAL=1)",
    )
    ap.add_argument(
        "--journal-compact-every", type=int,
        default=(
            int(os.environ["KOORD_JOURNAL_COMPACT_EVERY"])
            if os.environ.get("KOORD_JOURNAL_COMPACT_EVERY") else None
        ),
        help="delta frames between journal compactions (a full-state "
        "frame atomically replaces the file; default 256; env: "
        "KOORD_JOURNAL_COMPACT_EVERY)",
    )
    ap.add_argument(
        "--journal-fsync", action="store_true",
        default=bool(os.environ.get("KOORD_JOURNAL_FSYNC")),
        help="fsync every journal append (power-loss durability at a "
        "per-commit fsync cost; default flushes to the OS, which "
        "already survives the process crashes the tier replicates "
        "against; env: KOORD_JOURNAL_FSYNC=1)",
    )
    ap.add_argument(
        "--breaker-threshold", type=int,
        default=(
            int(os.environ["KOORD_BREAKER_THRESHOLD"])
            if os.environ.get("KOORD_BREAKER_THRESHOLD") else None
        ),
        help="circuit breaker (docs/REPLICATION.md \"Degradation "
        "ladder\"): consecutive device-launch failures that trip it "
        "open — Score then serves the bounded-staleness brownout "
        "cache with an explicit degraded flag, Assign fails fast with "
        "retry-after; 0 disables (default 3; env: "
        "KOORD_BREAKER_THRESHOLD)",
    )
    ap.add_argument(
        "--breaker-cooldown-ms", type=float,
        default=(
            float(os.environ["KOORD_BREAKER_COOLDOWN_MS"])
            if os.environ.get("KOORD_BREAKER_COOLDOWN_MS") else None
        ),
        help="how long an open breaker waits before admitting one "
        "half-open probe launch (default 250 ms; env: "
        "KOORD_BREAKER_COOLDOWN_MS)",
    )
    ap.add_argument(
        "--brownout-max-lag", type=int,
        default=(
            int(os.environ["KOORD_BROWNOUT_MAX_LAG"])
            if os.environ.get("KOORD_BROWNOUT_MAX_LAG") else None
        ),
        help="bounded staleness of breaker-open Score replies: max "
        "generations behind the current snapshot the brownout cache "
        "may serve (degraded flag set); a reply past the bound is "
        "REFUSED, never served (default 2; env: "
        "KOORD_BROWNOUT_MAX_LAG).  Assign never serves stale",
    )
    ap.add_argument(
        "--trace-export", nargs="?", const="1",
        default=os.environ.get("KOORD_TRACE_EXPORT") or None,
        help="distributed tracing (docs/OBSERVABILITY.md \"Distributed "
        "tracing\"): export completed spans as OTLP-shaped JSON lines "
        "to this directory (bare flag or '1' = <state-dir>/traces); "
        "requests carrying a trace_id get server spans either way, "
        "coalesced batches fan-in link to their one launch span, and "
        "`python -m koordinator_tpu.obs.assemble` merges the "
        "per-process exports into whole-request trees (env: "
        "KOORD_TRACE_EXPORT)",
    )
    for band, suffix in (("free", "FREE"), ("batch", "BATCH"),
                         ("mid", "MID"), ("prod", "PROD")):
        ap.add_argument(
            f"--shed-fraction-{band}", type=float,
            dest=f"shed_fraction_{band}",
            default=(
                float(os.environ[f"KOORD_SHED_FRACTION_{suffix}"])
                if os.environ.get(f"KOORD_SHED_FRACTION_{suffix}")
                else None
            ),
            help=f"admission shed ladder rung for the koord-{band} "
            "band: fraction of --max-inflight this band may fill "
            "before ITS new requests shed (must be in (0, 1] and "
            "monotone free <= batch <= mid <= prod; defaults "
            "0.50/0.65/0.80/1.00; env: "
            f"KOORD_SHED_FRACTION_{suffix})",
        )
    ap.add_argument(
        "--devprof-sample", type=int,
        default=(
            int(os.environ["KOORD_DEVPROF_SAMPLE"])
            if os.environ.get("KOORD_DEVPROF_SAMPLE") else None
        ),
        help="device-time truth (docs/OBSERVABILITY.md \"Device-time "
        "truth\"): sample 1-in-N serving launches for device wall-time "
        "through the XLA launch ledger, and capture compile time + XLA "
        "cost/memory analysis at every jit boundary's first compile; "
        "16 is the recommended rate; 0/unset = off — the serving path "
        "stays bit-inert (reply-byte parity, zero retraces).  Ledger "
        "persists to <state-dir>/devprof.json; read it with `python -m "
        "koordinator_tpu.obs.devprof <state-dir>` (env: "
        "KOORD_DEVPROF_SAMPLE)",
    )
    ap.add_argument(
        "--xla-cache", dest="xla_cache",
        default=None,
        help="persistent XLA compile cache directory (docs/KERNEL.md "
        "\"Cold path\"): an explicit path here outranks both the "
        "<state-dir>/xla-cache default and the KOORD_XLA_CACHE env; "
        "'' or '0' disables the cache for this run.  Point every "
        "replica of a tier (leader, followers, autoscaler spawns) at "
        "the SAME directory so one replica's compile is every "
        "replica's warm start (env: KOORD_XLA_CACHE)",
    )
    ap.add_argument(
        "--prewarm", action="store_true",
        default=bool(os.environ.get("KOORD_PREWARM")),
        help="AOT signature prewarm (docs/KERNEL.md \"Cold path\"): "
        "record every jit boundary's argument signatures into "
        "<state-dir>/prewarm.pkl and, on the next boot, AOT-compile "
        "the recorded set in ledger-hot order on a background thread "
        "while the daemon already serves — a restarted daemon reaches "
        "full warm speed without waiting for live traffic to re-trace "
        "every shape.  Progress publishes on koord_scorer_prewarm_* "
        "and /healthz 'prewarm'.  Default off: unset, the serving "
        "path is bit-identical to a build without the feature (env: "
        "KOORD_PREWARM=1)",
    )
    ap.add_argument(
        "--state-dir", default=None,
        help="daemon state directory (default: $XDG_STATE_HOME/"
        "koord-scheduler, per-user); the persistent XLA compile cache "
        "lives at <state-dir>/xla-cache so a restarted sidecar skips the "
        "multi-second cycle-kernel compile (KOORD_XLA_CACHE overrides)",
    )
    return ap


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    shed_fractions = {
        f"koord-{band}": value
        for band in ("free", "batch", "mid", "prod")
        if (value := getattr(args, f"shed_fraction_{band}")) is not None
    } or None
    server = SchedulerServer(
        config_path=args.config,
        lease_path=args.lease,
        identity=args.identity,
        uds_path=args.uds,
        http_host=args.http_host,
        http_port=args.http_port,
        shard=args.shard,
        state_dir=args.state_dir,
        mesh_devices=args.mesh_devices,
        pipeline_depth=args.pipeline_depth,
        coalesce_cap_ms=args.coalesce_cap_ms,
        max_inflight=args.max_inflight,
        replicate_from=args.replicate_from,
        relay_from=args.relay_from,
        tree_depth=args.tree_depth,
        repl_batch_bytes=args.repl_batch_bytes,
        repl_compress=not args.repl_no_compress,
        autoscale=args.autoscale,
        autoscale_min=args.autoscale_min,
        autoscale_max=args.autoscale_max,
        read_slo_p99_ms=args.read_slo_p99_ms,
        autoscale_interval_s=args.autoscale_interval_s,
        score_incr_max_ratio=args.score_incr_max_ratio,
        candidate_width=args.candidate_width,
        journal=args.journal,
        journal_compact_every=args.journal_compact_every,
        journal_fsync=args.journal_fsync,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_ms=args.breaker_cooldown_ms,
        brownout_max_lag=args.brownout_max_lag,
        trace_export=args.trace_export,
        shed_fractions=shed_fractions,
        devprof_sample=args.devprof_sample,
        xla_cache=args.xla_cache,
        prewarm=args.prewarm,
    ).start()
    try:
        threading.Event().wait()  # koordlint: disable=unbounded-wait(main thread parks forever by design; the server threads own the work and KeyboardInterrupt unparks)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
