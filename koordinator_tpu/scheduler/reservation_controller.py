"""Reservation lifecycle controller: phase machine, expiry, GC.

Reference: ``pkg/scheduler/plugins/reservation/controller/``:

* ``controller.go:171 sync`` — terminal phases are left alone; active
  reservations expire on TTL / ``expires`` / missing node; bound ones get
  their status (current owners + allocated) recomputed from the node's
  pods.
* ``garbage_collection.go:38 gcReservations`` — expired/succeeded
  reservations are deleted after ``defaultGCDuration`` (24h), immediately
  when their node is gone.
* phase setters mirror ``pkg/util/reservation/reservation.go:242-332``
  (SetReservationExpired / Succeeded / Available condition handling).

The controller owns reservation *dict* objects in the same shape
``model.reservation.encode_reservations`` consumes, so an expired
reservation drops out of the next cycle's ReservationTable (its restored
resources free up) with no extra plumbing: ``active_reservations()`` is
the encode input.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.model.resources import format_quantity, parse_quantity

# ReservationPhase (reference apis/scheduling/v1alpha1/reservation_types.go)
PENDING = "Pending"
AVAILABLE = "Available"
SUCCEEDED = "Succeeded"
FAILED = "Failed"

# condition reasons (reservation_types.go)
REASON_SCHEDULED = "Scheduled"
REASON_AVAILABLE = "Available"
REASON_EXPIRED = "Expired"
REASON_SUCCEEDED = "Succeeded"

DEFAULT_GC_CHECK_INTERVAL = 60.0  # garbage_collection.go:34
DEFAULT_GC_DURATION = 24 * 3600.0  # garbage_collection.go:35


@dataclasses.dataclass
class Condition:
    type: str
    status: bool
    reason: str
    last_transition: float
    last_probe: float


@dataclasses.dataclass
class Reservation:
    """One Reservation CR (spec + status), dict-spec compatible with
    model.reservation.encode_reservations."""

    name: str
    requests: Mapping = dataclasses.field(default_factory=dict)
    owners: Sequence[Mapping] = ()
    ttl_seconds: Optional[float] = 24 * 3600.0  # spec.TTL default 24h
    expires_at: Optional[float] = None  # spec.Expires wins over TTL
    allocate_once: bool = False
    allocate_policy: str = "Default"
    # reserve-pod priority (NewReservePod propagates it so reservations
    # compete/preempt at their own priority, util/reservation.go:165)
    priority: int = 0
    creation_time: float = 0.0

    phase: str = PENDING
    node: Optional[str] = None
    allocatable: Mapping = dataclasses.field(default_factory=dict)
    allocated: Mapping = dataclasses.field(default_factory=dict)
    current_owners: List[str] = dataclasses.field(default_factory=list)
    conditions: List[Condition] = dataclasses.field(default_factory=list)

    def is_terminal(self) -> bool:
        return self.phase in (SUCCEEDED, FAILED)

    def is_expired(self) -> bool:
        return self.phase == FAILED and any(
            c.reason == REASON_EXPIRED for c in self.conditions
        )

    def as_dict(self) -> Dict:
        """encode_reservations input row.  ``allocated`` holds axis-unit
        integers (computed by _sync_status); render them as quantities so
        encode_reservations' parse round-trips exactly (resources.py
        format_quantity contract)."""
        return {
            "name": self.name,
            "node": self.node,
            "allocatable": self.allocatable or self.requests,
            "allocated": {
                k: format_quantity(v, k) for k, v in self.allocated.items()
            },
            "owners": list(self.owners),
            "allocate_policy": self.allocate_policy,
            "allocate_once": self.allocate_once,
            "assigned_pods": len(self.current_owners),
        }


def _set_condition(r: Reservation, reason: str, status: bool, now: float):
    """SetReservationExpired/Succeeded condition handling
    (util/reservation.go:242-300): update the Ready condition in place,
    bump only the probe time when already not-ready."""
    for c in r.conditions:
        if c.type == "Ready":
            if c.status:  # was ready -> full transition
                c.status = status
                c.reason = reason
                c.last_transition = now
            else:  # already not ready: refresh reason/probe only
                c.reason = reason
            c.last_probe = now
            return
    r.conditions.append(
        Condition("Ready", status, reason, last_transition=now, last_probe=now)
    )


class ReservationController:
    """Phase machine + GC over a reservation store (controller.go:103)."""

    def __init__(
        self,
        node_exists: Optional[Callable[[str], bool]] = None,
        pods_on_node: Optional[Callable[[str], List[Mapping]]] = None,
        gc_duration: float = DEFAULT_GC_DURATION,
        clock: Callable[[], float] = time.time,
    ):
        self.reservations: Dict[str, Reservation] = {}
        self.node_exists = node_exists or (lambda n: True)
        self.pods_on_node = pods_on_node or (lambda n: [])
        self.gc_duration = gc_duration
        self.clock = clock

    # -- lifecycle events ---------------------------------------------------
    def create(self, r: Reservation) -> Reservation:
        if not r.creation_time:
            r.creation_time = self.clock()
        self.reservations[r.name] = r
        return r

    def mark_available(self, name: str, node: str, now: Optional[float] = None):
        """The scheduler bound the reservation (SetReservationAvailable,
        util/reservation.go:301): records node + allocatable, initializes
        conditions."""
        r = self.reservations[name]
        now = self.clock() if now is None else now
        r.node = node
        r.allocatable = dict(r.requests)
        r.phase = AVAILABLE
        r.conditions = [
            Condition("Scheduled", True, REASON_SCHEDULED, now, now),
            Condition("Ready", True, REASON_AVAILABLE, now, now),
        ]

    def mark_succeeded(self, name: str, now: Optional[float] = None):
        """AllocateOnce reservation fully consumed
        (SetReservationSucceeded, util/reservation.go:277)."""
        r = self.reservations[name]
        now = self.clock() if now is None else now
        r.phase = SUCCEEDED
        _set_condition(r, REASON_SUCCEEDED, False, now)

    # -- sync (controller.go:171) ------------------------------------------
    def _needs_expiration(self, r: Reservation, now: float) -> bool:
        if r.expires_at is not None:
            return now >= r.expires_at
        if r.ttl_seconds:
            return now - r.creation_time >= r.ttl_seconds
        return False

    def expire(self, r: Reservation, now: float):
        r.phase = FAILED
        _set_condition(r, REASON_EXPIRED, False, now)

    def sync(self, name: str, now: Optional[float] = None):
        r = self.reservations.get(name)
        if r is None or r.is_terminal():
            return
        now = self.clock() if now is None else now
        if self._needs_expiration(r, now):
            self.expire(r, now)
            return
        if r.node and not self.node_exists(r.node):
            self.expire(r, now)
            return
        self._sync_status(r, now)

    def _sync_status(self, r: Reservation, now: Optional[float] = None):
        """Recompute current owners + allocated from the node's pods
        (controller.go:208 syncStatus; pods carry a
        ``reservation_allocated`` annotation naming their reservation)."""
        if not r.node:
            return
        owners: List[str] = []
        allocated: Dict[str, int] = {}
        for pod in self.pods_on_node(r.node):
            if pod.get("reservation_allocated") != r.name:
                continue
            owners.append(pod.get("name", ""))
            for k, v in (pod.get("requests") or {}).items():
                allocated[k] = allocated.get(k, 0) + parse_quantity(v, k)
        r.current_owners = sorted(owners)
        r.allocated = allocated
        if r.allocate_once and owners and r.phase == AVAILABLE:
            self.mark_succeeded(r.name, now)

    def sync_all(self, now: Optional[float] = None):
        for name in list(self.reservations):
            self.sync(name, now)

    # -- GC (garbage_collection.go:38) --------------------------------------
    def gc(self, now: Optional[float] = None) -> List[str]:
        """Delete expired/succeeded reservations past the GC duration, or
        whose node no longer exists.  Returns the deleted names."""
        now = self.clock() if now is None else now
        deleted = []
        for name, r in list(self.reservations.items()):
            if not (r.is_expired() or r.phase == SUCCEEDED):
                continue
            stale = any(
                c.reason in (REASON_EXPIRED, REASON_SUCCEEDED)
                and now - c.last_transition > self.gc_duration
                for c in r.conditions
            )
            gone = bool(r.node) and not self.node_exists(r.node)
            if stale or gone:
                del self.reservations[name]
                deleted.append(name)
        return deleted

    # -- reservation-as-pod scheduling (eventhandlers) ----------------------
    def pending_reserve_pods(self) -> List[Dict]:
        """Pending reservations as reserve-pod dicts for the scheduling
        cycle (reference ``eventhandlers/reservation_handler.go:188``
        enqueues Reservations as pods built by ``NewReservePod``,
        ``util/reservation.go:53``): the pod carries the reservation's
        requests/priority plus the reserve-pod annotations."""
        out = []
        now = self.clock()
        for r in self.reservations.values():
            if r.phase != PENDING:
                continue
            if self._needs_expiration(r, now):
                # expiry is lazily applied: a dead reservation must not be
                # enqueued even if no sync pass ran yet
                self.expire(r, now)
                continue
            out.append(
                {
                    "name": f"reserve-pod-{r.name}",
                    "requests": dict(r.requests),
                    "priority": r.priority,
                    "annotations": {
                        "scheduling.koordinator.sh/reserve-pod": "true",
                        "scheduling.koordinator.sh/reservation-name": r.name,
                    },
                }
            )
        return out

    def on_reserve_pod_assigned(
        self, reservation_name: str, node: str, now: Optional[float] = None
    ) -> None:
        """The cycle placed a reserve pod: the reservation becomes
        Available on that node (SetReservationAvailable via the scheduler's
        reservation error-handler/bind flow).  Only a still-Pending
        reservation binds — a late callback must not resurrect an expired
        or already-bound one."""
        r = self.reservations.get(reservation_name)
        if r is None or r.phase != PENDING:
            return
        check_now = self.clock() if now is None else now
        if self._needs_expiration(r, check_now):
            self.expire(r, check_now)  # late bind of a dead reservation
            return
        self.mark_available(reservation_name, node, now)

    # -- snapshot feed ------------------------------------------------------
    def active_reservations(self) -> List[Dict]:
        """Rows for model.reservation.encode_reservations: only phases the
        transformer restores (Available; terminal phases release their
        resources to the next cycle)."""
        return [
            r.as_dict()
            for r in self.reservations.values()
            if r.phase == AVAILABLE
        ]
