"""Versioned component config: plugin args with defaults + validation.

Reference: ``pkg/scheduler/apis/config/types.go`` (LoadAwareSchedulingArgs
:30, NodeNUMAResourceArgs :103, ReservationArgs :150, CoschedulingArgs
:160, ElasticQuotaArgs :188, DeviceShareArgs :205), defaults
``v1beta2/defaults.go:33-48``, validation ``validation/``.  The component
config file is KubeSchedulerConfiguration-shaped YAML: profiles carry
pluginConfig entries keyed by plugin name; unknown fields are rejected
like strict decoding upstream.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional

import yaml

from koordinator_tpu.config import CycleConfig, LEAST_ALLOCATED, LoadAwareArgs, MOST_ALLOCATED
from koordinator_tpu.model import resources as res

LOADAWARE = "LoadAwareScheduling"
NODENUMA = "NodeNUMAResource"
RESERVATION = "Reservation"
COSCHEDULING = "Coscheduling"
ELASTICQUOTA = "ElasticQuota"
DEVICESHARE = "DeviceShare"
FIT = "NodeResourcesFit"


@dataclasses.dataclass(frozen=True)
class NodeNUMAResourceArgs:
    """types.go:103: default CPU bind policy + scoring strategy."""

    default_cpu_bind_policy: str = "FullPCPUs"
    scoring_strategy: str = LEAST_ALLOCATED
    numa_scoring_strategy: str = LEAST_ALLOCATED


@dataclasses.dataclass(frozen=True)
class CoschedulingArgs:
    """types.go:160: gang wait timeout + controller workers."""

    default_timeout_seconds: int = 600
    controller_workers: int = 1


@dataclasses.dataclass(frozen=True)
class ElasticQuotaArgs:
    """types.go:188: delay evict + revoke interval."""

    delay_evict_time_seconds: int = 300
    revoke_pods_interval_seconds: int = 60
    default_quota_group_max: Dict[str, str] = dataclasses.field(default_factory=dict)
    quota_group_namespace: str = "koordinator-system"


@dataclasses.dataclass(frozen=True)
class ReservationArgs:
    """types.go:150: enable preemption against reservations."""

    enable_preemption: bool = False
    min_candidate_nodes_percentage: int = 10
    min_candidate_nodes_absolute: int = 100


@dataclasses.dataclass(frozen=True)
class DeviceShareArgs:
    """types.go:205: allocation scoring strategy."""

    allocate_strategy: str = "FirstFit"
    scoring_strategy: str = LEAST_ALLOCATED


@dataclasses.dataclass
class Profile:
    scheduler_name: str
    cycle: CycleConfig
    numa: NodeNUMAResourceArgs
    coscheduling: CoschedulingArgs
    elasticquota: ElasticQuotaArgs
    reservation: ReservationArgs
    deviceshare: DeviceShareArgs


_KNOWN_PLUGINS = {
    LOADAWARE,
    NODENUMA,
    RESERVATION,
    COSCHEDULING,
    ELASTICQUOTA,
    DEVICESHARE,
    FIT,
}
_STRATEGIES = {LEAST_ALLOCATED, MOST_ALLOCATED}


class ConfigError(ValueError):
    pass


def _check_fields(args: Mapping, allowed: set, where: str, errs: List[str]):
    for k in args:
        if k not in allowed:
            errs.append(f"{where}: unknown field {k!r}")


def _resource_map(m: Optional[Mapping], where: str, errs: List[str]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for name, v in (m or {}).items():
        if name not in res.RESOURCE_INDEX:
            errs.append(f"{where}: unknown resource {name!r}")
            continue
        iv = int(v)
        if iv < 0:
            errs.append(f"{where}[{name}]: must be >= 0")
        out[name] = iv
    return out


def _loadaware(args: Mapping, errs: List[str]) -> LoadAwareArgs:
    where = f"pluginConfig[{LOADAWARE}]"
    _check_fields(
        args,
        {
            "resourceWeights",
            "usageThresholds",
            "estimatedScalingFactors",
            "filterExpiredNodeMetrics",
            "nodeMetricExpirationSeconds",
        },
        where,
        errs,
    )
    weights = _resource_map(args.get("resourceWeights"), f"{where}.resourceWeights", errs)
    thresholds = _resource_map(
        args.get("usageThresholds"), f"{where}.usageThresholds", errs
    )
    for name, pct in thresholds.items():
        if pct > 100:
            errs.append(f"{where}.usageThresholds[{name}]: percent > 100")
    factors = _resource_map(
        args.get("estimatedScalingFactors"), f"{where}.estimatedScalingFactors", errs
    )
    for name, pct in factors.items():
        if not 0 < pct <= 100:
            errs.append(f"{where}.estimatedScalingFactors[{name}]: want (0, 100]")
    defaults = LoadAwareArgs()
    return LoadAwareArgs(
        resource_weights=weights or defaults.resource_weights,
        usage_thresholds=thresholds or defaults.usage_thresholds,
        estimated_scaling_factors=factors or defaults.estimated_scaling_factors,
        filter_expired_node_metrics=bool(
            args.get("filterExpiredNodeMetrics", defaults.filter_expired_node_metrics)
        ),
        node_metric_expiration_seconds=int(
            args.get(
                "nodeMetricExpirationSeconds",
                defaults.node_metric_expiration_seconds,
            )
        ),
    )


def _fit(args: Mapping, errs: List[str]):
    where = f"pluginConfig[{FIT}]"
    _check_fields(args, {"scoringStrategy"}, where, errs)
    strategy = args.get("scoringStrategy", {}) or {}
    stype = strategy.get("type", LEAST_ALLOCATED)
    if stype not in _STRATEGIES:
        errs.append(f"{where}.scoringStrategy.type: unknown {stype!r}")
        stype = LEAST_ALLOCATED
    weights = {}
    for e in strategy.get("resources", []) or []:
        name, w = e.get("name"), int(e.get("weight", 1))
        if name not in res.RESOURCE_INDEX:
            errs.append(f"{where}.scoringStrategy.resources: unknown {name!r}")
            continue
        if not 0 < w <= 100:
            errs.append(f"{where}.scoringStrategy.resources[{name}]: weight (0,100]")
        weights[name] = w
    return stype, weights


def load_profile(doc: Mapping[str, Any]) -> Profile:
    """Parse one profile mapping (strict: unknown plugins/fields error)."""
    errs: List[str] = []
    name = doc.get("schedulerName", "koord-scheduler")
    la = LoadAwareArgs()
    fit_strategy, fit_weights = LEAST_ALLOCATED, {res.CPU: 1, res.MEMORY: 1}
    numa = NodeNUMAResourceArgs()
    cos = CoschedulingArgs()
    eq = ElasticQuotaArgs()
    rsv = ReservationArgs()
    ds = DeviceShareArgs()
    for entry in doc.get("pluginConfig", []) or []:
        pname = entry.get("name")
        args = entry.get("args", {}) or {}
        if pname not in _KNOWN_PLUGINS:
            errs.append(f"pluginConfig: unknown plugin {pname!r}")
            continue
        if pname == LOADAWARE:
            la = _loadaware(args, errs)
        elif pname == FIT:
            fit_strategy, w = _fit(args, errs)
            fit_weights = w or fit_weights
        elif pname == NODENUMA:
            _check_fields(
                args,
                {"defaultCPUBindPolicy", "scoringStrategy", "numaScoringStrategy"},
                f"pluginConfig[{NODENUMA}]",
                errs,
            )
            numa = NodeNUMAResourceArgs(
                default_cpu_bind_policy=args.get(
                    "defaultCPUBindPolicy", numa.default_cpu_bind_policy
                ),
                scoring_strategy=args.get("scoringStrategy", numa.scoring_strategy),
                numa_scoring_strategy=args.get(
                    "numaScoringStrategy", numa.numa_scoring_strategy
                ),
            )
            if numa.default_cpu_bind_policy not in ("FullPCPUs", "SpreadByPCPUs"):
                errs.append(
                    f"pluginConfig[{NODENUMA}].defaultCPUBindPolicy: unknown "
                    f"{numa.default_cpu_bind_policy!r}"
                )
        elif pname == COSCHEDULING:
            _check_fields(
                args,
                {"defaultTimeoutSeconds", "controllerWorkers"},
                f"pluginConfig[{COSCHEDULING}]",
                errs,
            )
            cos = CoschedulingArgs(
                default_timeout_seconds=int(
                    args.get("defaultTimeoutSeconds", cos.default_timeout_seconds)
                ),
                controller_workers=int(
                    args.get("controllerWorkers", cos.controller_workers)
                ),
            )
            if cos.default_timeout_seconds <= 0:
                errs.append(
                    f"pluginConfig[{COSCHEDULING}].defaultTimeoutSeconds: want > 0"
                )
        elif pname == ELASTICQUOTA:
            _check_fields(
                args,
                {
                    "delayEvictTime",
                    "revokePodInterval",
                    "defaultQuotaGroupMax",
                    "quotaGroupNamespace",
                },
                f"pluginConfig[{ELASTICQUOTA}]",
                errs,
            )
            eq = ElasticQuotaArgs(
                delay_evict_time_seconds=int(
                    args.get("delayEvictTime", eq.delay_evict_time_seconds)
                ),
                revoke_pods_interval_seconds=int(
                    args.get("revokePodInterval", eq.revoke_pods_interval_seconds)
                ),
                default_quota_group_max=dict(args.get("defaultQuotaGroupMax", {})),
                quota_group_namespace=args.get(
                    "quotaGroupNamespace", eq.quota_group_namespace
                ),
            )
        elif pname == RESERVATION:
            _check_fields(
                args,
                {
                    "enablePreemption",
                    "minCandidateNodesPercentage",
                    "minCandidateNodesAbsolute",
                },
                f"pluginConfig[{RESERVATION}]",
                errs,
            )
            rsv = ReservationArgs(
                enable_preemption=bool(args.get("enablePreemption", rsv.enable_preemption)),
                min_candidate_nodes_percentage=int(
                    args.get(
                        "minCandidateNodesPercentage",
                        rsv.min_candidate_nodes_percentage,
                    )
                ),
                min_candidate_nodes_absolute=int(
                    args.get(
                        "minCandidateNodesAbsolute", rsv.min_candidate_nodes_absolute
                    )
                ),
            )
            if not 0 <= rsv.min_candidate_nodes_percentage <= 100:
                errs.append(
                    f"pluginConfig[{RESERVATION}].minCandidateNodesPercentage: "
                    "want [0, 100]"
                )
        elif pname == DEVICESHARE:
            _check_fields(
                args,
                {"allocateStrategy", "scoringStrategy"},
                f"pluginConfig[{DEVICESHARE}]",
                errs,
            )
            ds = DeviceShareArgs(
                allocate_strategy=args.get("allocateStrategy", ds.allocate_strategy),
                scoring_strategy=args.get("scoringStrategy", ds.scoring_strategy),
            )
    if errs:
        raise ConfigError("; ".join(errs))
    cycle = CycleConfig(
        loadaware=la,
        fit_scoring_strategy=fit_strategy,
        fit_resource_weights=fit_weights,
    )
    return Profile(
        scheduler_name=name,
        cycle=cycle,
        numa=numa,
        coscheduling=cos,
        elasticquota=eq,
        reservation=rsv,
        deviceshare=ds,
    )


def load_config(text_or_doc) -> List[Profile]:
    """Load a KubeSchedulerConfiguration-shaped YAML string or dict."""
    doc = (
        yaml.safe_load(text_or_doc)
        if isinstance(text_or_doc, (str, bytes))
        else dict(text_or_doc)
    )
    if not doc:
        return [load_profile({})]
    profiles = doc.get("profiles")
    if not profiles:
        return [load_profile(doc)]
    return [load_profile(p) for p in profiles]
