"""NUMA topology-hint merging (kubelet-style, inside the scheduler).

Behavior parity with reference ``pkg/scheduler/frameworkext/topologymanager``:
hints from providers (CPU, devices, NUMA memory) are cross-permuted, each
permutation bitwise-ANDed, and the narrowest preferred merged hint wins
(``policy.go:124 mergeFilteredHints``).  Policies none / best-effort /
restricted / single-numa-node gate admission (``policy_*.go``).

Affinity masks are plain Python ints used as bitmasks (bit i = NUMA node i),
replacing the reference's ``pkg/util/bitmask``.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
from typing import Dict, List, Mapping, Optional, Sequence, Tuple


class NUMATopologyPolicy(str, enum.Enum):
    """reference apis/extension/numa_aware.go NUMATopologyPolicy."""

    NONE = ""
    BEST_EFFORT = "BestEffort"
    RESTRICTED = "Restricted"
    SINGLE_NUMA_NODE = "SingleNUMANode"


@dataclasses.dataclass(frozen=True)
class NUMATopologyHint:
    """reference topologymanager/policy.go:34 NUMATopologyHint.

    ``affinity`` is a bitmask int, or None for "any NUMA node".
    """

    affinity: Optional[int]
    preferred: bool

    def count(self) -> int:
        return bin(self.affinity).count("1") if self.affinity is not None else 0

    def is_narrower_than(self, other: "NUMATopologyHint") -> bool:
        """bitmask.IsNarrowerThan: fewer bits set; ties broken by the
        smaller (lower) mask value."""
        a, b = self.count(), other.count()
        if a != b:
            return a < b
        return (self.affinity or 0) < (other.affinity or 0)


def _mask(nodes: Sequence[int]) -> int:
    m = 0
    for n in nodes:
        m |= 1 << n
    return m


def _filter_providers_hints(
    providers_hints: Sequence[Mapping[str, Optional[Sequence[NUMATopologyHint]]]],
) -> List[List[NUMATopologyHint]]:
    """policy.go:91 filterProvidersHints: no-preference providers/resources
    become a single preferred any-numa hint; impossible resources become a
    single non-preferred any-numa hint."""
    out: List[List[NUMATopologyHint]] = []
    for hints in providers_hints:
        if not hints:
            out.append([NUMATopologyHint(None, True)])
            continue
        for resource in hints:
            rh = hints[resource]
            if rh is None:
                out.append([NUMATopologyHint(None, True)])
            elif len(rh) == 0:
                out.append([NUMATopologyHint(None, False)])
            else:
                out.append(list(rh))
    return out


def _merge_permutation(
    default_affinity: int, permutation: Sequence[NUMATopologyHint]
) -> NUMATopologyHint:
    """policy.go:65 mergePermutation: AND of affinities; preferred iff all
    hints in the permutation are preferred."""
    preferred = all(h.preferred for h in permutation)
    merged = default_affinity
    for h in permutation:
        merged &= default_affinity if h.affinity is None else h.affinity
    return NUMATopologyHint(merged, preferred)


def _merge_filtered_hints(
    numa_nodes: Sequence[int], filtered: Sequence[Sequence[NUMATopologyHint]]
) -> NUMATopologyHint:
    """policy.go:124 mergeFilteredHints."""
    default_affinity = _mask(numa_nodes)
    best = NUMATopologyHint(default_affinity, False)
    for permutation in itertools.product(*filtered):
        merged = _merge_permutation(default_affinity, permutation)
        if merged.count() == 0:
            continue
        if merged.preferred and not best.preferred:
            best = merged
            continue
        if not merged.preferred and best.preferred:
            continue
        if not merged.is_narrower_than(best):
            continue
        best = merged
    return best


def _filter_single_numa_hints(
    filtered: Sequence[Sequence[NUMATopologyHint]],
) -> List[List[NUMATopologyHint]]:
    """policy_single_numa_node.go filterSingleNumaHints: keep preferred
    don't-cares and preferred single-node hints."""
    out: List[List[NUMATopologyHint]] = []
    for one in filtered:
        out.append(
            [
                h
                for h in one
                if h.preferred and (h.affinity is None or h.count() == 1)
            ]
        )
    return out


def merge_hints(
    policy: NUMATopologyPolicy,
    numa_nodes: Sequence[int],
    providers_hints: Sequence[Mapping[str, Optional[Sequence[NUMATopologyHint]]]],
) -> Tuple[NUMATopologyHint, bool]:
    """Merge providers' hints under ``policy``; returns (hint, admit).

    reference topologymanager/policy_none.go (always admit, empty hint),
    policy_best_effort.go (admit always), policy_restricted.go (admit iff
    preferred), policy_single_numa_node.go (single-node filter + admit iff
    preferred).
    """
    if policy == NUMATopologyPolicy.NONE:
        return NUMATopologyHint(None, True), True

    filtered = _filter_providers_hints(providers_hints)
    if policy == NUMATopologyPolicy.SINGLE_NUMA_NODE:
        filtered = _filter_single_numa_hints(filtered)
        best = _merge_filtered_hints(numa_nodes, filtered)
        # a full-machine affinity collapses to "no affinity"
        if best.affinity == _mask(numa_nodes):
            best = NUMATopologyHint(None, best.preferred)
        return best, best.preferred

    best = _merge_filtered_hints(numa_nodes, filtered)
    if policy == NUMATopologyPolicy.RESTRICTED:
        return best, best.preferred
    return best, True  # BestEffort


def generate_cpu_hints(
    cpus_by_node: Mapping[int, int], num_needed: int
) -> Dict[str, List[NUMATopologyHint]]:
    """Generate CPU hints per NUMA-node subset (reference
    ``plugins/nodenumaresource/plugin.go GetPodTopologyHints`` semantics):
    every subset of NUMA nodes whose free CPUs cover the request is a hint;
    minimal-width subsets are preferred.
    """
    nodes = sorted(cpus_by_node)
    hints: List[NUMATopologyHint] = []
    min_width = None
    for width in range(1, len(nodes) + 1):
        for combo in itertools.combinations(nodes, width):
            if sum(cpus_by_node[n] for n in combo) >= num_needed:
                if min_width is None:
                    min_width = width
                hints.append(NUMATopologyHint(_mask(combo), width == min_width))
    return {"cpu": hints}
