"""FrameworkExtender: the plugin pipeline around the batched TPU cycle.

Reference: ``pkg/scheduler/frameworkext`` — the extender wraps the upstream
framework and interposes Before/After transformers around PreFilter /
Filter / Score (``framework_extender.go:155,192,216``), adds reservation
extension points (``interface.go:110-226``), debug score tables
(``debug.go:37``, ``framework_extender.go:236``) and an error-handler
dispatcher (``errorhandler_dispatcher.go``).

TPU-first shape: every plugin contributes *tensors* — a bool ``[P, N]``
filter mask and an i64 ``[P, N]`` score — composed once per cycle into a
single jitted program (masks AND, weighted scores SUM), instead of the
reference's per-(plugin, pod, node) goroutine fan-out.  Host-side extension
points (Reserve / Permit / PreBind) run only for the solver's chosen
placements.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG
from koordinator_tpu.model.snapshot import ClusterSnapshot
from koordinator_tpu.solver.greedy import CycleResult, greedy_assign, score_cycle


@dataclasses.dataclass
class CycleContext:
    """One scheduling cycle's world state handed to every plugin.

    ``extras`` carries optional subsystem tables (ZoneBatch, ReservationTable,
    DeviceBatch, policy vectors…) keyed by name; ``state`` is the host-side
    CycleState analog (reference framework.CycleState) for cross-extension
    communication within the cycle.
    """

    snapshot: ClusterSnapshot
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG
    extras: Dict[str, object] = dataclasses.field(default_factory=dict)
    state: Dict[str, object] = dataclasses.field(default_factory=dict)


class TensorPlugin:
    """Base extended plugin (reference framework.Plugin + frameworkext
    extension interfaces).  Override any subset."""

    name = "plugin"
    weight = 1

    def filter_mask(self, ctx: CycleContext) -> Optional[jnp.ndarray]:
        """bool[P, N] admission mask, or None when not filtering."""
        return None

    def score(self, ctx: CycleContext) -> Optional[jnp.ndarray]:
        """i64[P, N] scores in [0, MAX_NODE_SCORE], or None."""
        return None

    # Host-side extension points, invoked for chosen placements only.
    def reserve(self, ctx: CycleContext, pod_idx: int, node_idx: int) -> None:
        pass

    def unreserve(self, ctx: CycleContext, pod_idx: int, node_idx: int) -> None:
        pass

    def pre_bind(
        self, ctx: CycleContext, pod_idx: int, node_idx: int
    ) -> Optional[Mapping]:
        """Return a patch fragment; DefaultPreBind merges all fragments
        into one apiserver patch (reference plugins/defaultprebind)."""
        return None


Transformer = Callable[[CycleContext], CycleContext]
ErrorHandler = Callable[[CycleContext, int, Exception], bool]


@dataclasses.dataclass
class DebugScoresTable:
    """Top-N per-plugin score table (reference frameworkext/debug.go:37)."""

    top_n: int
    rows: List[Tuple[str, List[Tuple[str, int]]]]

    def __str__(self) -> str:
        lines = []
        for plugin, pairs in self.rows:
            cells = " | ".join(f"{n}:{s}" for n, s in pairs)
            lines.append(f"{plugin:>24} | {cells}")
        return "\n".join(lines)


class FrameworkExtender:
    """Composes transformers + tensor plugins into one cycle program."""

    def __init__(
        self,
        plugins: Sequence[TensorPlugin] = (),
        *,
        before_pre_filter: Sequence[Transformer] = (),
        before_score: Sequence[Transformer] = (),
        debug_top_n: int = 0,
    ):
        self.plugins = list(plugins)
        self.before_pre_filter = list(before_pre_filter)
        self.before_score = list(before_score)
        self.debug_top_n = debug_top_n
        self.error_handlers: List[ErrorHandler] = []
        self.last_debug: Optional[DebugScoresTable] = None

    def register(self, plugin: TensorPlugin) -> None:
        self.plugins.append(plugin)

    def register_error_handler(self, handler: ErrorHandler) -> None:
        """reference errorhandler_dispatcher.go: handlers run in order until
        one claims the failure."""
        self.error_handlers.append(handler)

    # -- phases -----------------------------------------------------------

    def run_transformers(self, ctx: CycleContext) -> CycleContext:
        """BeforePreFilter transformer chain (framework_extender.go:155)."""
        for t in self.before_pre_filter:
            ctx = t(ctx)
        return ctx

    def extended_tensors(
        self, ctx: CycleContext
    ) -> Tuple[Optional[jnp.ndarray], Optional[jnp.ndarray], Dict[str, jnp.ndarray]]:
        """Collect every plugin's mask and weighted score."""
        mask = None
        total = None
        per_plugin: Dict[str, jnp.ndarray] = {}
        for t in self.before_score:
            ctx = t(ctx)
        for pl in self.plugins:
            m = pl.filter_mask(ctx)
            if m is not None:
                mask = m if mask is None else (mask & m)
            s = pl.score(ctx)
            if s is not None:
                per_plugin[pl.name] = s
                ws = pl.weight * s
                total = ws if total is None else (total + ws)
        return mask, total, per_plugin

    def run_cycle(self, ctx: CycleContext) -> CycleResult:
        """transformers -> masks+scores -> sequential greedy assignment ->
        Reserve/Permit host hooks (the reference's full cycle, §3.1)."""
        ctx = self.run_transformers(ctx)
        mask, scores, per_plugin = self.extended_tensors(ctx)
        result = greedy_assign(
            ctx.snapshot, ctx.cfg, extra_mask=mask, extra_scores=scores
        )
        if self.debug_top_n:
            self.last_debug = self._debug_table(ctx, per_plugin, result)
        assignment = np.asarray(result.assignment)
        for p in np.flatnonzero(assignment >= 0):
            node = int(assignment[p])
            try:
                for pl in self.plugins:
                    pl.reserve(ctx, int(p), node)
            except Exception as exc:  # Reserve failure unwinds (Unreserve)
                for pl in self.plugins:
                    pl.unreserve(ctx, int(p), node)
                handled = any(h(ctx, int(p), exc) for h in self.error_handlers)
                if not handled:
                    raise
        return result

    def post_filter_preempt(
        self, ctx: CycleContext, result: CycleResult
    ) -> Dict[str, object]:
        """PostFilter: quota preemption dry run for unschedulable pods
        (reference elasticquota/preempt.go via the upstream preemption
        framework).  Requires ctx.extras["preemption"] = {
          "node_allocatable": {node: dense vec},
          "node_pods": {node: [pod dicts]},
          "quota_runtime": {quota: vec}, "quota_used": {quota: vec},
          "pending_pods": [pod dicts] (each with "quota")}.
        Returns {pod_name: NodeVictims} for pods that can preempt."""
        from koordinator_tpu.constraints.quota_enforce import run_quota_preemption
        from koordinator_tpu.model import resources as res

        from koordinator_tpu.constraints.quota_manager import DEFAULT_QUOTA

        inv = ctx.extras.get("preemption")
        if not inv:
            return {}
        out: Dict[str, object] = {}
        assignment = np.asarray(result.assignment)
        for pod in inv.get("pending_pods", ()):
            # a pod holding ANY placement (assigned or gang-WAITing with
            # resources reserved) never preempts; only truly unplaced pods
            # do.  Pods without a cycle index are treated as never placed.
            idx = pod.get("index")
            if idx is not None and idx < len(assignment) and assignment[idx] >= 0:
                continue
            quota = pod.get("quota") or DEFAULT_QUOTA  # match can_preempt
            nv = run_quota_preemption(
                pod,
                inv["node_allocatable"],
                inv["node_pods"],
                inv.get("quota_used", {}).get(quota, [0] * res.NUM_RESOURCES),
                inv.get("quota_runtime", {}).get(
                    quota, [1 << 60] * res.NUM_RESOURCES
                ),
            )
            if nv is not None:
                out[pod["name"]] = nv
        return out

    def run_score_only(self, ctx: CycleContext):
        """Score-only mode for strict plugin parity checks (the reference
        seam at framework_extender.go:216)."""
        ctx = self.run_transformers(ctx)
        mask, extra, per_plugin = self.extended_tensors(ctx)
        scores, feasible = score_cycle(ctx.snapshot, ctx.cfg)
        if extra is not None:
            scores = scores + extra
        if mask is not None:
            feasible = feasible & mask
        return scores, feasible, per_plugin

    def pre_bind_patches(
        self, ctx: CycleContext, result: CycleResult
    ) -> Dict[int, Dict]:
        """DefaultPreBind: merge every plugin's patch fragments into one
        combined patch per assigned pod (reference
        plugins/defaultprebind/plugin.go)."""
        patches: Dict[int, Dict] = {}
        assignment = np.asarray(result.assignment)
        status = np.asarray(result.status)
        for p in np.flatnonzero((assignment >= 0) & (status == 0)):
            merged: Dict = {}
            for pl in self.plugins:
                frag = pl.pre_bind(ctx, int(p), int(assignment[p]))
                if frag:
                    _deep_merge(merged, frag)
            if merged:
                patches[int(p)] = merged
        return patches

    def _debug_table(
        self,
        ctx: CycleContext,
        per_plugin: Mapping[str, jnp.ndarray],
        result: CycleResult,
    ) -> DebugScoresTable:
        node_names = ctx.snapshot.nodes.names or tuple(
            f"node-{i}" for i in range(ctx.snapshot.nodes.capacity)
        )
        rows = []
        for name, scores in per_plugin.items():
            s0 = np.asarray(scores[0] if scores.ndim == 2 else scores)
            top = np.argsort(-s0)[: self.debug_top_n]
            rows.append(
                (name, [(node_names[i] if i < len(node_names) else str(i), int(s0[i])) for i in top])
            )
        return DebugScoresTable(self.debug_top_n, rows)


def _deep_merge(dst: Dict, src: Mapping) -> None:
    for k, v in src.items():
        if isinstance(v, Mapping) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v
