"""Scheduler debug/service REST API.

Reference: ``pkg/scheduler/frameworkext/services/services.go:44``
(``InstallAPIHandler`` mounts a gin engine; plugins implementing
``APIServiceProvider`` expose ``/apis/v1/plugins/<name>``; ``:104`` adds
``/apis/v1/nodes/:nodeName`` returning the cached NodeInfo).  Here the
same surface over the stdlib WSGI stack — no gin, no framework deps —
serving JSON views of the FrameworkExtender's plugin state and the
resident snapshot.
"""

from __future__ import annotations

import json
import re
import threading
from typing import Any, Callable, Dict, Mapping, Optional, Tuple
from wsgiref.simple_server import WSGIServer, make_server

import numpy as np

from koordinator_tpu.model import resources as res
from koordinator_tpu.obs.lockwitness import witness_lock

Handler = Callable[[Mapping[str, str]], Tuple[int, Any]]


class APIService:
    """Route registry: plugins register handlers under their name, the
    node endpoint reads the latest encoded snapshot."""

    def __init__(self):
        self._routes: Dict[str, Handler] = {}
        self._snapshot = None
        self._lock = witness_lock("scheduler.services.APIService._lock")

    # -- registration (APIServiceProvider.RegisterEndpoints analog) --
    def register_plugin(self, plugin_name: str, path: str, handler: Handler) -> None:
        with self._lock:
            self._routes[f"/apis/v1/plugins/{plugin_name}/{path.strip('/')}"] = handler

    def set_snapshot(self, snapshot) -> None:
        with self._lock:
            self._snapshot = snapshot

    # -- views --
    def _node_view(self, name: str) -> Tuple[int, Any]:
        snap = self._snapshot
        if snap is None:
            return 503, {"error": "no snapshot synced"}
        names = list(snap.nodes.names)
        if name not in names:
            return 404, {"error": f"node {name} not found"}
        i = names.index(name)

        def vec(arr):
            row = np.asarray(arr)[i]
            return {
                res.RESOURCE_AXIS[j]: int(v) for j, v in enumerate(row) if v
            }

        return 200, {
            "name": name,
            "allocatable": vec(snap.nodes.allocatable),
            "requested": vec(snap.nodes.requested),
            "usage": vec(snap.nodes.usage),
            "metricFresh": bool(np.asarray(snap.nodes.metric_fresh)[i]),
        }

    def dispatch(self, path: str, query: Mapping[str, str]) -> Tuple[int, Any]:
        m = re.fullmatch(r"/apis/v1/nodes/([^/]+)", path)
        if m:
            return self._node_view(m.group(1))
        with self._lock:
            handler = self._routes.get(path)
        if handler is None:
            if path == "/apis/v1/plugins":
                with self._lock:
                    return 200, sorted(self._routes)
            return 404, {"error": f"no route {path}"}
        return handler(query)

    # -- WSGI --
    def wsgi_app(self, environ, start_response):
        path = environ.get("PATH_INFO", "/")
        query = dict(
            pair.split("=", 1)
            for pair in environ.get("QUERY_STRING", "").split("&")
            if "=" in pair
        )
        try:
            status, body = self.dispatch(path, query)
        except Exception as exc:  # handler bug -> 500, never kill the server
            status, body = 500, {"error": str(exc)}
        payload = json.dumps(body).encode()
        reasons = {200: "OK", 404: "Not Found", 500: "Internal", 503: "Unavailable"}
        start_response(
            f"{status} {reasons.get(status, 'Status')}",
            [("Content-Type", "application/json"),
             ("Content-Length", str(len(payload)))],
        )
        return [payload]

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> WSGIServer:
        server = make_server(host, port, self.wsgi_app)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server


def install_framework_endpoints(api: APIService, extender) -> None:
    """Mount the FrameworkExtender's debug state the way debug.go:32 and
    services.go:82 expose score tables and plugin internals."""

    def debug_scores(_q) -> Tuple[int, Any]:
        table = getattr(extender, "last_debug", None)
        return 200, {
            "scores": (
                None
                if table is None
                else (table.rows if hasattr(table, "rows") else table)
            ),
            "debug_top_n": extender.debug_top_n,
        }

    def set_debug_scores(q) -> Tuple[int, Any]:
        # runtime setter on its OWN route (reference debug.go:32-51: the
        # -debug-scores flag has live setters, not just a startup value);
        # the reader above stays a pure view so scrapes cannot mutate
        if "top_n" not in q:
            return 400, {"error": "missing top_n"}
        try:
            extender.debug_top_n = max(0, int(q["top_n"]))
        except ValueError:
            return 400, {"error": f"bad top_n {q['top_n']!r}"}
        if extender.debug_top_n == 0:
            # disabling must not leave a stale table served as live data
            extender.last_debug = None
        return 200, {"debug_top_n": extender.debug_top_n}

    def plugins_list(_q) -> Tuple[int, Any]:
        return 200, [p.name for p in extender.plugins]

    api.register_plugin("frameworkext", "debug-scores", debug_scores)
    api.register_plugin("frameworkext", "set-debug-scores", set_debug_scores)
    api.register_plugin("frameworkext", "plugins", plugins_list)
