"""koordlint rule: ``metrics-doc-drift`` (ISSUE 12).

The ``koord_scorer_*`` family table in ``docs/OBSERVABILITY.md`` is the
operator contract — dashboards, alert rules and the SLO-gate runbooks
are written against it.  Eleven PRs of family growth have kept it in
sync by review discipline alone; this rule makes the sync STATIC, the
wire-contract shape applied to observability: the families registered
in ``obs/scorer_metrics.py`` (the ``_FAMILIES`` table, names resolved
through the module-level constants) are diffed against the markdown
table's rows, in BOTH directions, with the declared kind
(counter/gauge/histogram) cross-checked.

* a family registered but absent from the doc table flags the
  ``_FAMILIES`` entry's line (the metric shipped undocumented — no
  operator will ever alert on it);
* a table row naming a family that is not registered flags the doc
  line (the doc promises a series the daemon never exports — a
  dashboard of NaNs);
* a kind mismatch flags the doc line (a histogram documented as a
  gauge breaks every ``_bucket``/``_count`` query written from it).

All diff functions take source TEXT so tests can seed one-sided
regressions (the wire-contract convention); ``check_repo`` reads the
two real files.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.analysis.core import Violation

RULE = "metrics-doc-drift"

PY_PATH = os.path.join("koordinator_tpu", "obs", "scorer_metrics.py")
MD_PATH = os.path.join("docs", "OBSERVABILITY.md")

_PREFIX = "koord_scorer_"
_KINDS = ("counter", "gauge", "histogram")

# one markdown table row: | `koord_scorer_x` | kind | ... (the family
# reference table in docs/OBSERVABILITY.md)
_MD_ROW_RE = re.compile(
    r"^\|\s*`(" + _PREFIX + r"\w+)`\s*\|\s*(\w+)\s*\|"
)


def parse_registered_families(
    py_text: str,
) -> List[Tuple[str, str, int]]:
    """``(family_name, kind, line)`` for every ``_FAMILIES`` entry in
    obs/scorer_metrics.py source text.  Entry names may be module-level
    string constants (the convention) or inline string literals."""
    tree = ast.parse(py_text)
    consts: Dict[str, str] = {}
    families_node: Optional[ast.AST] = None
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if isinstance(node.value, ast.Constant) and isinstance(
            node.value.value, str
        ):
            consts[target.id] = node.value.value
        elif target.id == "_FAMILIES":
            families_node = node.value
    out: List[Tuple[str, str, int]] = []
    if not isinstance(families_node, (ast.Tuple, ast.List)):
        return out
    for entry in families_node.elts:
        if not isinstance(entry, (ast.Tuple, ast.List)) or len(entry.elts) < 2:
            continue
        name_node, kind_node = entry.elts[0], entry.elts[1]
        if isinstance(name_node, ast.Name):
            name = consts.get(name_node.id)
        elif isinstance(name_node, ast.Constant) and isinstance(
            name_node.value, str
        ):
            name = name_node.value
        else:
            name = None
        kind = (
            kind_node.value
            if isinstance(kind_node, ast.Constant)
            and isinstance(kind_node.value, str)
            else None
        )
        if name and kind:
            out.append((name, kind, entry.lineno))
    return out


def parse_documented_families(md_text: str) -> List[Tuple[str, str, int]]:
    """``(family_name, kind, line)`` for every ``koord_scorer_*`` row of
    the markdown family table."""
    out: List[Tuple[str, str, int]] = []
    for lineno, line in enumerate(md_text.splitlines(), start=1):
        m = _MD_ROW_RE.match(line.strip())
        if m:
            out.append((m.group(1), m.group(2), lineno))
    return out


def diff_metrics_doc(
    py_text: str,
    md_text: str,
    py_path: str = PY_PATH,
    md_path: str = MD_PATH,
) -> List[Violation]:
    registered = parse_registered_families(py_text)
    documented = parse_documented_families(md_text)
    if not registered:
        return [Violation(
            RULE, py_path, 0,
            "no _FAMILIES entries parsed from the scorer metrics module "
            "— the registration table moved; update metricsdoc.py's "
            "parser with it",
        )]
    if not documented:
        return [Violation(
            RULE, md_path, 0,
            "no koord_scorer_* rows parsed from the family table — the "
            "doc table moved or was deleted; the operator contract must "
            "stay diffable",
        )]
    out: List[Violation] = []
    doc_by_name = {name: (kind, line) for name, kind, line in documented}
    reg_by_name = {name: (kind, line) for name, kind, line in registered}
    for name, kind, line in registered:
        doc = doc_by_name.get(name)
        if doc is None:
            out.append(Violation(
                RULE, py_path, line,
                f"family {name!r} ({kind}) is registered but missing "
                f"from the {md_path} family table — an undocumented "
                "metric is invisible to every dashboard and alert rule",
            ))
        elif doc[0] != kind:
            out.append(Violation(
                RULE, md_path, doc[1],
                f"family {name!r} documented as {doc[0]!r} but "
                f"registered as {kind!r} — _bucket/_count queries "
                "written from the doc would break",
            ))
    for name, kind, line in documented:
        if kind not in _KINDS:
            out.append(Violation(
                RULE, md_path, line,
                f"family {name!r} documents unknown kind {kind!r} "
                f"(expected one of {', '.join(_KINDS)})",
            ))
        if name not in reg_by_name:
            out.append(Violation(
                RULE, md_path, line,
                f"family {name!r} is documented but never registered in "
                f"{py_path} — the doc promises a series the daemon does "
                "not export",
            ))
    return out


def check_repo(root: str) -> List[Violation]:
    def read(rel: str) -> Optional[str]:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            return None
        with open(path, "r", encoding="utf-8") as f:
            return f.read()

    py_text = read(PY_PATH)
    if py_text is None:
        return [Violation(RULE, PY_PATH, 0, "scorer_metrics.py not found")]
    md_text = read(MD_PATH)
    if md_text is None:
        return [Violation(
            RULE, MD_PATH, 0,
            "docs/OBSERVABILITY.md not found — the family table is the "
            "operator contract the registered metrics diff against",
        )]
    return diff_metrics_doc(py_text, md_text)
