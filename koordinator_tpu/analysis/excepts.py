"""broad-except: no silently swallowed errors.

A ``except Exception:`` (or bare ``except:`` / ``except BaseException:``)
handler passes when it demonstrably surfaces the failure:

* it re-raises (``raise`` anywhere in the handler body), or
* it logs (a call to ``log``/``logger``/``logging`` machinery, incl.
  ``.exception()``/``.error()``/…), or
* it binds the exception (``as exc``) and actually USES the bound name —
  building a 500 body, an error reply, an errs list all count, or
* the ``except`` line carries
  ``# koordlint: disable=broad-except(<reason>)``.

Anything else swallows the error with no trace — the class of handler
that turned PR-1 device faults into silent cold-path demotions.
"""

from __future__ import annotations

import ast
from typing import List

from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "broad-except"

_LOG_ATTRS = ("exception", "error", "warning", "warn", "info", "debug",
              "log", "critical", "fatal")
_LOG_ROOTS = ("log", "logger", "logging")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in ("Exception", "BaseException")
            for e in t.elts
        )
    return False


def _surfaces(handler: ast.ExceptHandler) -> bool:
    bound = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if bound and node.id == bound:
                return True
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _LOG_ATTRS:
                root = fn.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Call):
                    # logging.getLogger(...).exception(...)
                    return True
                if isinstance(root, ast.Name) and (
                    root.id in _LOG_ROOTS or root.id.startswith("log")
                ):
                    return True
        # sys.exc_info() / traceback use also surfaces
        if isinstance(node, ast.Attribute) and node.attr == "exc_info":
            return True
    return False


def check(source: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(source.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if _surfaces(node):
            continue
        out.append(
            Violation(
                rule=RULE,
                path=source.path,
                line=node.lineno,
                message=(
                    "broad except swallows the error silently: re-raise, "
                    "log it, surface the bound exception, or tag with "
                    "# koordlint: disable=broad-except(<reason>)"
                ),
            )
        )
    return out
