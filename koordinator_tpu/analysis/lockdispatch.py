"""lock-held-dispatch: blocking device readbacks under a state lock.

The coalescing dispatch engine (ISSUE 5, bridge/server.py) exists
because the daemon once held ONE servicer lock across every RPC body —
including the device dispatch and the blocking ``np.asarray`` readback,
so sixteen parallel Score workers queued single-file behind a single
transfer.  The refactor's invariant is lexical and therefore checkable:
a ``with <...state lock...>:`` block must never contain a blocking
device->host transfer (``np.asarray``/``np.array``/``np.copy`` on
device values, ``.item()``, ``.block_until_ready()``,
``jax.device_get``).  Capture references under the lock; launch and
read back outside it (the device-dispatch queue serializes launches).

Scope: with-blocks whose context expression's terminal attribute names
a state/servicer lock (``_state_lock``, ``state_lock``,
``_servicer_lock``, or a bare ``_lock`` — the pre-split servicer's
spelling).  Nested function *definitions* inside the block are skipped:
a closure defined under the lock does not run under it.  Host-only
registries that guard plain dict/list state under a ``_lock`` never
trip the rule because they perform no device readbacks; a with-block
that legitimately must read back under a lock (none should) can carry
``# koordlint: disable=lock-held-dispatch``.

ISSUE 6 extends the rule to the **pipeline seam**: the dispatcher's
launch critical section (functions carrying the
``@launch_section`` decorator from bridge/coalesce.py, and with-blocks
on a ``*_launch_lock``) must only capture state and dispatch device
work asynchronously — a blocking ``device_get``/``block_until_ready``
inside it stalls every queued launch exactly the way the old single
lock did, un-pipelining the engine silently.  Nested defs inside a
launch-section function are exempt: that is precisely where the
readback closure (the only code allowed to block) lives.  The shard
path's materialize-inside-the-demotion-guard transfers carry reasoned
per-line suppressions.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "lock-held-dispatch"

_NP_MODULES = ("np", "numpy", "onp", "_np")
_NP_SYNC_FUNCS = ("asarray", "array", "copy")
_JAX_MODULES = ("jax",)
_LOCK_NAMES = ("_state_lock", "state_lock", "_servicer_lock", "_lock")
# the pipelined dispatcher's launch critical section (ISSUE 6)
_LAUNCH_LOCK_NAMES = ("_launch_lock", "launch_lock")
_LAUNCH_DECORATOR = "launch_section"


def _terminal_name(node: ast.AST) -> str:
    """The last segment of a Name/Attribute chain (``self.x._lock`` ->
    "_lock"); '' for anything else (calls like ``maybe_span(...)``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _root_module(node: ast.AST) -> str:
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_state_lock_with(node: ast.With) -> bool:
    return any(
        _terminal_name(item.context_expr) in _LOCK_NAMES
        for item in node.items
    )


def _is_launch_lock_with(node: ast.With) -> bool:
    return any(
        _terminal_name(item.context_expr) in _LAUNCH_LOCK_NAMES
        for item in node.items
    )


def _is_launch_section_def(node: ast.AST) -> bool:
    """A function carrying the ``@launch_section`` marker (bare name or
    attribute form, e.g. ``@coalesce.launch_section``) runs under the
    dispatcher's launch lock."""
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    return any(
        _terminal_name(dec) == _LAUNCH_DECORATOR
        for dec in node.decorator_list
    )


def _walk_skip_defs(nodes) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class
    definitions (a closure defined under the lock runs elsewhere)."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                   ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _blocking_call(sub: ast.AST) -> str:
    """Name of the blocking device->host transfer this Call performs,
    or '' for anything else."""
    if not isinstance(sub, ast.Call):
        return ""
    fn = sub.func
    if isinstance(fn, ast.Attribute) and (
        _root_module(fn) in _NP_MODULES and fn.attr in _NP_SYNC_FUNCS
    ):
        return f"np.{fn.attr}()"
    if isinstance(fn, ast.Attribute) and fn.attr == "item":
        return ".item()"
    if isinstance(fn, ast.Attribute) and fn.attr == "block_until_ready":
        return ".block_until_ready()"
    if isinstance(fn, ast.Attribute) and (
        _root_module(fn) in _JAX_MODULES and fn.attr == "device_get"
    ):
        return "jax.device_get()"
    return ""


_STATE_MSG = (
    "{flagged} while the servicer state lock is held serializes every "
    "RPC behind one device->host transfer; capture references under "
    "the lock and read back outside it (the device-dispatch queue "
    "orders launches)"
)
_LAUNCH_MSG = (
    "{flagged} inside the dispatcher's launch critical section stalls "
    "every queued launch behind one device->host transfer — the "
    "pipeline un-pipelines silently; launch sections capture + "
    "dispatch asynchronously, only the readback closure (a nested "
    "def, exempt) may block"
)


def check(source: SourceFile) -> List[Violation]:
    # a blocking call can sit under BOTH scopes at once (a state-lock
    # with-block nested inside a launch-section def); one flagged line
    # is one violation, so dedup on (path, line) keeping the first
    # (outermost) scope's message
    out: List[Violation] = []
    seen: set = set()
    for node in ast.walk(source.tree):
        if isinstance(node, ast.With) and _is_state_lock_with(node):
            body, msg = node.body, _STATE_MSG
        elif isinstance(node, ast.With) and _is_launch_lock_with(node):
            body, msg = node.body, _LAUNCH_MSG
        elif _is_launch_section_def(node):
            body, msg = node.body, _LAUNCH_MSG
        else:
            continue
        for sub in _walk_skip_defs(body):
            flagged = _blocking_call(sub)
            if flagged:
                key = (source.path, sub.lineno)
                if key in seen:
                    continue
                seen.add(key)
                out.append(
                    Violation(
                        rule=RULE,
                        path=source.path,
                        line=sub.lineno,
                        message=msg.format(flagged=flagged),
                    )
                )
    return out
