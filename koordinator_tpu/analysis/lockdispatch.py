"""lock-held-dispatch: blocking device readbacks under a state lock.

The coalescing dispatch engine (ISSUE 5, bridge/server.py) exists
because the daemon once held ONE servicer lock across every RPC body —
including the device dispatch and the blocking ``np.asarray`` readback,
so sixteen parallel Score workers queued single-file behind a single
transfer.  The refactor's invariant is lexical and therefore checkable:
a ``with <...state lock...>:`` block must never contain a blocking
device->host transfer (``np.asarray``/``np.array``/``np.copy`` on
device values, ``.item()``, ``.block_until_ready()``,
``jax.device_get``).  Capture references under the lock; launch and
read back outside it (the device-dispatch queue serializes launches).

Scope: with-blocks whose context expression's terminal attribute names
a state/servicer lock (``_state_lock``, ``state_lock``,
``_servicer_lock``, or a bare ``_lock`` — the pre-split servicer's
spelling).  Nested function *definitions* inside the block are skipped:
a closure defined under the lock does not run under it.  Host-only
registries that guard plain dict/list state under a ``_lock`` never
trip the rule because they perform no device readbacks; a with-block
that legitimately must read back under a lock (none should) can carry
``# koordlint: disable=lock-held-dispatch``.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "lock-held-dispatch"

_NP_MODULES = ("np", "numpy", "onp", "_np")
_NP_SYNC_FUNCS = ("asarray", "array", "copy")
_JAX_MODULES = ("jax",)
_LOCK_NAMES = ("_state_lock", "state_lock", "_servicer_lock", "_lock")


def _terminal_name(node: ast.AST) -> str:
    """The last segment of a Name/Attribute chain (``self.x._lock`` ->
    "_lock"); '' for anything else (calls like ``maybe_span(...)``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _root_module(node: ast.AST) -> str:
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_state_lock_with(node: ast.With) -> bool:
    return any(
        _terminal_name(item.context_expr) in _LOCK_NAMES
        for item in node.items
    )


def _walk_skip_defs(nodes) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class
    definitions (a closure defined under the lock runs elsewhere)."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                   ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def check(source: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(source.tree):
        if not isinstance(node, ast.With) or not _is_state_lock_with(node):
            continue
        for sub in _walk_skip_defs(node.body):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            flagged = None
            if isinstance(fn, ast.Attribute) and (
                _root_module(fn) in _NP_MODULES
                and fn.attr in _NP_SYNC_FUNCS
            ):
                flagged = f"np.{fn.attr}()"
            elif isinstance(fn, ast.Attribute) and fn.attr == "item":
                flagged = ".item()"
            elif isinstance(fn, ast.Attribute) and (
                fn.attr == "block_until_ready"
            ):
                flagged = ".block_until_ready()"
            elif isinstance(fn, ast.Attribute) and (
                _root_module(fn) in _JAX_MODULES
                and fn.attr == "device_get"
            ):
                flagged = "jax.device_get()"
            if flagged is not None:
                out.append(
                    Violation(
                        rule=RULE,
                        path=source.path,
                        line=sub.lineno,
                        message=(
                            f"{flagged} while the servicer state lock "
                            "is held serializes every RPC behind one "
                            "device->host transfer; capture references "
                            "under the lock and read back outside it "
                            "(the device-dispatch queue orders launches)"
                        ),
                    )
                )
    return out
