"""Shared koordlint infrastructure: violations, suppressions, the runner.

Suppression syntax (line-scoped, on the offending line or the line just
above it):

    risky_thing()  # koordlint: disable=retrace-hazard
    # koordlint: disable=broad-except(reason: probe must never raise)
    except Exception:

Multiple rules separate with commas; an optional parenthesised reason is
encouraged (and REQUIRED by review convention for broad-except).  Tags
never suppress whole files — a blanket-suppressed file would hide new
regressions behind an old annotation.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set

# '#' for Python, '//' for Go sources (wire-contract tags live in wire.go)
_DISABLE_RE = re.compile(r"(?:#|//)\s*koordlint:\s*disable=(.*)$")
# one rule token: name, optional (reason), flexible whitespace
_RULE_TOKEN_RE = re.compile(r"\s*([a-z0-9\-]+)\s*(\([^)]*\))?\s*")


def _parse_rule_list(tail: str) -> Set[str]:
    """Strict sequential tokenizer: rule[,rule...] with optional
    parenthesised reasons.  Scanning STOPS at the first non-token text,
    so words inside a reason (or trailing prose) can never leak into
    the suppressed-rule set."""
    rules: Set[str] = set()
    i = 0
    while i < len(tail):
        m = _RULE_TOKEN_RE.match(tail, i)
        if not m or not m.group(1):
            break
        rules.add(m.group(1))
        i = m.end()
        if i < len(tail) and tail[i] == ",":
            i += 1
        else:
            break
    return rules


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def parse_suppressions(text: str, lang: str = "python") -> Dict[int, Set[str]]:
    """Map line number -> set of rule names disabled on that line.

    For Python sources the tags are extracted from REAL comment tokens
    (via tokenize), so a string literal or docstring that merely
    mentions ``koordlint: disable=`` — the rule messages themselves do —
    can never register a phantom suppression.  Non-Python sources (Go,
    for wire-contract tags) fall back to a per-line regex."""
    out: Dict[int, Set[str]] = {}

    def record(lineno: int, comment: str) -> None:
        m = _DISABLE_RE.search(comment)
        if m:
            rules = _parse_rule_list(m.group(1))
            if rules:
                out[lineno] = rules

    if lang == "python":
        import io
        import tokenize

        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    record(tok.start[0], tok.string)
            return out
        except (tokenize.TokenError, IndentationError, SyntaxError):
            out.clear()  # unparseable: per-line fallback below
    for lineno, line in enumerate(text.splitlines(), start=1):
        record(lineno, line)
    return out


class SourceFile:
    """One parsed Python file handed to every AST rule."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.suppressions = parse_suppressions(text)

    def suppressed(self, rule: str, line: int) -> bool:
        # the offending line, or a dedicated comment line just above it
        for at in (line, line - 1):
            if rule in self.suppressions.get(at, ()):
                return True
        return False


def _filter(source: SourceFile, violations: Iterable[Violation]) -> List[Violation]:
    return [
        v for v in violations if not source.suppressed(v.rule, v.line)
    ]


def run_rules_on_source(
    path: str, text: str, rules: Optional[Sequence[str]] = None,
    honor_suppressions: bool = True,
) -> List[Violation]:
    """Run the AST rules over one file's source text (the unit-test seam:
    seeded-regression fixtures feed synthetic sources through here).
    ``honor_suppressions=False`` returns the RAW findings — the
    suppression audit diffs them against the live tags to spot stale
    annotations."""
    from koordinator_tpu.analysis import (
        bareretry,
        devbound,
        donation,
        excepts,
        guards,
        hostsync,
        lockdispatch,
        retrace,
        spanleak,
        unboundedwait,
    )

    try:
        source = SourceFile(path, text)
    except SyntaxError as exc:
        return [
            Violation(
                rule="parse-error",
                path=path,
                line=exc.lineno or 0,
                message=f"cannot parse: {exc.msg}",
            )
        ]
    out: List[Violation] = []
    table = {
        "donation-safety": donation.check,
        "retrace-hazard": retrace.check,
        "host-sync-in-jit": hostsync.check,
        "broad-except": excepts.check,
        "span-leak": spanleak.check,
        "lock-held-dispatch": lockdispatch.check,
        "bare-retry": bareretry.check,
        "unbounded-wait": unboundedwait.check,
        "unguarded-shared-state": guards.check,
        "unregistered-jit-boundary": devbound.check,
    }
    for rule, fn in table.items():
        if rules is not None and rule not in rules:
            continue
        found = fn(source)
        out.extend(found if not honor_suppressions
                   else _filter(source, found))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def iter_python_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames
            if d != "__pycache__" and not d.startswith(".")
        ]
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def find_repo_root(start: Optional[str] = None) -> str:
    """Nearest ancestor containing the koordinator_tpu package."""
    here = os.path.abspath(start or os.getcwd())
    probe = here
    while True:
        if os.path.isdir(os.path.join(probe, "koordinator_tpu")):
            return probe
        parent = os.path.dirname(probe)
        if parent == probe:
            return here
        probe = parent


def run_repo(
    root: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    wire: bool = True,
    honor_suppressions: bool = True,
) -> List[Violation]:
    """The full pass: AST rules over every repo Python file plus the
    cross-language wire-contract diff, the metrics-vs-doc table diff
    and the whole-program lock-order graph (cycles + LOCKORDER.md
    drift).  Returns sorted violations."""
    from koordinator_tpu.analysis import (
        lockgraph,
        metricsdoc,
        prewarmdrift,
        wire_contract,
    )

    root = root or find_repo_root(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    scan_roots = [os.path.join(root, "koordinator_tpu")]
    extra_files = [os.path.join(root, "bench.py")]
    out: List[Violation] = []
    for scan_root in scan_roots:
        if not os.path.isdir(scan_root):
            continue
        for path in iter_python_files(scan_root):
            out.extend(_run_file(path, root, rules, honor_suppressions))
    for path in extra_files:
        if os.path.exists(path):
            out.extend(_run_file(path, root, rules, honor_suppressions))
    if wire and (rules is None or "wire-contract" in rules):
        out.extend(_filter_file_comments(
            root, wire_contract.check_repo(root), honor_suppressions))
    if rules is None or "metrics-doc-drift" in rules:
        out.extend(_filter_file_comments(
            root, metricsdoc.check_repo(root), honor_suppressions))
    if rules is None or "prewarm-drift" in rules:
        out.extend(_filter_file_comments(
            root, prewarmdrift.check_repo(root), honor_suppressions))
    if rules is None or {lockgraph.CYCLE_RULE, lockgraph.DRIFT_RULE} & set(rules):
        found = [
            v for v in lockgraph.check_repo(root)
            if rules is None or v.rule in rules
        ]
        out.extend(_filter_file_comments(root, found, honor_suppressions))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def _filter_file_comments(
    root: str, violations: Iterable[Violation],
    honor_suppressions: bool = True,
) -> List[Violation]:
    """Line-suppression for the repo-wide rules (wire-contract points at
    Go sources, the lock-graph rules at Python ones): honor
    ``// koordlint: disable=<rule>`` / ``# koordlint: ...`` on the
    flagged line or the line above.  Line-0 violations (message-level
    drift like a never-emitted field, a stale pb2 regen or a stale
    generated doc) are deliberately NOT suppressible — the fix there is
    the wire edit or a regen, and the ``_ALLOWED_UNDECODED`` allowlist
    covers legitimate one-sided reads."""
    if not honor_suppressions:
        return list(violations)
    cache: Dict[str, Dict[int, Set[str]]] = {}
    out: List[Violation] = []
    for v in violations:
        if v.line > 0:
            if v.path not in cache:
                path = os.path.join(root, v.path)
                lang = "python" if v.path.endswith(".py") else "go"
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        cache[v.path] = parse_suppressions(f.read(), lang=lang)
                except OSError:
                    cache[v.path] = {}
            sups = cache[v.path]
            if any(
                v.rule in sups.get(at, ()) for at in (v.line, v.line - 1)
            ):
                continue
        out.append(v)
    return out


def _run_file(path: str, root: str, rules: Optional[Sequence[str]],
              honor_suppressions: bool = True) -> List[Violation]:
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    rel = os.path.relpath(path, root)
    return run_rules_on_source(rel, text, rules, honor_suppressions)
