"""Runtime companion: assert warm cycles stay inside a retrace budget.

The static pass catches the *sources* of retraces; this guard locks the
*outcome* in at test time: wrap a warm delta-Sync/Assign sequence in
``retrace_guard(budget=0)`` and any jit cache miss inside the block —
a retrace from leaked static metadata, a bucket that failed to stick, a
geometry wobble — fails the test with the observed counts.

Counting: jax's monitoring bus records a
``/jax/core/compile/jaxpr_trace_duration`` event for every trace and a
``.../backend_compile_duration`` event for every XLA compile.  A single
logical cache miss can record more than one trace event (nested
jaxprs), so budgets are exact only at 0 — which is precisely the warm
path's contract.  Trace events are the primary signal: a retrace that
hits the persistent compile cache skips the backend compile but still
re-traces (and still pays trace time on the hot path).
"""

from __future__ import annotations

import contextlib
from typing import Iterator

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class RetraceBudgetExceeded(AssertionError):
    pass


class RetraceCounter:
    """Counts jit traces/compiles between ``start()`` and ``stop()``."""

    def __init__(self):
        self.traces = 0
        self.compiles = 0
        self._active = False

    # registered once per guard; the _active flag makes the callback a
    # no-op outside the with-block even if unregistration is unavailable
    def _on_event(self, name: str, *args, **kw) -> None:
        if not self._active:
            return
        if name == _TRACE_EVENT:
            self.traces += 1
        elif name == _COMPILE_EVENT:
            self.compiles += 1

    def start(self) -> None:
        from jax._src import monitoring

        monitoring.register_event_duration_secs_listener(self._on_event)
        self._active = True

    def stop(self) -> None:
        self._active = False
        _unregister_listener(self._on_event)


def _unregister_listener(fn) -> None:
    """Drift-tolerant removal of a jax monitoring-bus listener — a
    long-lived process must not silently accumulate no-op listeners."""
    from jax._src import monitoring

    unregister = getattr(
        monitoring, "_unregister_event_duration_listener_by_callback", None
    )
    if unregister is not None:
        unregister(fn)
        return
    # private-API drift fallback: unhook by hand, or at least warn
    listeners = getattr(monitoring, "_event_duration_secs_listeners", None)
    if isinstance(listeners, list) and fn in listeners:
        listeners.remove(fn)
        return
    import warnings

    warnings.warn(
        "retrace_guard could not unregister its jax monitoring "
        "listener (private API drift); it remains registered as a "
        "no-op for this process",
        RuntimeWarning,
        stacklevel=2,
    )


def watch_cache_misses(callback) -> "callable":
    """Register a PERSISTENT jit cache-miss listener (the obs metric
    families' feed): ``callback(kind)`` fires with ``"trace"`` per jaxpr
    trace and ``"compile"`` per backend compile, for the life of the
    process or until the returned unhook callable is invoked.

    Unlike :func:`retrace_guard` (a scoped assertion for tests), this is
    the serving-path counter: the bridge daemon exports the counts as
    ``koord_scorer_jit_cache_miss_total`` so a warm stream that starts
    retracing is visible on /metrics, not only in a failed test.  The
    callback runs on whatever thread jax traces on — keep it to a
    counter bump."""
    from jax._src import monitoring

    def _on_event(name: str, *args, **kw) -> None:
        if name == _TRACE_EVENT:
            callback("trace")
        elif name == _COMPILE_EVENT:
            callback("compile")

    monitoring.register_event_duration_secs_listener(_on_event)

    def unhook() -> None:
        _unregister_listener(_on_event)

    return unhook


@contextlib.contextmanager
def retrace_guard(budget: int = 0) -> Iterator[RetraceCounter]:
    """Fail with :class:`RetraceBudgetExceeded` when more than ``budget``
    jit traces happen inside the block.

    The budget is over TRACE events (cache misses); ``counter.compiles``
    additionally reports how many reached XLA.  Warm up every shape the
    block will touch before entering — the guard asserts steady state,
    not first-touch compilation.
    """
    counter = RetraceCounter()
    counter.start()
    try:
        yield counter
    finally:
        counter.stop()
    if counter.traces > budget:
        raise RetraceBudgetExceeded(
            f"retrace budget exceeded: {counter.traces} jit trace(s) "
            f"({counter.compiles} backend compile(s)) inside a "
            f"budget-{budget} block — warm-path shapes/static metadata "
            "changed mid-stream"
        )
