"""host-sync-in-jit: device->host round trips inside jitted functions.

A ``np.asarray``/``.item()``/``float()``/``int()`` on a jnp value inside
a jitted function forces a concretization during trace — either a tracer
error or, through weak-type escape hatches, a silent per-call host sync
that turns the single-device program into a ping-pong (the tunneled
backend pays ~68 ms per round trip; see bench.py's rtt_floor).  A bare
``print()`` traces once and then never runs again — debugging that
"works" until the cache warms; ``jax.debug.print`` is the traced form.

Scope: bodies of jitted functions (decorator or partial spelling),
excluding nested non-jitted closures only when they are themselves
jit-wrapped.  ``int()``/``float()`` are flagged only when applied to an
obvious jnp/jax expression — ``int(shape[0])`` and enum coercions are
host-side constants and stay legal.

The obs span API (koordinator_tpu/obs/spans.py) is covered too: a
``begin_span``/``end_span``/``.span()``/``.note()`` inside jitted code
would record trace-time wall clock ONCE and then never run again (the
bare-print trap), and a note of a live tracer value would force a
concretization.  Telemetry instruments around device programs, never
inside them — that is the subsystem's zero-overhead contract
(tests/test_resident_warm.py locks it in at zero jit cache misses).
"""

from __future__ import annotations

import ast
from typing import List

from koordinator_tpu.analysis import jitscope
from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "host-sync-in-jit"

_NP_MODULES = ("np", "numpy", "onp", "_np")
_JNP_MODULES = ("jnp", "jax")
_NP_SYNC_FUNCS = ("asarray", "array", "copy")
# obs span API: begin/end are unambiguous names; span/note/commit only
# count on a receiver that is recognizably the telemetry/span recorder
_OBS_METHODS = ("begin_span", "end_span")
_OBS_RECEIVERS = ("obs", "spans", "telemetry", "recorder", "span_recorder")
_OBS_RECEIVER_METHODS = ("span", "note", "commit", "commit_cycle")


def _root_module(node: ast.AST) -> str:
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _attr_chain(node: ast.AST):
    """All names along an attribute chain: ``self.telemetry.spans.note``
    -> ("self", "telemetry", "spans", "note")."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return tuple(reversed(parts))


def _mentions_jnp(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in _JNP_MODULES:
            return True
    return False


def check(source: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    for spec in jitscope.jitted_defs(source.tree):
        # closures (lax.scan step fns) run under this trace and are
        # scanned; nested JITTED defs get their own pass — descending
        # into them here would double-report their bodies
        for node in jitscope.scope_walk(spec.func, into_closures=True):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            # np.asarray / np.array / np.copy on anything
            if isinstance(fn, ast.Attribute) and (
                _root_module(fn) in _NP_MODULES
                and fn.attr in _NP_SYNC_FUNCS
            ):
                out.append(
                    Violation(
                        rule=RULE,
                        path=source.path,
                        line=node.lineno,
                        message=(
                            f"np.{fn.attr}() inside jitted {spec.name}() "
                            "forces a device->host sync per call; use "
                            "jnp equivalents, or hoist to the caller"
                        ),
                    )
                )
            # .item() on anything
            elif isinstance(fn, ast.Attribute) and fn.attr == "item":
                out.append(
                    Violation(
                        rule=RULE,
                        path=source.path,
                        line=node.lineno,
                        message=(
                            f".item() inside jitted {spec.name}() is a "
                            "host sync; keep the value on device"
                        ),
                    )
                )
            # float()/int() over an expression that touches jnp/jax
            elif (
                isinstance(fn, ast.Name)
                and fn.id in ("float", "int", "bool")
                and node.args
                and _mentions_jnp(node.args[0])
            ):
                out.append(
                    Violation(
                        rule=RULE,
                        path=source.path,
                        line=node.lineno,
                        message=(
                            f"{fn.id}() on a jnp value inside jitted "
                            f"{spec.name}() concretizes the tracer (host "
                            "sync); compute on device or hoist the check"
                        ),
                    )
                )
            # obs span/telemetry API: trace-time-only wall clock (and a
            # tracer note forces a host sync); instrument OUTSIDE jit
            elif isinstance(fn, ast.Attribute) and (
                fn.attr in _OBS_METHODS
                or (
                    fn.attr in _OBS_RECEIVER_METHODS
                    and any(
                        seg in _OBS_RECEIVERS for seg in _attr_chain(fn)[:-1]
                    )
                )
            ):
                out.append(
                    Violation(
                        rule=RULE,
                        path=source.path,
                        line=node.lineno,
                        message=(
                            f"obs span API .{fn.attr}() inside jitted "
                            f"{spec.name}() records trace-time wall "
                            "clock once (and concretizes tracer "
                            "arguments); instrument around the jitted "
                            "call, not inside it"
                        ),
                    )
                )
            # bare print(): traces once, then silently never runs
            elif isinstance(fn, ast.Name) and fn.id == "print":
                out.append(
                    Violation(
                        rule=RULE,
                        path=source.path,
                        line=node.lineno,
                        message=(
                            f"print() inside jitted {spec.name}() runs only "
                            "at trace time; use jax.debug.print"
                        ),
                    )
                )
    return out
