"""koordlint rule: ``unbounded-wait`` (ISSUE 13).

The degradation ladder's premise is that a fault degrades service
instead of hanging it — and an UNBOUNDED wait is exactly where a fault
turns into a hang nobody can distinguish from a deadlock.  Two shapes,
both with a named production failure mode:

* ``<x>.wait()`` with no timeout — a ``threading.Condition`` or
  ``Event`` wait that a lost notify (or a crashed peer that will never
  set the event) parks FOREVER.  The repo convention is the
  coalescer's backstop: ``cond.wait(timeout=1.0)`` inside the state
  re-check loop — a lost notify is a bug this recovers from at 1 Hz,
  not a hang.  Deliberate forever-parks (a main thread idling behind
  daemon threads) take a reasoned disable tag.
* a client RPC stub call with no ``timeout=``/``deadline=`` kwarg — a
  hung daemon then hangs every caller, and the propagated-deadline
  machinery (ISSUE 13: ``deadline_ms`` on the wire, evicted server-side
  before a launch slot) never gets to run because the transport itself
  never gives up.  The rule recognizes the repo's stub idiom: a call
  whose callee is named ``stub`` or ends in ``_stub``.

Shapes NOT flagged: ``wait(x)`` with any argument (a bounded wait,
however long, surfaces in a stack sample as progress), ``wait_for``
with a timeout kwarg, and computed receivers that merely contain
"wait" in a longer method name.

Suppression::

    threading.Event().wait()  # koordlint: disable=unbounded-wait(main thread parks forever by design; daemon threads own the work)
"""

from __future__ import annotations

import ast
from typing import List

from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "unbounded-wait"

_DEADLINE_KWARGS = {"timeout", "deadline"}


def _is_stub_callee(fn) -> bool:
    """The repo's client idiom: locals named ``stub`` (client.py's
    ``stub(request)``) or helper results bound as ``*_stub``."""
    if isinstance(fn, ast.Name):
        name = fn.id
    elif isinstance(fn, ast.Attribute):
        name = fn.attr
    else:
        return False
    return name == "stub" or name.endswith("_stub")


def check(source: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(source.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        kwarg_names = {k.arg for k in node.keywords if k.arg}
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr == "wait"
            and not node.args
            and not kwarg_names & _DEADLINE_KWARGS
            and not any(k.arg is None for k in node.keywords)
        ):
            out.append(Violation(
                rule=RULE,
                path=source.path,
                line=node.lineno,
                message=(
                    ".wait() with no timeout parks this thread forever "
                    "on a lost notify or a peer that died; use the "
                    "backstop idiom (wait(timeout=1.0) inside the "
                    "state re-check loop) or tag a deliberate "
                    "forever-park with a reasoned disable"
                ),
            ))
            continue
        if (
            _is_stub_callee(fn)
            # an RPC invocation passes the request positionally; a
            # zero-arg call is a stub FACTORY (``self._score_stub()``)
            and node.args
            and not kwarg_names & _DEADLINE_KWARGS
            and not any(k.arg is None for k in node.keywords)
        ):
            out.append(Violation(
                rule=RULE,
                path=source.path,
                line=node.lineno,
                message=(
                    "client RPC stub call without a timeout/deadline "
                    "kwarg: a hung daemon hangs every caller and the "
                    "propagated per-RPC deadline never applies; pass "
                    "timeout= (seconds) on every stub invocation"
                ),
            ))
    return out
