"""wire-contract: one-sided wire edits fail lint, not a Sync frame.

The BatchedScorer seam has THREE codecs that must agree byte-for-byte:
``bridge/scorer.proto`` (from which bridge/codegen.py's checked-in
``scorer_pb2`` is emitted), the hand-rolled Go protowire codec in
``go/scorerclient/wire.go`` + ``delta.go``, and the independent Python
mirror ``bridge/wirecheck.py``.  The runtime tests can only exercise the
Python pair (no Go toolchain in the image), so the Go half is checked
STATICALLY here: the marshal/unmarshal functions are parsed out of the
Go source and diffed against the proto —

* field names (snake_case -> CamelCase, ``_id`` -> ``ID``),
* field numbers and emit ORDER (ascending order is what makes the
  marshaling byte-stable against the Python runtime),
* integer widths (proto int32/int64 -> appendPackedInt32/Int64 etc.),
* endianness helpers for the packed little-endian byte payloads
  (``// i32 LE`` / ``i64 LE`` annotations in the proto are the spec),
* the shared delta-encoding constant (delta.go DefaultMaxDeltaRatio
  must equal state.py numpy_to_tensor's default max_delta_ratio),

plus a runtime probe that the checked-in ``scorer_pb2`` descriptor
matches the .proto (catching a stale regen).  All functions take source
TEXT so tests can seed one-sided regressions.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.analysis.core import Violation

RULE = "wire-contract"

_SCALARS = {"int32", "int64", "uint32", "uint64", "bool", "string",
            "bytes", "double", "float", "sint32", "sint64", "fixed32",
            "fixed64"}

# proto (type, repeated) -> the wire.go append helper that emits it
_EXPECTED_HELPER = {
    ("int64", True): "appendPackedInt64",
    ("int64", False): "appendVarintField",
    ("int32", True): "appendPackedInt32",
    ("bool", True): "appendPackedBools",
    ("bool", False): "appendVarintField",
    ("string", True): "appendRepeatedString",
    ("string", False): "appendStringField",
    ("bytes", False): "appendBytesField",
}

# reply fields the Go client deliberately does not decode
_ALLOWED_UNDECODED = {("ScoreReply", 1)}  # legacy per-pod lists; Go is flat-only


@dataclasses.dataclass
class ProtoField:
    num: int
    name: str
    ptype: str
    repeated: bool
    le_width: Optional[int]  # 32/64 from an "iNN LE" comment annotation

    @property
    def is_message(self) -> bool:
        return self.ptype not in _SCALARS


def camel(snake: str) -> str:
    return "".join(
        "ID" if seg == "id" else seg.capitalize()
        for seg in snake.split("_")
    )


# ---- parsers ----

_MSG_RE = re.compile(r"^message\s+(\w+)\s*\{", re.M)
_FIELD_RE = re.compile(
    r"^\s*(repeated\s+)?(\w+)\s+(\w+)\s*=\s*(\d+)\s*;(.*)$"
)
# inline form allows several fields on the message's own line (the empty
# 5th group keeps _field_of's comment-annotation slot aligned)
_FIELD_INLINE_RE = re.compile(
    r"(repeated\s+)?(\w+)\s+(\w+)\s*=\s*(\d+)\s*;()"
)
_LE_RE = re.compile(r"i(32|64)\s+LE")


def parse_proto(text: str) -> Dict[str, List[ProtoField]]:
    out: Dict[str, List[ProtoField]] = {}
    current: Optional[str] = None
    for line in text.splitlines():
        m = _MSG_RE.match(line.strip())
        if m:
            current = m.group(1)
            out[current] = []
            # single-line message: "message GangTable { repeated ... }"
            rest = line.split("{", 1)[1]
            for fm in _FIELD_INLINE_RE.finditer(rest):
                out[current].append(_field_of(fm))
            if "}" in rest:
                current = None
            continue
        if current is None:
            continue
        if line.strip().startswith("}"):
            current = None
            continue
        fm = _FIELD_RE.match(line)
        if fm:
            out[current].append(_field_of(fm))
    return out


def _field_of(m: "re.Match") -> ProtoField:
    trail = m.group(5) or ""
    le = _LE_RE.search(trail)
    return ProtoField(
        num=int(m.group(4)),
        name=m.group(3),
        ptype=m.group(2),
        repeated=bool(m.group(1)),
        le_width=int(le.group(1)) if le else None,
    )


@dataclasses.dataclass
class GoEmit:
    num: int
    helper: str
    field: Optional[str]  # receiver field name the value came from
    line: int


_GO_MARSHAL_HEAD = re.compile(
    r"^func \((\w+) \*(\w+)\) [Mm]arshal\(\) \[\]byte \{"
)
_GO_RANGE = re.compile(r"for\s+\w+,\s*(\w+)\s*:=\s*range\s+(\w+)\.(\w+)")
_GO_GUARD = re.compile(r"if\s+(\w+)\.(\w+)\s*\{")
_GO_EMIT = re.compile(r"=\s*(append\w+)\(b,\s*(\d+),\s*(.+)\)\s*(?://.*)?$")


def parse_go_marshals(text: str) -> Dict[str, List[GoEmit]]:
    """struct name -> ordered field emissions of its marshal function."""
    out: Dict[str, List[GoEmit]] = {}
    recv = struct = None
    loop_fields: Dict[str, str] = {}
    guard_field: Optional[str] = None
    for lineno, line in enumerate(text.splitlines(), start=1):
        head = _GO_MARSHAL_HEAD.match(line)
        if head:
            recv, struct = head.group(1), head.group(2)
            out[struct] = []
            loop_fields = {}
            guard_field = None
            continue
        if struct is None:
            continue
        if line.startswith("}"):
            recv = struct = None
            continue
        if line.strip() == "}":
            guard_field = None  # inner block closed: the guard is over
            continue
        rng = _GO_RANGE.search(line)
        if rng and rng.group(2) == recv:
            loop_fields[rng.group(1)] = rng.group(3)
        grd = _GO_GUARD.search(line)
        if grd and grd.group(1) == recv:
            guard_field = grd.group(2)
        emit = _GO_EMIT.search(line)
        if not emit:
            continue
        helper, num, expr = emit.group(1), int(emit.group(2)), emit.group(3)
        field: Optional[str] = None
        fm = re.match(rf"{recv}\.(\w+)", expr)
        if fm:
            field = fm.group(1)
        elif expr in loop_fields:
            field = loop_fields[expr]
        elif guard_field is not None:
            # e.g. `if r.Flat { appendVarintField(b, 3, 1) }` — consume
            # the guard so a later local-variable emit is not
            # mis-attributed to it
            field = guard_field
            guard_field = None
        out[struct].append(GoEmit(num, helper, field, lineno))
    return out


_GO_UNMARSHAL_HEAD = re.compile(r"^func Unmarshal(\w+)\(b \[\]byte\)")
_GO_CASE = re.compile(r"^\s*case\s+(\d+):")
_GO_ASSIGN = re.compile(r"r\.((?:Flat\.)?\w+)(?:\s*=|\s*=\s*append\()")
_GO_LE_HELPER = re.compile(r"=\s*(le\w+|string|float64FromBits|packedInt32)")


def parse_go_unmarshals(text: str) -> Dict[str, List[Tuple[int, str, str, int]]]:
    """Unmarshal functions -> [(case_num, assigned_field, helper, line)].
    Nested switches (FlatScores inside ScoreReply) associate with the
    nearest preceding ``case N:`` — assignments to ``Flat.X`` carry the
    inner field number."""
    out: Dict[str, List[Tuple[int, str, str, int]]] = {}
    current: Optional[str] = None
    last_case = -1
    for lineno, line in enumerate(text.splitlines(), start=1):
        head = _GO_UNMARSHAL_HEAD.match(line)
        if head:
            current = head.group(1)
            out[current] = []
            last_case = -1
            continue
        if current is None:
            continue
        if line.startswith("}"):
            current = None
            continue
        cm = _GO_CASE.match(line)
        if cm:
            last_case = int(cm.group(1))
            continue
        am = _GO_ASSIGN.search(line)
        if am and last_case >= 0:
            hm = _GO_LE_HELPER.search(line)
            helper = hm.group(1) if hm else ""
            out[current].append((last_case, am.group(1), helper, lineno))
    return out


# ---- the diff ----

def diff_proto_go(
    proto_text: str,
    wire_go_text: str,
    go_path: str = "go/scorerclient/wire.go",
) -> List[Violation]:
    proto = parse_proto(proto_text)
    marshals = parse_go_marshals(wire_go_text)
    unmarshals = parse_go_unmarshals(wire_go_text)
    out: List[Violation] = []

    def v(line: int, msg: str) -> None:
        out.append(Violation(rule=RULE, path=go_path, line=line, message=msg))

    # -- marshal side: Go -> Python requests --
    for struct, emits in marshals.items():
        fields = proto.get(struct)
        if fields is None:
            v(emits[0].line if emits else 0,
              f"Go struct {struct} has a marshal but no proto message")
            continue
        by_num = {f.num: f for f in fields}
        nums = [e.num for e in emits]
        if nums != sorted(nums):
            v(emits[0].line,
              f"{struct}.marshal emits fields out of ascending order "
              f"({nums}): byte-stability against the Python runtime "
              "requires ascending field numbers")
        seen = set()
        for e in emits:
            seen.add(e.num)
            f = by_num.get(e.num)
            if f is None:
                v(e.line,
                  f"{struct}.marshal emits field {e.num} which does not "
                  f"exist in proto message {struct}")
                continue
            want_name = camel(f.name)
            if e.field is not None and e.field != want_name:
                v(e.line,
                  f"{struct}.marshal field {e.num}: Go emits {e.field!r} "
                  f"but proto field {e.num} is '{f.name}' "
                  f"(expected Go field {want_name})")
            if f.is_message:
                if e.helper != "appendMessage":
                    v(e.line,
                      f"{struct}.{want_name} (field {e.num}) is a message "
                      f"({f.ptype}) but is emitted with {e.helper}")
            else:
                want_helper = _EXPECTED_HELPER.get((f.ptype, f.repeated))
                if want_helper and e.helper != want_helper:
                    v(e.line,
                      f"{struct}.{want_name} (field {e.num}, "
                      f"{'repeated ' if f.repeated else ''}{f.ptype}) "
                      f"emitted with {e.helper}; width/kind contract "
                      f"expects {want_helper}")
        for f in fields:
            if f.num not in seen:
                v(0,
                  f"{struct}.marshal never emits proto field {f.num} "
                  f"('{f.name}'): a populated value would be dropped "
                  "from the wire")

    # -- unmarshal side: Python replies -> Go --
    for msg, cases in unmarshals.items():
        fields = proto.get(msg)
        if fields is None:
            continue
        flat_fields = proto.get("FlatScores", [])
        flat_by_num = {f.num: f for f in flat_fields}
        by_num = {f.num: f for f in fields}
        decoded = set()
        for num, assigned, helper, line in cases:
            if assigned.startswith("Flat."):
                f = flat_by_num.get(num)
                scope, name = "FlatScores", assigned[len("Flat."):]
            else:
                f = by_num.get(num)
                scope, name = msg, assigned
                decoded.add(num)
            if name == "HasFlat":
                continue  # presence marker, not a wire field
            if f is None:
                v(line,
                  f"Unmarshal{msg} decodes field {num} into {assigned} "
                  f"but proto message {scope} has no field {num}")
                continue
            want_name = camel(f.name)
            if name != want_name:
                v(line,
                  f"Unmarshal{msg} field {num}: Go assigns {assigned!r} "
                  f"but proto field {num} is '{f.name}' "
                  f"(expected {want_name})")
            if f.le_width and helper and helper != f"leInt{f.le_width}s":
                v(line,
                  f"{scope}.{f.name} is annotated i{f.le_width} LE but "
                  f"Unmarshal{msg} decodes it with {helper}; wrong width "
                  "or endianness silently corrupts the payload")
        for f in fields:
            if f.num not in decoded and (msg, f.num) not in _ALLOWED_UNDECODED:
                v(0,
                  f"Unmarshal{msg} never decodes proto field {f.num} "
                  f"('{f.name}')")
    return out


# ---- the third mirror: bridge/wirecheck.py message decoders ----
# The runtime golden round-trips exercise wirecheck against scorer_pb2,
# but only for field values the fixtures happen to populate — a decoder
# branch MISSING for a new field (the ISSUE-13 deadline/band/degraded
# additions are the motivating case) silently drops the value instead
# of failing a test.  This check parses the hand-rolled
# ``if field == N: r["name"] = ...`` walks out of wirecheck.py via AST
# and diffs them against the proto: every scalar field must have a
# branch, under its proto name, at its proto number.

_WIRECHECK_DECODERS = {
    "ScoreRequest": "decode_score_request",
    "AssignRequest": "decode_assign_request",
    "ScoreReply": "decode_score_reply",
    "SyncReply": "decode_sync_reply",
    "AssignReply": "decode_assign_reply",
}


def _branch_field_keys(fn: ast.FunctionDef):
    """[(field_num, {r-subscript keys used in the branch}, line)] for
    every ``if field == <const>`` branch in a wirecheck decoder."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        test = node.test
        if not (
            isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and test.left.id == "field"
            and len(test.comparators) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and isinstance(test.comparators[0].value, int)
        ):
            continue
        num = int(test.comparators[0].value)
        keys = set()
        for sub in node.body:
            for n in ast.walk(sub):
                if (
                    isinstance(n, ast.Subscript)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "r"
                    and isinstance(n.slice, ast.Constant)
                    and isinstance(n.slice.value, str)
                ):
                    keys.add(n.slice.value)
        out.append((num, keys, node.lineno))
    return out


def check_wirecheck_messages(
    proto_text: str,
    wirecheck_text: str,
    path: str = "koordinator_tpu/bridge/wirecheck.py",
) -> List[Violation]:
    proto = parse_proto(proto_text)
    out: List[Violation] = []
    try:
        tree = ast.parse(wirecheck_text)
    except SyntaxError:
        return out  # the AST rules already report a parse error
    funcs = {
        n.name: n for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef)
    }
    for msg, fname in _WIRECHECK_DECODERS.items():
        fields = proto.get(msg)
        if fields is None:
            continue
        fn = funcs.get(fname)
        if fn is None:
            out.append(Violation(
                RULE, path, 0,
                f"wirecheck.py decoder {fname} for proto message "
                f"{msg} not found (the independent mirror lost a "
                "message)",
            ))
            continue
        branches = _branch_field_keys(fn)
        by_num = {num: (keys, line) for num, keys, line in branches}
        for f in fields:
            got = by_num.get(f.num)
            if got is None:
                out.append(Violation(
                    RULE, path, fn.lineno,
                    f"{fname} has no 'field == {f.num}' branch: proto "
                    f"{msg}.{f.name} would be silently dropped by the "
                    "wirecheck mirror",
                ))
                continue
            keys, line = got
            # message-typed fields decode into nested dicts whose key
            # usually matches; scalar fields MUST land under the proto
            # name so the two mirrors stay diffable
            if keys and f.name not in keys:
                out.append(Violation(
                    RULE, path, line,
                    f"{fname} field {f.num} writes {sorted(keys)} but "
                    f"proto {msg} field {f.num} is '{f.name}'",
                ))
        for num, _keys, line in branches:
            if num not in {f.num for f in fields}:
                out.append(Violation(
                    RULE, path, line,
                    f"{fname} decodes field {num} which does not exist "
                    f"in proto message {msg}",
                ))
    return out


_GO_RATIO = re.compile(r"DefaultMaxDeltaRatio\s*=\s*([0-9.]+)")


def python_delta_ratio_default(state_py_text: str) -> Optional[float]:
    """state.py numpy_to_tensor's max_delta_ratio default, via AST."""
    tree = ast.parse(state_py_text)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "numpy_to_tensor":
            args = node.args
            defaults = dict(
                zip([a.arg for a in args.args][-len(args.defaults):],
                    args.defaults)
            ) if args.defaults else {}
            d = defaults.get("max_delta_ratio")
            if isinstance(d, ast.Constant) and isinstance(d.value, (int, float)):
                return float(d.value)
    return None


def check_delta_constants(
    delta_go_text: str,
    state_py_text: str,
    go_path: str = "go/scorerclient/delta.go",
) -> List[Violation]:
    out: List[Violation] = []
    m = _GO_RATIO.search(delta_go_text)
    py = python_delta_ratio_default(state_py_text)
    if m is None:
        out.append(Violation(RULE, go_path, 0,
                             "DefaultMaxDeltaRatio constant not found"))
    elif py is not None and abs(float(m.group(1)) - py) > 1e-12:
        line = delta_go_text[: m.start()].count("\n") + 1
        out.append(Violation(
            RULE, go_path, line,
            f"DefaultMaxDeltaRatio={m.group(1)} but bridge/state.py "
            f"numpy_to_tensor defaults max_delta_ratio={py}: the two "
            "sides would disagree on when a delta frame is worth it",
        ))
    for field in ("DeltaIdx", "DeltaVal"):
        if not re.search(rf"t\.{field}\s*=\s*LEInt64Bytes\(", delta_go_text):
            out.append(Violation(
                RULE, go_path, 0,
                f"DeltaTensor does not pack {field} with LEInt64Bytes: "
                "delta payloads are little-endian int64 by contract "
                "(state.py decode_tensor np.frombuffer '<i8')",
            ))
    return out


# ---- replication stream framing (ISSUE 8) ----
# Three statements of the leader->follower frame header must agree:
# replication/codec.py FRAME_FIELDS (the layout's home),
# bridge/wirecheck.py REPLICA_FRAME_FIELDS (the independent runtime
# mirror), and go/scorerclient/replica.go replicaFrameFields.  Same
# treatment as scorer.proto vs wire.go: field names, emit order, byte
# widths, and the magic/version constants, diffed statically so a
# one-sided framing edit fails lint before any frame is built.

_GO_FRAME_ENTRY = re.compile(r'\{"(\w+)",\s*(\d+)\}')
_GO_FRAME_CONST = re.compile(
    r"(ReplicaFrameMagic|ReplicaFrameVersion|ReplicaHeaderLen)\s*=\s*"
    r"(0[xX][0-9a-fA-F]+|\d+)"
)


def _parse_py_frame_table(text: str, table_name: str, consts: Tuple[str, ...]):
    """(fields, constants, line-of-table) from a Python source: the
    ``table_name`` tuple-of-(name, width) assignment plus the named
    integer constants, via AST."""
    fields: List[Tuple[str, int]] = []
    values: Dict[str, int] = {}
    line = 0
    tree = ast.parse(text)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if target.id == table_name and isinstance(
            node.value, (ast.Tuple, ast.List)
        ):
            line = node.lineno
            for elt in node.value.elts:
                if (
                    isinstance(elt, (ast.Tuple, ast.List))
                    and len(elt.elts) == 2
                    and isinstance(elt.elts[0], ast.Constant)
                    and isinstance(elt.elts[1], ast.Constant)
                ):
                    fields.append(
                        (str(elt.elts[0].value), int(elt.elts[1].value))
                    )
        elif target.id in consts and isinstance(node.value, ast.Constant):
            if isinstance(node.value.value, int):
                values[target.id] = int(node.value.value)
    return fields, values, line


def _parse_go_frame_table(text: str):
    """(fields, constants, line-of-table) from replica.go: the
    replicaFrameFields literal entries in order plus the frame
    constants."""
    fields: List[Tuple[str, int]] = []
    line = 0
    in_table = False
    for lineno, src in enumerate(text.splitlines(), start=1):
        if "replicaFrameFields" in src and "=" in src:
            in_table = True
            line = lineno
            continue
        if in_table:
            m = _GO_FRAME_ENTRY.search(src)
            if m:
                fields.append((m.group(1), int(m.group(2))))
            elif src.strip() == "}":
                in_table = False
    consts = {
        m.group(1): int(m.group(2), 0)
        for m in _GO_FRAME_CONST.finditer(text)
    }
    return fields, consts, line


def check_replication_framing(
    codec_text: str,
    wirecheck_text: str,
    replica_go_text: str,
    go_path: str = "go/scorerclient/replica.go",
) -> List[Violation]:
    out: List[Violation] = []
    spec, spec_consts, _ = _parse_py_frame_table(
        codec_text, "FRAME_FIELDS", ("MAGIC", "VERSION")
    )
    mirror, mirror_consts, mirror_line = _parse_py_frame_table(
        wirecheck_text, "REPLICA_FRAME_FIELDS",
        ("REPLICA_MAGIC", "REPLICA_VERSION"),
    )
    go_fields, go_consts, go_line = _parse_go_frame_table(replica_go_text)
    if not spec:
        out.append(Violation(
            RULE, "koordinator_tpu/replication/codec.py", 0,
            "FRAME_FIELDS table not found: the replication frame "
            "layout has lost its one canonical Python statement",
        ))
        return out

    def diff_table(got, got_path, got_line, label):
        if got != spec:
            out.append(Violation(
                RULE, got_path, got_line,
                f"{label} frame table {got} disagrees with "
                f"replication/codec.py FRAME_FIELDS {spec}: a follower "
                "decoding with one layout while the leader emits the "
                "other tears every frame on the stream",
            ))

    if not mirror:
        out.append(Violation(
            RULE, "koordinator_tpu/bridge/wirecheck.py", 0,
            "REPLICA_FRAME_FIELDS mirror table not found in "
            "wirecheck.py (the independent second implementation)",
        ))
    else:
        diff_table(mirror, "koordinator_tpu/bridge/wirecheck.py",
                   mirror_line, "wirecheck.py REPLICA_FRAME_FIELDS")
    if not go_fields:
        out.append(Violation(
            RULE, go_path, 0,
            "replicaFrameFields table not found in replica.go",
        ))
    else:
        diff_table(go_fields, go_path, go_line,
                   "replica.go replicaFrameFields")
    # constants: magic + version must agree on all three sides, and the
    # Go header-length constant must equal the table's width sum
    pairs = (
        ("MAGIC", spec_consts.get("MAGIC"),
         mirror_consts.get("REPLICA_MAGIC"),
         go_consts.get("ReplicaFrameMagic")),
        ("VERSION", spec_consts.get("VERSION"),
         mirror_consts.get("REPLICA_VERSION"),
         go_consts.get("ReplicaFrameVersion")),
    )
    for name, spec_v, mirror_v, go_v in pairs:
        if mirror_v is not None and mirror_v != spec_v:
            out.append(Violation(
                RULE, "koordinator_tpu/bridge/wirecheck.py", 0,
                f"replica frame {name}: wirecheck.py says "
                f"{mirror_v:#x} but codec.py says {spec_v:#x}"
                if spec_v is not None else
                f"replica frame {name} missing from codec.py",
            ))
        if go_v is not None and go_v != spec_v:
            out.append(Violation(
                RULE, go_path, 0,
                f"replica frame {name}: replica.go says {go_v:#x} "
                f"but codec.py says "
                f"{spec_v:#x}" if spec_v is not None else
                f"replica frame {name} missing from codec.py",
            ))
    want_len = sum(w for _, w in spec)
    go_len = go_consts.get("ReplicaHeaderLen")
    if go_len is not None and go_len != want_len:
        out.append(Violation(
            RULE, go_path, 0,
            f"ReplicaHeaderLen={go_len} but the frame table sums to "
            f"{want_len}: the Go reader would mis-frame every stream",
        ))
    return out


def check_pb2_descriptor(
    proto_text: str, pb2_module=None
) -> List[Violation]:
    """The emitted layout: the checked-in scorer_pb2 must match the
    .proto (a stale regen would silently skew codegen from the contract
    the Go side is diffed against)."""
    if pb2_module is None:
        from koordinator_tpu.bridge.codegen import pb2 as pb2_module
    proto = parse_proto(proto_text)
    out: List[Violation] = []
    path = "koordinator_tpu/bridge/scorer_pb2.py"
    for msg, fields in proto.items():
        cls = getattr(pb2_module, msg, None)
        if cls is None:
            out.append(Violation(
                RULE, path, 0,
                f"proto message {msg} missing from emitted scorer_pb2",
            ))
            continue
        emitted = {
            f.name: f.number for f in cls.DESCRIPTOR.fields
        }
        for f in fields:
            got = emitted.pop(f.name, None)
            if got is None:
                out.append(Violation(
                    RULE, path, 0,
                    f"{msg}.{f.name} missing from emitted scorer_pb2 "
                    "(stale regen?)",
                ))
            elif got != f.num:
                out.append(Violation(
                    RULE, path, 0,
                    f"{msg}.{f.name} is field {got} in scorer_pb2 but "
                    f"{f.num} in scorer.proto (stale regen)",
                ))
        for name, num in emitted.items():
            out.append(Violation(
                RULE, path, 0,
                f"scorer_pb2 {msg}.{name} (field {num}) does not exist "
                "in scorer.proto (stale regen)",
            ))
    return out


def check_repo(root: str) -> List[Violation]:
    def read(*parts: str) -> Optional[str]:
        path = os.path.join(root, *parts)
        if not os.path.exists(path):
            return None
        with open(path, "r", encoding="utf-8") as f:
            return f.read()

    proto = read("koordinator_tpu", "bridge", "scorer.proto")
    if proto is None:
        return [Violation(RULE, "koordinator_tpu/bridge/scorer.proto", 0,
                          "scorer.proto not found")]
    out: List[Violation] = []
    wire = read("go", "scorerclient", "wire.go")
    if wire is not None:
        out.extend(diff_proto_go(proto, wire))
    wcheck_msgs = read("koordinator_tpu", "bridge", "wirecheck.py")
    if wcheck_msgs is not None:
        out.extend(check_wirecheck_messages(proto, wcheck_msgs))
    delta = read("go", "scorerclient", "delta.go")
    state = read("koordinator_tpu", "bridge", "state.py")
    if delta is not None and state is not None:
        out.extend(check_delta_constants(delta, state))
    codec = read("koordinator_tpu", "replication", "codec.py")
    wcheck = read("koordinator_tpu", "bridge", "wirecheck.py")
    replica = read("go", "scorerclient", "replica.go")
    if codec is not None and wcheck is not None and replica is not None:
        out.extend(check_replication_framing(codec, wcheck, replica))
    try:
        out.extend(check_pb2_descriptor(proto))
    except ImportError:  # no protobuf runtime: the static diff still ran
        pass
    return out
