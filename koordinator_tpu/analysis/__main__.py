"""CLI: ``python -m koordinator_tpu.analysis`` (koordlint).

Exit status 0 = clean, 1 = violations (one ``file:line: [rule] message``
per line), 2 = usage error.  The same pass runs under tier-1 via
``tests/test_koordlint.py``, so CI and the CLI can never disagree.
"""

from __future__ import annotations

import argparse
import sys

from koordinator_tpu.analysis import RULES
from koordinator_tpu.analysis.core import run_repo


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.analysis",
        description="koordlint: JAX-invariant static analysis + "
        "wire-contract cross-check",
    )
    ap.add_argument(
        "--root",
        default=None,
        help="repo root (default: auto-detected from the package location)",
    )
    ap.add_argument(
        "--rules",
        default=None,
        help=f"comma-separated subset of rules to run (all: {','.join(RULES)})",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print rule names and exit"
    )
    ap.add_argument(
        "--suppressions", action="store_true",
        help="audit every live 'koordlint: disable=' tag: list them "
        "with reasons; stale tags and reason-required rules suppressed "
        "without a reason fail the run",
    )
    ap.add_argument(
        "--write-lockorder", action="store_true",
        help="regenerate docs/LOCKORDER.md from the derived lock graph "
        "and exit",
    )
    args = ap.parse_args(argv)
    if args.list_rules:
        for rule in RULES:
            print(rule)
        return 0
    if args.write_lockorder:
        from koordinator_tpu.analysis import lockgraph
        from koordinator_tpu.analysis.core import find_repo_root

        path = lockgraph.write_lockorder(args.root or find_repo_root())
        print(f"wrote {path}")
        return 0
    if args.suppressions:
        from koordinator_tpu.analysis import suppressions

        tags, problems = suppressions.audit(args.root)
        print(suppressions.format_report(tags, problems))
        return 1 if problems else 0
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
    # root=None lets run_repo resolve the repo from the package location,
    # so the CLI works from any cwd
    violations = run_repo(root=args.root, rules=rules)
    for v in violations:
        print(v.format())
    if violations:
        print(
            f"koordlint: {len(violations)} violation(s)  "
            "(suppress a line with '# koordlint: disable=<rule>')",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
