"""Recognize jitted functions and their static/donated arguments.

Handles the three spellings the repo uses::

    @jax.jit                                   # plain decorator
    @partial(jax.jit, static_argnames=("cfg",))
    @partial(jax.jit, donate_argnums=(0,))
    scatter = jax.jit(_scatter, donate_argnums=(0,))   # call form

Flow-insensitive and module-local by design: a jit wrapper imported from
another module is invisible here (the donation contract of an exported
helper belongs in its own module's call sites and docstring — see
solver/resident.py's ``apply_flat_delta``).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple


def is_jitted_def(node: ast.AST) -> bool:
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    return any(
        _is_jit_ref(deco) or _jit_call_spec(deco) is not None
        for deco in node.decorator_list
    )


def scope_walk(scope: ast.AST, into_closures: bool = False):
    """Walk a function/module scope without descending into nested
    function DEFINITIONS — nested defs are their own scopes, and nested
    jitted defs get their own pass, so walking into them double-reports
    and mis-attributes violations.

    ``into_closures=True`` descends into nested NON-jitted defs: a
    closure inside a jitted function (the ``step`` of a ``lax.scan``)
    executes under the enclosing trace, so trace-context rules must see
    its body; only nested JITTED defs stay excluded."""
    stack = list(scope.body) if hasattr(scope, "body") else []
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if into_closures and not is_jitted_def(node):
                stack.extend(node.body)
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_jit_ref(node: ast.AST) -> bool:
    """jax.jit / jit / pjit-style attribute reference."""
    if isinstance(node, ast.Attribute):
        return node.attr in ("jit", "pjit")
    if isinstance(node, ast.Name):
        return node.id in ("jit", "pjit")
    return False


def _literal_strs(node: Optional[ast.AST]) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        elts = node.elts
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    else:
        return out
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.add(e.value)
    return out


def _literal_ints(node: Optional[ast.AST]) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        elts = node.elts
    elif isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    else:
        return out
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            out.add(e.value)
    return out


@dataclasses.dataclass
class JitSpec:
    """One jit application: the wrapped function (when visible) plus the
    static/donate argument declarations."""

    name: str
    func: Optional[ast.FunctionDef]
    static_names: Set[str]
    static_nums: Set[int]
    donate_names: Set[str]
    donate_nums: Set[int]
    line: int

    def params(self) -> List[str]:
        if self.func is None:
            return []
        a = self.func.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]

    def positional_params(self) -> List[str]:
        if self.func is None:
            return []
        a = self.func.args
        return [p.arg for p in a.posonlyargs + a.args]

    def static_params(self) -> Set[str]:
        out = set(self.static_names)
        pos = self.positional_params()
        for i in self.static_nums:
            if 0 <= i < len(pos):
                out.add(pos[i])
        return out

    def donated_params(self) -> Set[str]:
        out = set(self.donate_names)
        pos = self.positional_params()
        for i in self.donate_nums:
            if 0 <= i < len(pos):
                out.add(pos[i])
        return out


def _spec_from_call_kwargs(call: ast.Call) -> Tuple[Set[str], Set[int], Set[str], Set[int]]:
    static_names: Set[str] = set()
    static_nums: Set[int] = set()
    donate_names: Set[str] = set()
    donate_nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            static_names |= _literal_strs(kw.value)
        elif kw.arg == "static_argnums":
            static_nums |= _literal_ints(kw.value)
        elif kw.arg == "donate_argnames":
            donate_names |= _literal_strs(kw.value)
        elif kw.arg == "donate_argnums":
            donate_nums |= _literal_ints(kw.value)
    return static_names, static_nums, donate_names, donate_nums


def _jit_call_spec(node: ast.AST) -> Optional[Tuple[Set[str], Set[int], Set[str], Set[int]]]:
    """Match ``jax.jit(...)`` or ``partial(jax.jit, ...)`` call nodes."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jit_ref(node.func):
        return _spec_from_call_kwargs(node)
    # partial(jax.jit, static_argnames=...)
    f = node.func
    is_partial = (isinstance(f, ast.Name) and f.id == "partial") or (
        isinstance(f, ast.Attribute) and f.attr == "partial"
    )
    if is_partial and node.args and _is_jit_ref(node.args[0]):
        return _spec_from_call_kwargs(node)
    return None


def jitted_defs(tree: ast.AST) -> List[JitSpec]:
    """Every function DEFINITION wrapped by jit (decorator spellings)."""
    out: List[JitSpec] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            if _is_jit_ref(deco):
                out.append(
                    JitSpec(node.name, node, set(), set(), set(), set(),
                            node.lineno)
                )
                break
            spec = _jit_call_spec(deco)
            if spec is not None:
                sn, si, dn, di = spec
                out.append(JitSpec(node.name, node, sn, si, dn, di, node.lineno))
                break
    return out


def jit_assignments(tree: ast.AST) -> Dict[str, JitSpec]:
    """``name = jax.jit(fn, ...)`` module/function-level assignments.
    The wrapped fn's def is attached when it is a plain module-level name."""
    defs = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef)
    }
    out: Dict[str, JitSpec] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        if not _is_jit_ref(call.func):
            continue
        sn, si, dn, di = _spec_from_call_kwargs(call)
        wrapped = None
        if call.args and isinstance(call.args[0], ast.Name):
            wrapped = defs.get(call.args[0].id)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = JitSpec(
                    tgt.id, wrapped, sn, si, dn, di, node.lineno
                )
    return out


def donating_callables(tree: ast.AST) -> Dict[str, JitSpec]:
    """Module-local names that, when CALLED, donate some arguments."""
    out: Dict[str, JitSpec] = {}
    for spec in jitted_defs(tree):
        if spec.donate_nums or spec.donate_names:
            out[spec.name] = spec
    for name, spec in jit_assignments(tree).items():
        if spec.donate_nums or spec.donate_names:
            out[name] = spec
    return out
