"""span-leak: every ``begin_span`` must end on ALL exit paths.

The obs span recorder (koordinator_tpu/obs/spans.py) exposes a raw
``begin_span(name) -> handle`` / ``end_span(handle)`` pair for call
sites where the context-manager form can't be used (e.g. a span whose
recorder may be None).  A raw ``begin_span`` whose ``end_span`` only
runs on the happy path leaks the span whenever the stage raises — the
flight recorder then shows a stage that "never finished" on every
cycle AFTER the bad one, which is exactly the misleading artifact a
post-mortem tool must not produce.

Accepted shapes (anything else is a violation):

* ``with recorder.span("stage"): ...`` — the context-manager form
  (no raw begin/end at the call site at all; preferred).
* ``h = r.begin_span("x")`` immediately followed by a ``try:`` whose
  ``finally:`` calls ``end_span`` (the canonical raw form).
* ``begin_span`` anywhere inside a ``try`` whose ``finally`` calls
  ``end_span``.
* ``begin_span`` inside an ``__enter__`` whose class's ``__exit__``
  calls ``end_span`` (the context-manager *implementation* pattern —
  obs/spans.py itself).

ISSUE 14 extends the rule over the distributed-tracing API:

* ``start_trace_span(...)`` mints an exportable span that MUST be
  ended or aborted on every exit — a leaked TraceSpan never exports,
  so the assembled tree silently loses the very RPC a post-mortem is
  looking for.  Accepted shapes: the with-block form (TraceSpan is a
  context manager), ``return start_trace_span(...)`` (a factory hands
  ownership to its caller — ``ScorerServicer._start_rpc_span``), or an
  enclosing function that demonstrably closes both paths — ``.end(``/
  ``.abort(`` in some ``finally:``, or ``.abort(`` in an except
  handler plus an ``.end(`` on the fall-through.
* a ``SpanExporter(...)`` handle must be CLOSED: with-block,
  ``return``-factory, ``.close(`` in a protecting ``finally:``, or
  assignment to ``self.<attr>`` inside a class whose ``close`` method
  calls ``.close(`` (the CycleTelemetry/ScorerClient lifetime shape).

Suppressible per line like every rule:
``# koordlint: disable=span-leak(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "span-leak"


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _contains_call(node: ast.AST, name: str) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _call_name(sub) == name:
            return True
    return False


def _ends_in_finally(try_node: ast.Try) -> bool:
    return any(_contains_call(stmt, "end_span") for stmt in try_node.finalbody)


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _in_protected_try(node: ast.AST, parents) -> bool:
    """Inside a Try (body/handlers/orelse — not the finally itself)
    whose finalbody ends the span."""
    child = node
    while child in parents:
        parent = parents[child]
        if isinstance(parent, ast.Try) and _ends_in_finally(parent):
            # `child` is the Try's direct child on the ancestor path;
            # anywhere but the finalbody itself counts as protected
            if child not in parent.finalbody:
                return True
        child = parent
    return False


def _followed_by_protected_try(node: ast.AST, parents) -> bool:
    """The canonical raw form: the begin_span statement's NEXT sibling
    is a Try whose finally ends the span."""
    stmt = node
    while stmt in parents and not isinstance(stmt, ast.stmt):
        stmt = parents[stmt]
    if not isinstance(stmt, ast.stmt) or stmt not in parents:
        return False
    block_owner = parents[stmt]
    for field in ("body", "orelse", "finalbody"):
        block = getattr(block_owner, field, None)
        if isinstance(block, list) and stmt in block:
            i = block.index(stmt)
            if i + 1 < len(block):
                nxt = block[i + 1]
                return isinstance(nxt, ast.Try) and _ends_in_finally(nxt)
            return False
    # statements inside an except handler live on the handler, not the Try
    if isinstance(block_owner, ast.excepthandler):
        block = block_owner.body
        if stmt in block:
            i = block.index(stmt)
            if i + 1 < len(block):
                nxt = block[i + 1]
                return isinstance(nxt, ast.Try) and _ends_in_finally(nxt)
    return False


def _in_enter_with_exit(node: ast.AST, parents) -> bool:
    """The CM implementation pattern: begin in __enter__, end in the
    same class's __exit__."""
    child = node
    func: Optional[ast.AST] = None
    while child in parents:
        parent = parents[child]
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = parent
            break
        child = parent
    if func is None or func.name != "__enter__" or func not in parents:
        return False
    cls = parents[func]
    if not isinstance(cls, ast.ClassDef):
        return False
    for stmt in cls.body:
        if (
            isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
            and stmt.name == "__exit__"
            and _contains_call(stmt, "end_span")
        ):
            return True
    return False


def _enclosing_function(node: ast.AST, parents) -> Optional[ast.AST]:
    child = node
    while child in parents:
        parent = parents[child]
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return parent
        child = parent
    return None


def _is_returned(node: ast.AST, parents) -> bool:
    """``return <call>(...)`` — a factory transfers ownership to its
    caller (ScorerServicer._start_rpc_span is the canonical one)."""
    return isinstance(parents.get(node), ast.Return)


def _in_with_items(node: ast.AST, parents) -> bool:
    """The call is a with-statement's context expression (directly or
    under the withitem): the CM protocol ends/closes it."""
    child = node
    while child in parents:
        parent = parents[child]
        if isinstance(parent, ast.withitem):
            return True
        if isinstance(parent, ast.stmt):
            return False
        child = parent
    return False


def _attr_call_in(node: ast.AST, *names: str) -> bool:
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in names
        ):
            return True
    return False


def _function_closes_span(func: ast.AST) -> bool:
    """The enclosing function demonstrably closes BOTH paths of a
    TraceSpan: ``.end(``/``.abort(`` in some finally, or ``.abort(``
    in an except handler plus an ``.end(`` on the fall-through."""
    finally_close = False
    handler_abort = False
    end_anywhere = False
    for sub in ast.walk(func):
        if isinstance(sub, ast.Try):
            if any(
                _attr_call_in(s, "end", "abort") for s in sub.finalbody
            ):
                finally_close = True
            for handler in sub.handlers:
                if any(
                    _attr_call_in(s, "abort", "end")
                    for s in handler.body
                ):
                    handler_abort = True
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "end"
        ):
            end_anywhere = True
    return finally_close or (handler_abort and end_anywhere)


def _assigned_to_self_with_close(node: ast.AST, parents) -> bool:
    """``self.x = SpanExporter(...)`` inside a class whose ``close``
    method calls ``.close(`` — the long-lived handle shape
    (CycleTelemetry, ScorerClient)."""
    parent = parents.get(node)
    if not (
        isinstance(parent, ast.Assign)
        and len(parent.targets) == 1
        and isinstance(parent.targets[0], ast.Attribute)
        and isinstance(parent.targets[0].value, ast.Name)
        and parent.targets[0].value.id == "self"
    ):
        return False
    child: ast.AST = parent
    while child in parents:
        up = parents[child]
        if isinstance(up, ast.ClassDef):
            return any(
                isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                and stmt.name == "close"
                and _attr_call_in(stmt, "close")
                for stmt in up.body
            )
        child = up
    return False


def _in_try_closing(node: ast.AST, parents, *names: str) -> bool:
    """Inside (or immediately followed by) a Try whose finally calls
    one of ``names`` — the close-in-finally shape for handles."""
    child = node
    while child in parents:
        parent = parents[child]
        if isinstance(parent, ast.Try) and child not in parent.finalbody:
            if any(_attr_call_in(s, *names) for s in parent.finalbody):
                return True
        child = parent
    # the begin-then-try sibling shape
    stmt = node
    while stmt in parents and not isinstance(stmt, ast.stmt):
        stmt = parents[stmt]
    if isinstance(stmt, ast.stmt) and stmt in parents:
        owner = parents[stmt]
        for field in ("body", "orelse", "finalbody"):
            block = getattr(owner, field, None)
            if isinstance(block, list) and stmt in block:
                i = block.index(stmt)
                if i + 1 < len(block) and isinstance(block[i + 1], ast.Try):
                    return any(
                        _attr_call_in(s, *names)
                        for s in block[i + 1].finalbody
                    )
    return False


def check(source: SourceFile) -> List[Violation]:
    parents = _parents(source.tree)
    out: List[Violation] = []
    for node in ast.walk(source.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "begin_span":
            if _in_protected_try(node, parents):
                continue
            if _followed_by_protected_try(node, parents):
                continue
            if _in_enter_with_exit(node, parents):
                continue
            out.append(
                Violation(
                    rule=RULE,
                    path=source.path,
                    line=node.lineno,
                    message=(
                        "begin_span() without a guaranteed end_span() on "
                        "every exit: an exception here leaks the span into "
                        "every later flight record.  Use "
                        "`with recorder.span(...)`, or follow begin_span "
                        "immediately with try/finally calling end_span"
                    ),
                )
            )
        elif name == "start_trace_span":
            if _is_returned(node, parents) or _in_with_items(node, parents):
                continue
            func = _enclosing_function(node, parents)
            if func is not None and _function_closes_span(func):
                continue
            out.append(
                Violation(
                    rule=RULE,
                    path=source.path,
                    line=node.lineno,
                    message=(
                        "start_trace_span() without end()/abort() on "
                        "every exit: a leaked TraceSpan never exports, "
                        "so the assembled trace silently loses this "
                        "RPC.  Use `with ... as span:`, return it from "
                        "a factory, or abort in an except handler and "
                        "end on the fall-through"
                    ),
                )
            )
        elif name == "SpanExporter":
            if _is_returned(node, parents) or _in_with_items(node, parents):
                continue
            if _assigned_to_self_with_close(node, parents):
                continue
            if _in_try_closing(node, parents, "close"):
                continue
            out.append(
                Violation(
                    rule=RULE,
                    path=source.path,
                    line=node.lineno,
                    message=(
                        "SpanExporter() handle is never closed on this "
                        "path: close it in a finally, use the with-"
                        "block form, or hold it on self in a class "
                        "whose close() closes it"
                    ),
                )
            )
    return out
