"""span-leak: every ``begin_span`` must end on ALL exit paths.

The obs span recorder (koordinator_tpu/obs/spans.py) exposes a raw
``begin_span(name) -> handle`` / ``end_span(handle)`` pair for call
sites where the context-manager form can't be used (e.g. a span whose
recorder may be None).  A raw ``begin_span`` whose ``end_span`` only
runs on the happy path leaks the span whenever the stage raises — the
flight recorder then shows a stage that "never finished" on every
cycle AFTER the bad one, which is exactly the misleading artifact a
post-mortem tool must not produce.

Accepted shapes (anything else is a violation):

* ``with recorder.span("stage"): ...`` — the context-manager form
  (no raw begin/end at the call site at all; preferred).
* ``h = r.begin_span("x")`` immediately followed by a ``try:`` whose
  ``finally:`` calls ``end_span`` (the canonical raw form).
* ``begin_span`` anywhere inside a ``try`` whose ``finally`` calls
  ``end_span``.
* ``begin_span`` inside an ``__enter__`` whose class's ``__exit__``
  calls ``end_span`` (the context-manager *implementation* pattern —
  obs/spans.py itself).

Suppressible per line like every rule:
``# koordlint: disable=span-leak(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "span-leak"


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _contains_call(node: ast.AST, name: str) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _call_name(sub) == name:
            return True
    return False


def _ends_in_finally(try_node: ast.Try) -> bool:
    return any(_contains_call(stmt, "end_span") for stmt in try_node.finalbody)


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _in_protected_try(node: ast.AST, parents) -> bool:
    """Inside a Try (body/handlers/orelse — not the finally itself)
    whose finalbody ends the span."""
    child = node
    while child in parents:
        parent = parents[child]
        if isinstance(parent, ast.Try) and _ends_in_finally(parent):
            # `child` is the Try's direct child on the ancestor path;
            # anywhere but the finalbody itself counts as protected
            if child not in parent.finalbody:
                return True
        child = parent
    return False


def _followed_by_protected_try(node: ast.AST, parents) -> bool:
    """The canonical raw form: the begin_span statement's NEXT sibling
    is a Try whose finally ends the span."""
    stmt = node
    while stmt in parents and not isinstance(stmt, ast.stmt):
        stmt = parents[stmt]
    if not isinstance(stmt, ast.stmt) or stmt not in parents:
        return False
    block_owner = parents[stmt]
    for field in ("body", "orelse", "finalbody"):
        block = getattr(block_owner, field, None)
        if isinstance(block, list) and stmt in block:
            i = block.index(stmt)
            if i + 1 < len(block):
                nxt = block[i + 1]
                return isinstance(nxt, ast.Try) and _ends_in_finally(nxt)
            return False
    # statements inside an except handler live on the handler, not the Try
    if isinstance(block_owner, ast.excepthandler):
        block = block_owner.body
        if stmt in block:
            i = block.index(stmt)
            if i + 1 < len(block):
                nxt = block[i + 1]
                return isinstance(nxt, ast.Try) and _ends_in_finally(nxt)
    return False


def _in_enter_with_exit(node: ast.AST, parents) -> bool:
    """The CM implementation pattern: begin in __enter__, end in the
    same class's __exit__."""
    child = node
    func: Optional[ast.AST] = None
    while child in parents:
        parent = parents[child]
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = parent
            break
        child = parent
    if func is None or func.name != "__enter__" or func not in parents:
        return False
    cls = parents[func]
    if not isinstance(cls, ast.ClassDef):
        return False
    for stmt in cls.body:
        if (
            isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
            and stmt.name == "__exit__"
            and _contains_call(stmt, "end_span")
        ):
            return True
    return False


def check(source: SourceFile) -> List[Violation]:
    parents = _parents(source.tree)
    out: List[Violation] = []
    for node in ast.walk(source.tree):
        if not (isinstance(node, ast.Call) and _call_name(node) == "begin_span"):
            continue
        if _in_protected_try(node, parents):
            continue
        if _followed_by_protected_try(node, parents):
            continue
        if _in_enter_with_exit(node, parents):
            continue
        out.append(
            Violation(
                rule=RULE,
                path=source.path,
                line=node.lineno,
                message=(
                    "begin_span() without a guaranteed end_span() on "
                    "every exit: an exception here leaks the span into "
                    "every later flight record.  Use "
                    "`with recorder.span(...)`, or follow begin_span "
                    "immediately with try/finally calling end_span"
                ),
            )
        )
    return out
