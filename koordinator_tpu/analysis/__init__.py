"""koordlint: JAX-invariant static analysis + wire-contract cross-check.

PR 1's hardest bugs were all statically detectable classes: pod names
riding as static pytree metadata (silent per-cycle retrace), donated
buffers read after donation in the resident-snapshot scatter path, and
host syncs hiding inside hot jitted cycles.  This package makes those
bug classes un-landable instead of re-debugged per PR: an AST pass over
the repo plus a cross-language diff of the BatchedScorer wire contract,
wired into tier-1 via ``tests/test_koordlint.py`` and runnable as
``python -m koordinator_tpu.analysis``.

Rules (each suppressible per line with ``# koordlint: disable=<rule>``):

* ``donation-safety``   — a name passed to a ``donate_argnums`` /
  ``donate_argnames`` jitted call must not be read again in the same
  scope after the call (solver/resident.py scatter-path bug class).
* ``retrace-hazard``    — Python ``if``/``while``/``assert`` on
  tracer-typed values inside jitted functions, unhashable or
  tuple-of-str static args at call sites, and name/str payloads inside
  pytree registrations (the PR-1 name-tuple retrace).
* ``host-sync-in-jit``  — ``np.asarray``, ``.item()``, ``float()``/
  ``int()`` on jnp values, ``print()``, and the obs span/telemetry API
  (koordinator_tpu/obs/) inside jitted functions — instrumentation
  records AROUND device programs, never inside them.
* ``span-leak``         — raw ``begin_span`` calls must guarantee the
  matching ``end_span`` on every exit path (context manager or
  try/finally); a leaked span poisons every later flight record.
* ``lock-held-dispatch`` — blocking device readbacks (``np.asarray``,
  ``.item()``, ``.block_until_ready()``, ``jax.device_get``) inside a
  ``with <state lock>:`` block — the serialized-daemon bug class the
  coalescing dispatch engine (ISSUE 5) removed: capture under the
  lock, read back outside it.
* ``broad-except``      — ``except Exception:`` handlers must re-raise,
  log, or surface the bound error; silent swallowers need a reasoned
  ``# koordlint: disable=broad-except(<reason>)`` tag.
* ``unbounded-wait``    — ``Condition.wait()``/``Event.wait()`` with no
  timeout (a lost notify or a dead peer turns into a hang; use the
  backstop ``wait(timeout=1.0)`` re-check-loop idiom) and client RPC
  stub calls with no ``timeout=``/``deadline=`` kwarg (a hung daemon
  must not hang every caller — ISSUE 13's deadline propagation needs
  the transport to give up too).  Deliberate forever-parks take a
  reasoned disable tag.
* ``bare-retry``        — a ``while``/``for`` retry loop (one that
  contains an ``except``) sleeping a FIXED ``time.sleep(<literal>)``
  cadence: no jitter (thundering herd on recovery), no exponential
  cap, no deadline budget.  Retries pace through the one shared
  ``replication.retry.BackoffPolicy``; deliberate fixed-cadence polls
  take a reasoned disable tag.
* ``wire-contract``     — statically diffs scorer.proto (the layout
  bridge/codegen.py's emitted ``scorer_pb2`` is generated from) against
  the hand-rolled Go codec in go/scorerclient/wire.go + delta.go:
  field names, numbers, emit order, integer widths, endianness helpers
  and the shared delta-ratio constant.
* ``metrics-doc-drift`` — statically diffs the ``koord_scorer_*``
  families registered in obs/scorer_metrics.py against the family
  table in docs/OBSERVABILITY.md, both directions plus the declared
  kind: an undocumented metric or a documented-but-never-exported one
  fails lint like a one-sided wire edit.
* ``lock-order-cycle``  — the whole-program lock graph
  (analysis/lockgraph.py): every ``threading.Lock/RLock/Condition``
  creation site becomes a canonical identity, nested acquisitions
  (lexical ``with`` nesting, calls resolved through the cross-module
  method table, the ``@launch_section``/``run_exclusive`` dispatch
  seams, ``Condition.wait`` re-acquires) become order edges, and any
  cycle in the derived order — a deadlock two threads can close —
  fails lint.
* ``lockorder-doc-drift`` — the derived lock order IS
  ``docs/LOCKORDER.md`` (generated; ``--write-lockorder``): a lock or
  edge missing from the doc, a doc row nothing derives, or a witness
  factory name disagreeing with the derived identity fails lint, both
  directions (the metrics-doc-drift pattern).
* ``unregistered-jit-boundary`` — device-time truth (ISSUE 19,
  analysis/devbound.py): every jitted def under ``solver/``,
  ``parallel/`` or ``bridge/`` must register with the XLA launch
  ledger via ``@devprof.boundary("<name>")`` (stacked ABOVE the jit
  decorator, name a string literal); ``jax.jit(fn)`` call-form
  assignments and ``shard_map`` launches outside any jitted def are
  flagged — an unregistered boundary's compiles and device time
  silently escape the ledger, /metrics and /healthz.
* ``prewarm-drift``     — statically diffs every ``@devprof.boundary``
  registration in the repo against the AOT prewarm tables in
  obs/prewarm.py (``PREWARM_BOUNDARIES`` + ``PREWARM_EXCLUDED``), both
  directions: a registered boundary in neither table rots the replay
  set (its compiles land cold every boot), a table entry nothing
  registers is a stale row, and a name in both tables is a
  contradiction — the tables partition the boundary space.
* ``unguarded-shared-state`` — guarded-state inference
  (analysis/guards.py): an attribute a class writes under its lock is
  presumed lock-protected, so a lock-free write elsewhere (or a
  lock-free read of a structure mutated in place under the lock)
  fails; ``__init__``/``*_locked`` methods and rebind-only atomic
  reads are exempt, everything else takes a REASONED suppression.

The runtime companions: ``analysis.retrace_guard`` locks the warm
path's compile economics in at test time (tests/test_resident_warm.py),
and ``obs.lockwitness`` (``KOORD_LOCK_WITNESS=1``) validates the
derived lock order against real interleavings — the chaos-trace and
replication-storm replays run witness-enabled in tier-1.  The
suppression ledger is auditable: ``--suppressions`` lists every live
disable tag and fails on stale tags or reason-required rules
suppressed without a reason.
"""

from koordinator_tpu.analysis.core import (  # noqa: F401
    Violation,
    iter_python_files,
    run_repo,
    run_rules_on_source,
)
from koordinator_tpu.analysis.retrace_guard import (  # noqa: F401
    RetraceBudgetExceeded,
    retrace_guard,
)

RULES = (
    "donation-safety",
    "retrace-hazard",
    "host-sync-in-jit",
    "broad-except",
    "span-leak",
    "lock-held-dispatch",
    "bare-retry",
    "unbounded-wait",
    "wire-contract",
    "metrics-doc-drift",
    "prewarm-drift",
    "lock-order-cycle",
    "lockorder-doc-drift",
    "unguarded-shared-state",
    "unregistered-jit-boundary",
)
