"""retrace-hazard: things that silently recompile the hot cycle.

Six statically detectable shapes of the PR-1 name-tuple retrace:

1. Python control flow (``if``/``while``/``assert``) on a TRACED
   parameter inside a jitted function.  Branching on a tracer either
   raises (abstract truthiness) or — worse, for weak types — forces a
   concretization; branching on values that vary per cycle retraces.
   ``x is None`` / ``x is not None`` checks are exempt: pytree presence
   is part of the trace signature, branching on it is the idiomatic way
   to specialize a jitted function.
2. Unhashable or string-tuple STATIC arguments at call sites of a
   module-local jitted function: a list/dict/set static arg raises at
   call time, and a tuple-of-str static arg (names!) keys the jit cache
   on payload data — one retrace per distinct name set.
3. Name/str payloads registered as pytree METADATA: a field called
   ``name``/``names`` (or ``*_name``/``*_names``) in ``meta_fields`` of
   ``register_dataclass`` (or an aux_data tuple of
   ``register_pytree_node``) keys every downstream jit cache on object
   names — the exact PR-1 bug.  Intentional embedded-API registrations
   carry a reasoned disable tag instead.
4. TRACED wave knobs at a jit boundary: a jitted function that takes
   ``wave`` or ``top_m`` without declaring it static traces the wave
   width into the program — the loop structure then re-specializes on
   every distinct value, a silent per-cycle retrace of the hottest
   program in the repo (solver/wave.py, the wave Pallas kernel, and
   parallel/shard_assign.py all pass them via ``static_argnames``).
   ISSUE 7 extends the same shape to the MESH knobs: a traced ``mesh``
   / device-count / shard-width argument at a jit boundary
   re-specializes the partitioned program per value exactly the same
   way (parallel/mesh.py, solver/resident.py and shard_assign.py all
   declare ``mesh`` static), and a shard_map BODY taking one of these
   names as a parameter receives it as a traced per-shard operand —
   the mesh belongs in the ``shard_map(..., mesh=)`` binding or the
   closure, never in the operand list.
5. UNHASHABLE / UNFROZEN CycleConfig term configs (ISSUE 15): the
   config rides jit as a static argument, so every dataclass reachable
   from CycleConfig's field annotations (the fused scoring-term
   configs, the LoadAware args, ...) must be ``frozen=True``, must not
   carry a mutable field default, and every mapping-typed field must
   go through ``_freeze`` in ``__post_init__`` — a raw dict field
   either raises at the first jit call (unhashable) or, frozen into an
   arbitrary-order tuple by a caller, mints one retrace per ordering.
6. TRACED candidate counts/widths at a jit boundary (ISSUE 16): the
   sparse engine's candidate width C is configuration (it rides the
   static CycleConfig), and per-pod candidate COUNTS vary per cycle —
   a jitted function taking ``num_candidates``/``c_width``/... as a
   traced argument specializes the [P, C] program per distinct value,
   one silent retrace per feasibility change.  Pad the candidate list
   to C with out-of-range sentinels instead (solver/candidates.py):
   pad the candidate list, don't trace the count.
"""

from __future__ import annotations

import ast
from typing import List, Set

from koordinator_tpu.analysis import jitscope
from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "retrace-hazard"

_NAMEY = ("name", "names")


def _is_namey(field: str) -> bool:
    return field in _NAMEY or any(
        field.endswith("_" + suffix) for suffix in _NAMEY
    )


# attribute reads that are concrete at trace time: branching on them
# specializes per shape bucket, it does not retrace per cycle
_TRACE_CONST_ATTRS = ("shape", "ndim", "dtype", "size")


def _exempt_names(test: ast.AST) -> Set[int]:
    """ids of Name nodes used only in trace-time-constant positions:
    ``x is (not) None`` compares, ``x.shape``/``.ndim``/``.dtype``/
    ``.size`` reads, and ``len(x)`` calls."""
    exempt: Set[int] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            if all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ) and any(
                isinstance(o, ast.Constant) and o.value is None
                for o in operands
            ):
                for o in operands:
                    if isinstance(o, ast.Name):
                        exempt.add(id(o))
        elif isinstance(node, ast.Attribute):
            if node.attr in _TRACE_CONST_ATTRS and isinstance(
                node.value, ast.Name
            ):
                exempt.add(id(node.value))
        elif isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "len":
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        exempt.add(id(arg))
    return exempt


def _tracer_branches(source: SourceFile, spec: jitscope.JitSpec) -> List[Violation]:
    out: List[Violation] = []
    traced = set(spec.params()) - spec.static_params()
    # closures run under this trace, so branches on the enclosing
    # traced params inside them count; nested JITTED defs get their own
    # pass with their own parameter namespace
    for node in jitscope.scope_walk(spec.func, into_closures=True):
        if isinstance(node, (ast.If, ast.While)):
            test, kind = node.test, type(node).__name__.lower()
        elif isinstance(node, ast.Assert):
            test, kind = node.test, "assert"
        else:
            continue
        exempt = _exempt_names(test)
        for name in ast.walk(test):
            if (
                isinstance(name, ast.Name)
                and isinstance(name.ctx, ast.Load)
                and name.id in traced
                and id(name) not in exempt
            ):
                out.append(
                    Violation(
                        rule=RULE,
                        path=source.path,
                        line=node.lineno,
                        message=(
                            f"Python {kind} on traced argument "
                            f"'{name.id}' inside jitted "
                            f"{spec.name}(); use lax.cond/jnp.where, or "
                            "declare it static if it is configuration"
                        ),
                    )
                )
                break
    return out


def _static_call_args(source: SourceFile) -> List[Violation]:
    """Unhashable / tuple-of-str values passed to static params of
    module-local jitted functions."""
    specs = {
        s.name: s for s in jitscope.jitted_defs(source.tree)
    }
    specs.update(jitscope.jit_assignments(source.tree))
    out: List[Violation] = []
    for node in ast.walk(source.tree):
        if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
            continue
        spec = specs.get(node.func.id)
        if spec is None:
            continue
        static = spec.static_params()
        if not static and not spec.static_nums:
            continue
        pos = spec.positional_params()
        candidates = []
        for i, arg in enumerate(node.args):
            pname = pos[i] if i < len(pos) else None
            if i in spec.static_nums or (pname and pname in static):
                candidates.append((pname or f"#{i}", arg))
        for kw in node.keywords:
            if kw.arg in static:
                candidates.append((kw.arg, kw.value))
        for pname, val in candidates:
            if isinstance(val, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                ast.DictComp, ast.SetComp)):
                out.append(
                    Violation(
                        rule=RULE,
                        path=source.path,
                        line=val.lineno,
                        message=(
                            f"unhashable {type(val).__name__.lower()} passed "
                            f"as static arg '{pname}' of {spec.name}(); jit "
                            "static args must be hashable"
                        ),
                    )
                )
            elif isinstance(val, ast.Tuple) and val.elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in val.elts
            ):
                out.append(
                    Violation(
                        rule=RULE,
                        path=source.path,
                        line=val.lineno,
                        message=(
                            f"tuple-of-str passed as static arg '{pname}' of "
                            f"{spec.name}(): the jit cache keys on the string "
                            "payload — one retrace per distinct value (the "
                            "PR-1 name-tuple bug); keep names host-side"
                        ),
                    )
                )
    return out


# cycle-batching knobs that select loop structure: traced values here
# mean one retrace per distinct width (rule docstring, shape 4)
_WAVE_STATIC_PARAMS = ("wave", "top_m")

# mesh-partitioning knobs (ISSUE 7): the mesh, the device count and the
# shard width all select the PARTITIONED program structure — traced,
# each distinct value re-specializes the sharded cycle silently
_MESH_STATIC_PARAMS = (
    "mesh", "device_count", "n_devices", "num_devices",
    "n_shards", "num_shards", "shard_width",
)

# incremental-rescore knobs (ISSUE 9): a dirty COUNT at a jit boundary
# is the same hazard shape — delta sizes vary per cycle, so a traced
# n_dirty/dirty_width specializes the rescore per distinct count, one
# silent retrace per delta size.  The count must never cross the
# boundary at all: dirty indices ride bucket-PADDED index vectors whose
# pad slots carry an out-of-range target dropped by mode="drop"
# (solver/incremental.py), exactly the delta scatter's discipline.
_DIRTY_STATIC_PARAMS = (
    "n_dirty", "num_dirty", "dirty_count", "dirty_width",
    "n_dirty_nodes", "n_dirty_pods",
)

# sparse candidate knobs (ISSUE 16): the candidate width selects the
# [P, C] program shape (configuration — it rides the static
# CycleConfig) and per-pod candidate counts vary with every
# feasibility change; traced, either one mints a retrace per distinct
# value.  The candidate list is padded to C with out-of-range
# sentinels (solver/candidates.py) so neither ever crosses a jit
# boundary: pad the candidate list, don't trace the count.
_CAND_STATIC_PARAMS = (
    "num_candidates", "n_candidates", "candidate_count",
    "candidate_width", "cand_width", "c_width",
)


def _traced_wave_knobs(source: SourceFile, spec: jitscope.JitSpec) -> List[Violation]:
    if spec.func is None:
        return []
    static = spec.static_params()
    out: List[Violation] = []
    for pname in spec.params():
        if pname in static:
            continue
        if pname in _WAVE_STATIC_PARAMS:
            out.append(
                Violation(
                    rule=RULE,
                    path=source.path,
                    line=spec.line,
                    message=(
                        f"jit boundary {spec.name}() takes '{pname}' as a "
                        "TRACED argument: the wave width selects loop "
                        "structure, so every distinct value retraces the "
                        "cycle silently; declare it in static_argnames "
                        "(it is configuration, like cfg)"
                    ),
                )
            )
        elif pname in _MESH_STATIC_PARAMS:
            out.append(
                Violation(
                    rule=RULE,
                    path=source.path,
                    line=spec.line,
                    message=(
                        f"jit boundary {spec.name}() takes '{pname}' as a "
                        "TRACED argument: the mesh/device-count/shard "
                        "width selects the partitioned program structure, "
                        "so every distinct value retraces the sharded "
                        "cycle silently; declare it in static_argnames "
                        "(it is configuration, like cfg)"
                    ),
                )
            )
        elif pname in _DIRTY_STATIC_PARAMS:
            out.append(
                Violation(
                    rule=RULE,
                    path=source.path,
                    line=spec.line,
                    message=(
                        f"jit boundary {spec.name}() takes '{pname}' as a "
                        "TRACED argument: delta sizes vary per cycle, so "
                        "a traced dirty count retraces the rescore per "
                        "distinct value; don't pass the count at all — "
                        "pad the dirty-index vector to a power-of-two "
                        "bucket with out-of-range slots mode=\"drop\" "
                        "discards (solver/incremental.py)"
                    ),
                )
            )
        elif pname in _CAND_STATIC_PARAMS:
            out.append(
                Violation(
                    rule=RULE,
                    path=source.path,
                    line=spec.line,
                    message=(
                        f"jit boundary {spec.name}() takes '{pname}' as a "
                        "TRACED argument: candidate counts vary with every "
                        "feasibility change (and the width is "
                        "configuration, like cfg), so each distinct value "
                        "retraces the sparse [P, C] program silently; "
                        "pad the candidate list, don't trace the count "
                        "(solver/candidates.py pads to C with "
                        "out-of-range sentinels)"
                    ),
                )
            )
    return out


def _shard_map_body_knobs(source: SourceFile) -> List[Violation]:
    """Mesh knobs in a shard_map BODY's parameter list (rule shape 4,
    the shard_map boundary): operands of ``shard_map`` are traced
    per-shard arrays, so a body taking ``mesh``/``n_devices``/... as a
    parameter would receive the partitioning configuration as a traced
    value.  The mesh rides the ``shard_map(..., mesh=)`` binding (or
    the closure); flag the def.

    Resolution is LEXICALLY SCOPED: a ``shard_map(body, ...)`` call
    resolves ``body`` among the defs of its own enclosing scope first,
    then the module scope — a file-wide name table would collide on
    same-named nested defs (``body`` is the natural name; two unrelated
    ``body`` defs in different functions must not flag each other)."""

    def scope_defs_and_calls(scope_body):
        """One lexical scope's direct defs and the calls in it, NOT
        descending into nested function bodies (each gets its own
        pass)."""
        defs, calls = {}, []
        stack = list(scope_body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node  # visible here; body is its own scope
                continue
            if isinstance(node, ast.Call):
                calls.append(node)
            stack.extend(ast.iter_child_nodes(node))
        return defs, calls

    module_defs, _ = scope_defs_and_calls(source.tree.body)
    scopes = [source.tree] + [
        n for n in ast.walk(source.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    out: List[Violation] = []
    seen = set()
    for scope in scopes:
        defs, calls = scope_defs_and_calls(scope.body)
        for node in calls:
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            if not name.endswith("shard_map") and name != "shard_map_compat":
                continue
            if not node.args or not isinstance(node.args[0], ast.Name):
                continue
            body = defs.get(node.args[0].id) or module_defs.get(
                node.args[0].id
            )
            if body is None or id(body) in seen:
                continue
            seen.add(id(body))
            params = [a.arg for a in (
                body.args.posonlyargs + body.args.args + body.args.kwonlyargs
            )]
            for pname in params:
                if pname in _MESH_STATIC_PARAMS:
                    out.append(
                        Violation(
                            rule=RULE,
                            path=source.path,
                            line=body.lineno,
                            message=(
                                f"shard_map body {body.name}() takes "
                                f"'{pname}' as a parameter: shard_map "
                                "operands are traced per-shard values, "
                                "so the mesh/device-count/shard width "
                                "would retrace the partitioned program "
                                "per value; bind it via "
                                "shard_map(..., mesh=) or the closure "
                                "instead"
                            ),
                        )
                    )
    return out


# annotation identifiers that mean "this field is a mapping and must be
# frozen to a sorted tuple before it can be a static jit argument"
_MAPPINGY_TYPES = {"ResMap", "Mapping", "MutableMapping", "Dict", "dict"}


def _annotation_names(ann) -> Set[str]:
    """Identifier names mentioned by a field annotation — handles
    Name/Attribute/Subscript forms and string annotations ("X | None")."""
    import re as _re

    if ann is None:
        return set()
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return set(_re.findall(r"[A-Za-z_][A-Za-z0-9_]*", ann.value))
    names: Set[str] = set()
    for node in ast.walk(ann):
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, ast.Attribute):
            names.add(node.attr)
    return names


def _dataclass_frozen(cls: ast.ClassDef):
    """(is_dataclass, frozen) from the decorator list."""
    for dec in cls.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = target.attr if isinstance(target, ast.Attribute) else (
            target.id if isinstance(target, ast.Name) else ""
        )
        if name != "dataclass":
            continue
        frozen = False
        if isinstance(dec, ast.Call):
            for kw in dec.keywords:
                if kw.arg == "frozen" and isinstance(kw.value, ast.Constant):
                    frozen = bool(kw.value.value)
        return True, frozen
    return False, False


def _frozen_fields(cls: ast.ClassDef) -> Set[str]:
    """Field names ``__post_init__`` re-binds through ``_freeze``:
    ``object.__setattr__(self, "field", _freeze(...))``."""
    out: Set[str] = set()
    for node in cls.body:
        if not (
            isinstance(node, ast.FunctionDef)
            and node.name == "__post_init__"
        ):
            continue
        for call in ast.walk(node):
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "__setattr__"
                and len(call.args) == 3
                and isinstance(call.args[1], ast.Constant)
                and isinstance(call.args[1].value, str)
            ):
                continue
            value = call.args[2]
            if (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id == "_freeze"
            ):
                out.add(call.args[1].value)
    return out


def _term_config_classes(source: SourceFile):
    """The CycleConfig dataclass plus every module-local dataclass
    reachable from its field annotations (the term configs of ISSUE 15,
    LoadAwareArgs, ...).  Empty when the file defines no CycleConfig."""
    classes = {
        n.name: n for n in ast.walk(source.tree)
        if isinstance(n, ast.ClassDef)
    }
    if "CycleConfig" not in classes:
        return {}
    reach = {}
    queue = ["CycleConfig"]
    while queue:
        name = queue.pop()
        if name in reach:
            continue
        cls = classes.get(name)
        if cls is None:
            continue
        reach[name] = cls
        for node in cls.body:
            if isinstance(node, ast.AnnAssign):
                for ref in _annotation_names(node.annotation):
                    if ref in classes and ref not in reach:
                        queue.append(ref)
    return reach


def _term_config_fields(source: SourceFile) -> List[Violation]:
    """Rule shape 5 (ISSUE 15): CycleConfig and its term configs are
    STATIC jit arguments — unfrozen dataclasses, mutable field
    defaults, and mapping-typed fields that never pass through
    ``_freeze`` in ``__post_init__`` all fail lint."""
    out: List[Violation] = []
    for name, cls in _term_config_classes(source).items():
        is_dc, frozen = _dataclass_frozen(cls)
        if not is_dc:
            continue  # a plain class is not a config dataclass
        if not frozen:
            out.append(Violation(
                rule=RULE, path=source.path, line=cls.lineno,
                message=(
                    f"config dataclass {name} reachable from CycleConfig "
                    "is not frozen=True: CycleConfig rides jit as a "
                    "static argument, so every nested config must be "
                    "immutable and hashable"
                ),
            ))
        freezes = _frozen_fields(cls)
        for node in cls.body:
            if not isinstance(node, ast.AnnAssign) or not isinstance(
                node.target, ast.Name
            ):
                continue
            field = node.target.id
            if isinstance(node.value, (ast.List, ast.Dict, ast.Set,
                                       ast.ListComp, ast.DictComp,
                                       ast.SetComp)):
                out.append(Violation(
                    rule=RULE, path=source.path, line=node.lineno,
                    message=(
                        f"{name}.{field} has a mutable "
                        f"{type(node.value).__name__.lower()} default: "
                        "term-config fields must be hashable (freeze "
                        "mappings to sorted tuples via _freeze)"
                    ),
                ))
            ann_names = _annotation_names(node.annotation)
            if ann_names & _MAPPINGY_TYPES and field not in freezes:
                # a default that is already a _freeze(...) call AND
                # never reassigned is equally safe only if callers
                # cannot pass a raw dict — they can, so the
                # __post_init__ freeze is required regardless
                out.append(Violation(
                    rule=RULE, path=source.path, line=node.lineno,
                    message=(
                        f"{name}.{field} is mapping-typed but "
                        "__post_init__ never passes it through "
                        "_freeze: a caller-supplied dict makes the "
                        "config unhashable at the jit boundary "
                        "(mappings must go through _freeze)"
                    ),
                ))
    return out


def _pytree_metadata(source: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(source.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else ""
        )
        if attr != "register_dataclass":
            continue
        for kw in node.keywords:
            if kw.arg != "meta_fields":
                continue
            for field in jitscope._literal_strs(kw.value):
                if _is_namey(field):
                    out.append(
                        Violation(
                            rule=RULE,
                            path=source.path,
                            line=kw.value.lineno,
                            message=(
                                f"pytree meta field '{field}' looks like an "
                                "object-name payload: static metadata keys "
                                "every jit cache on it, so a changed name "
                                "retraces the cycle (the PR-1 bug); carry "
                                "names host-side or tag with a reason"
                            ),
                        )
                    )
    return out


def check(source: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    for spec in jitscope.jitted_defs(source.tree):
        out.extend(_tracer_branches(source, spec))
        out.extend(_traced_wave_knobs(source, spec))
    for spec in jitscope.jit_assignments(source.tree).values():
        out.extend(_traced_wave_knobs(source, spec))
    out.extend(_shard_map_body_knobs(source))
    out.extend(_static_call_args(source))
    out.extend(_pytree_metadata(source))
    out.extend(_term_config_fields(source))
    return out
