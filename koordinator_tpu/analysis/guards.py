"""koordlint rule: ``unguarded-shared-state`` (ISSUE 17).

Guarded-state inference, the static shadow of ``go test -race``: if a
class owns a lock and writes an instance attribute under it at one or
more sites, every OTHER access to that attribute in the same class is
presumed to need the lock too — the PR-14 brownout widen-memoization
race and the PR-12 breaker verdict race were both exactly this shape,
caught by hand in review.

Mechanics per class (classes that create no ``threading.Lock/RLock/
Condition`` — plain or through the ``obs.lockwitness`` factories — are
out of scope):

* an attribute is GUARDED when a non-init method writes it inside a
  ``with self._lock:`` block (any of the class's locks counts — the
  rule checks locked-vs-lockfree, not which lock; the lock-order graph
  owns the which-lock question) or after a lexical ``.acquire()``;
* a lock-free WRITE to a guarded attribute outside ``__init__``/
  ``__post_init__`` always trips — two writers race regardless of how
  atomic each store is;
* a lock-free READ trips only when some write MUTATES the value in
  place (``self.x[k] = v``, ``self.x.append(...)`` and friends):
  iterating a dict/list another thread is mutating throws; reading an
  attribute that is only ever REBOUND (``self.x = new`` /
  ``self.x += 1``) observes a consistent value under the GIL — the
  immutable-rebinding / atomic-read exemptions the repo already leans
  on (brownout memo swaps, stats counters read by scrapes).

Exemptions, matching repo convention:

* ``__init__`` / ``__post_init__`` writes (construction happens-before
  publication);
* methods named ``*_locked`` (the caller-holds-the-lock convention
  lock-held-dispatch already keys on);
* nested functions and lambdas (closures run under the dispatcher's
  locks elsewhere — the lock graph models those seams);
* everything else needs a REASONED suppression:
  ``# koordlint: disable=unguarded-shared-state(reason: ...)`` — the
  suppression audit (``--suppressions``) fails tags without a reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "unguarded-shared-state"

_LOCK_KINDS = ("Lock", "RLock", "Condition")
_FACTORIES = ("witness_lock", "witness_rlock", "witness_condition")

# receiver-mutating method names: a call ``self.x.append(...)`` edits
# the object in place, so lock-free readers can observe a torn
# iteration (RuntimeError) — unlike a rebind, which swaps atomically
_MUTATORS = frozenset((
    "append", "appendleft", "add", "insert", "extend", "update", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "sort",
    "reverse", "setdefault", "__setitem__", "__delitem__",
))

_INIT_METHODS = ("__init__", "__post_init__")


def _terminal_name(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_lock_creation(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    term = _terminal_name(value.func)
    return term in _LOCK_KINDS or term in _FACTORIES


def _self_attr(expr: ast.AST) -> Optional[str]:
    """``self.x`` -> ``x``."""
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return expr.attr
    return None


class _Access:
    __slots__ = ("attr", "line", "locked", "kind", "init")

    def __init__(self, attr: str, line: int, locked: bool, kind: str,
                 init: bool):
        self.attr = attr
        self.line = line
        self.locked = locked
        self.kind = kind  # "read" | "rebind" | "mutate"
        self.init = init


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attribute names bound to lock objects anywhere in the class."""
    out: Set[str] = set()
    for item in cls.body:
        if (isinstance(item, ast.Assign) and len(item.targets) == 1
                and isinstance(item.targets[0], ast.Name)
                and _is_lock_creation(item.value)):
            out.add(item.targets[0].id)
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(item):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and _is_lock_creation(node.value)):
                attr = _self_attr(node.targets[0])
                if attr is not None:
                    out.add(attr)
    return out


def _held_expr(expr: ast.AST, locks: Set[str]) -> bool:
    """Is this with-item / acquire receiver one of the class locks?"""
    attr = _self_attr(expr)
    return attr is not None and attr in locks


def check(source: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(source.tree):
        if isinstance(node, ast.ClassDef):
            out.extend(_check_class(source, node))
    # dedup (nested classes walked twice by ast.walk are not, but keep
    # the lockdispatch convention anyway)
    seen: Set[tuple] = set()
    uniq: List[Violation] = []
    for v in out:
        key = (v.path, v.line, v.message)
        if key not in seen:
            seen.add(key)
            uniq.append(v)
    uniq.sort(key=lambda v: (v.path, v.line))
    return uniq


def _check_class(source: SourceFile, cls: ast.ClassDef) -> List[Violation]:
    locks = _lock_attrs(cls)
    if not locks:
        return []
    accesses: List[_Access] = []
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name.endswith("_locked"):
            continue  # caller-holds-the-lock convention
        init = item.name in _INIT_METHODS
        _collect(item.body, locks, held=False, init=init, out=accesses)

    guarded: Set[str] = {
        a.attr for a in accesses
        if a.locked and not a.init and a.kind in ("rebind", "mutate")
    } - locks
    if not guarded:
        return []
    mutated: Set[str] = {
        a.attr for a in accesses if a.kind == "mutate"
    }
    out: List[Violation] = []
    for a in accesses:
        if a.attr not in guarded or a.locked or a.init:
            continue
        if a.kind in ("rebind", "mutate"):
            out.append(Violation(
                RULE, source.path, a.line,
                f"lock-free write to {cls.name}.{a.attr}, which "
                f"{cls.name} elsewhere writes under its lock — two "
                "writers race; take the lock here or suppress with the "
                "reason the race is benign",
            ))
        elif a.attr in mutated:
            out.append(Violation(
                RULE, source.path, a.line,
                f"lock-free read of {cls.name}.{a.attr}, which is "
                "mutated in place under the lock elsewhere — an "
                "iteration here can see a mid-mutation structure; take "
                "the lock, snapshot under it, or suppress with a reason",
            ))
    return out


def _collect(stmts: List[ast.stmt], locks: Set[str], held: bool,
             init: bool, out: List[_Access]) -> None:
    """Walk one statement block tracking whether a class lock is held
    lexically (``with self._lock:`` or after ``self._lock.acquire()``)."""
    acquired_here = False
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # closures run elsewhere; the lock graph owns them
        now_held = held or acquired_here
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = now_held
            for item in stmt.items:
                _scan_expr(item.context_expr, locks, now_held, init, out)
                if _held_expr(item.context_expr, locks):
                    inner = True
            _collect(list(stmt.body), locks, inner, init, out)
            continue
        for expr in ast.iter_child_nodes(stmt):
            if isinstance(expr, ast.expr):
                _scan_expr(expr, locks, now_held, init, out)
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                _scan_target(target, locks, now_held, init, out)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            _scan_target(stmt.target, locks, now_held, init, out,
                         aug=isinstance(stmt, ast.AugAssign))
        elif isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                _scan_target(target, locks, now_held, init, out)
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                _collect(list(sub), locks, now_held, init, out)
        for handler in getattr(stmt, "handlers", ()) or ():
            _collect(list(handler.body), locks, now_held, init, out)
        if _acquires_lock(stmt, locks):
            acquired_here = True


def _acquires_lock(stmt: ast.stmt, locks: Set[str]) -> bool:
    for node in ast.walk(stmt):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
                and _held_expr(node.func.value, locks)):
            return True
    return False


def _scan_target(target: ast.AST, locks: Set[str], held: bool,
                 init: bool, out: List[_Access], aug: bool = False) -> None:
    attr = _self_attr(target)
    if attr is not None:
        if attr not in locks:
            out.append(_Access(attr, target.lineno, held, "rebind", init))
        return
    if isinstance(target, ast.Subscript):
        attr = _self_attr(target.value)
        if attr is not None and attr not in locks:
            out.append(_Access(attr, target.lineno, held, "mutate", init))
        else:
            _scan_expr(target, locks, held, init, out)
        return
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            _scan_target(elt, locks, held, init, out)


def _scan_expr(expr: ast.AST, locks: Set[str], held: bool, init: bool,
               out: List[_Access]) -> None:
    """Record reads (and mutator-call mutations) of self attributes."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                recv = _self_attr(func.value)
                if recv is not None and recv not in locks:
                    kind = ("mutate" if func.attr in _MUTATORS
                            else "read")
                    out.append(_Access(recv, node.lineno, held, kind, init))
                    stack.extend(node.args)
                    stack.extend(kw.value for kw in node.keywords)
                    continue
            stack.extend(ast.iter_child_nodes(node))
            continue
        attr = _self_attr(node)
        if attr is not None:
            if attr not in locks and not isinstance(
                    getattr(node, "ctx", None), (ast.Store, ast.Del)):
                out.append(_Access(attr, node.lineno, held, "read", init))
            continue
        stack.extend(ast.iter_child_nodes(node))
