"""donation-safety: no reads of a buffer after it was donated.

The solver/resident.py bug class: ``_scatter_flat`` donates its first
argument (the pre-delta resident buffer is dead once the new generation
commits), so any later read of that name in the calling scope touches a
buffer XLA may already have aliased over — silently wrong values on
backends with aliasing, a use-after-donate error on others.

Flow-insensitive by line number within one function scope: a read of the
donated name strictly after the donating call is a violation unless the
name was re-bound first (the ``arr = scatter(arr, ...)`` idiom re-binds
on the call line itself, which counts)."""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, List

from koordinator_tpu.analysis import jitscope
from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "donation-safety"


@dataclasses.dataclass(frozen=True)
class _KnownDonor:
    """A donating helper whose jit wrapper lives in ANOTHER module —
    invisible to jitscope's module-local scan, so its donation contract
    is declared here by (positional param order, donated param names).
    ISSUE 9 extends the rule over the resident-score-tensor scatter
    call sites this way: bridge/server.py donates the resident scores
    buffer to solver/incremental.py's ``rescore_dirty`` exactly like
    bridge/state.py donates snapshot buffers to ``apply_flat_delta``."""

    positional: tuple
    donated: frozenset

    def positional_params(self):
        return list(self.positional)

    def donated_params(self):
        return set(self.donated)


# exported donating helpers by callable name; a call site in ANY scanned
# module is checked against the donated-argument contract.  Names are
# specific enough that a same-named unrelated local function is
# implausible — and a module-LOCAL jit def of the same name wins (the
# dict update order below).
_KNOWN_DONORS = {
    # solver/resident.py: donates the pre-delta resident buffer
    "apply_flat_delta": _KnownDonor(
        positional=("arr", "idx", "val", "mesh"),
        donated=frozenset({"arr"}),
    ),
    # solver/incremental.py: donates the pre-rescore resident scores
    # tensor (feasible is deliberately NOT donated — in-flight
    # readbacks hold it; see the module docstring)
    "rescore_dirty": _KnownDonor(
        positional=("snapshot", "scores", "feasible", "node_rows",
                    "pod_rows", "cfg", "mesh"),
        donated=frozenset({"scores"}),
    ),
}


def _scopes(tree: ast.AST) -> Iterator[ast.AST]:
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def check(source: SourceFile) -> List[Violation]:
    # the known cross-module donors apply everywhere EXCEPT where the
    # module defines the name itself — a local def's declared donate
    # args (possibly none) are the truth for its own module
    donors = dict(_KNOWN_DONORS)
    for node in ast.walk(source.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            donors.pop(node.name, None)
    donors.update(jitscope.donating_callables(source.tree))
    out: List[Violation] = []
    for scope in _scopes(source.tree):
        # gather loads / stores of every name in this scope, by line.
        # An AugAssign target (`buf += 1`) READS the old value even
        # though its ctx is Store: count it as a load and NOT as a
        # forgiving rebind — `buf += 1` after donating buf is itself a
        # read-after-donate, and must not silence later reads either.
        loads: List[ast.Name] = []
        stores: List[ast.Name] = []
        calls: List[ast.Call] = []
        aug_target_ids = set()
        for node in jitscope.scope_walk(scope):
            if isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name
            ):
                aug_target_ids.add(id(node.target))
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load) or id(node) in aug_target_ids:
                    loads.append(node)
                else:
                    stores.append(node)
            elif isinstance(node, ast.Call):
                calls.append(node)
        for call in calls:
            if not isinstance(call.func, ast.Name):
                continue
            spec = donors.get(call.func.id)
            if spec is None:
                continue
            if isinstance(spec, jitscope.JitSpec) and spec.func is None:
                continue
            pos = spec.positional_params()
            donated_idx = sorted(
                i for i, p in enumerate(pos) if p in spec.donated_params()
            )
            donated_args: List[ast.Name] = []
            for i in donated_idx:
                if i < len(call.args) and isinstance(call.args[i], ast.Name):
                    donated_args.append(call.args[i])
            for kw in call.keywords:
                if kw.arg in spec.donated_params() and isinstance(
                    kw.value, ast.Name
                ):
                    donated_args.append(kw.value)
            end = call.end_lineno or call.lineno
            end_col = call.end_col_offset or 0
            own = {id(n) for n in ast.walk(call)}
            for arg in donated_args:
                # first re-bind after the call forgives later reads;
                # a store ON the call line is the x = f(x) idiom
                rebinds = [
                    s.lineno for s in stores
                    if s.id == arg.id and s.lineno >= call.lineno
                ]
                horizon = min(rebinds) if rebinds else None
                for load in loads:
                    if load.id != arg.id or id(load) in own:
                        continue
                    # lexicographically after the call: later line, or
                    # the call's end line past its closing paren (the
                    # `return scatter(buf, ...), buf.sum()` form)
                    after = load.lineno > end or (
                        load.lineno == end and load.col_offset > end_col
                    )
                    if not after:
                        continue
                    if horizon is not None and load.lineno >= horizon:
                        continue
                    out.append(
                        Violation(
                            rule=RULE,
                            path=source.path,
                            line=load.lineno,
                            message=(
                                f"'{arg.id}' is read after being donated to "
                                f"{call.func.id}() on line {call.lineno}; the "
                                "buffer may already be aliased over "
                                "(re-bind the name or copy before the call)"
                            ),
                        )
                    )
    return out
