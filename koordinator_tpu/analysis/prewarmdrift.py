"""koordlint rule: ``prewarm-drift`` (ISSUE 20).

The AOT prewarm replay set (obs/prewarm.py) is only useful while it
covers the serving path: a ``@devprof.boundary``-registered jit
boundary that neither ``PREWARM_BOUNDARIES`` nor ``PREWARM_EXCLUDED``
names is a signature set that silently rots — its compiles land back
on the cold path every boot and nobody notices until the p99 does.
This rule makes the coverage STATIC, the metrics-doc-drift shape
applied to the prewarm contract: every boundary registration in the
repo is diffed against the two tables in obs/prewarm.py, in BOTH
directions.

* a registered boundary absent from both tables flags the
  registration line (decide: replayable, or excluded with a reason);
* a boundary listed in BOTH tables flags the prewarm.py entry (the
  tables partition the boundary space — one name, one verdict);
* a table entry naming a boundary no ``@devprof.boundary`` registers
  flags the prewarm.py entry (the replay set promises a boundary the
  ledger never mints — a renamed or deleted boundary left a stale
  row behind).

All diff functions take source TEXT so tests can seed one-sided
regressions (the wire-contract convention); ``check_repo`` walks the
real tree.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.analysis.core import Violation, iter_python_files

RULE = "prewarm-drift"

PREWARM_PATH = os.path.join("koordinator_tpu", "obs", "prewarm.py")


def _boundary_name(deco: ast.AST) -> Optional[str]:
    """The string-literal name of a ``@devprof.boundary("...")`` (or
    bare ``@boundary("...")``) decorator, else None."""
    if not isinstance(deco, ast.Call):
        return None
    f = deco.func
    if not (
        (isinstance(f, ast.Attribute) and f.attr == "boundary")
        or (isinstance(f, ast.Name) and f.id == "boundary")
    ):
        return None
    if deco.args and isinstance(deco.args[0], ast.Constant) and isinstance(
        deco.args[0].value, str
    ):
        return deco.args[0].value
    return None


def parse_boundary_registrations(
    py_text: str,
) -> List[Tuple[str, int]]:
    """``(boundary_name, line)`` for every ``@devprof.boundary``
    decorator with a string-literal name in one file's source text.
    (AST-based, so a decorator spelled inside a docstring example does
    not count — only real registrations do.)"""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(ast.parse(py_text)):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            name = _boundary_name(deco)
            if name is not None:
                out.append((name, node.lineno))
    return out


def parse_prewarm_tables(
    prewarm_text: str,
) -> Tuple[Dict[str, int], Dict[str, int]]:
    """``(replayable, excluded)`` name->line maps parsed from
    obs/prewarm.py source text: the ``PREWARM_BOUNDARIES`` tuple and
    the keys of the ``PREWARM_EXCLUDED`` dict."""
    replayable: Dict[str, int] = {}
    excluded: Dict[str, int] = {}
    tree = ast.parse(prewarm_text)
    for node in tree.body:
        targets = (
            node.targets
            if isinstance(node, ast.Assign)
            else [node.target] if isinstance(node, ast.AnnAssign) else []
        )
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        value = getattr(node, "value", None)
        if "PREWARM_BOUNDARIES" in names and isinstance(
            value, (ast.Tuple, ast.List)
        ):
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    replayable[elt.value] = elt.lineno
        elif "PREWARM_EXCLUDED" in names and isinstance(value, ast.Dict):
            for key in value.keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    excluded[key.value] = key.lineno
    return replayable, excluded


def diff_prewarm(
    registrations: List[Tuple[str, str, int]],
    prewarm_text: str,
    prewarm_path: str = PREWARM_PATH,
) -> List[Violation]:
    """Diff ``(name, path, line)`` boundary registrations against the
    prewarm tables, both directions."""
    replayable, excluded = parse_prewarm_tables(prewarm_text)
    if not replayable and not excluded:
        return [Violation(
            RULE, prewarm_path, 0,
            "no PREWARM_BOUNDARIES / PREWARM_EXCLUDED entries parsed "
            "from the prewarm module — the tables moved; update "
            "prewarmdrift.py's parser with them",
        )]
    out: List[Violation] = []
    registered = {name for name, _, _ in registrations}
    for name, path, line in sorted(registrations):
        in_replay = name in replayable
        in_excluded = name in excluded
        if not in_replay and not in_excluded:
            out.append(Violation(
                RULE, path, line,
                f"boundary {name!r} is registered with the launch "
                f"ledger but absent from both prewarm tables in "
                f"{prewarm_path} — its signatures never make the AOT "
                "replay set, so every boot pays its compile cold.  "
                "Add it to PREWARM_BOUNDARIES, or to PREWARM_EXCLUDED "
                "with the reason it cannot replay",
            ))
        elif in_replay and in_excluded:
            out.append(Violation(
                RULE, prewarm_path, replayable[name],
                f"boundary {name!r} appears in BOTH PREWARM_BOUNDARIES "
                "and PREWARM_EXCLUDED — the tables partition the "
                "boundary space; keep exactly one verdict",
            ))
    for name, line in sorted(replayable.items()):
        if name not in registered:
            out.append(Violation(
                RULE, prewarm_path, line,
                f"PREWARM_BOUNDARIES lists {name!r} but no "
                "@devprof.boundary registration mints that name — a "
                "renamed or deleted boundary left a stale replay row; "
                "remove it or fix the name",
            ))
    for name, line in sorted(excluded.items()):
        if name not in registered:
            out.append(Violation(
                RULE, prewarm_path, line,
                f"PREWARM_EXCLUDED lists {name!r} but no "
                "@devprof.boundary registration mints that name — a "
                "renamed or deleted boundary left a stale exclusion; "
                "remove it or fix the name",
            ))
    return out


def check_repo(root: str) -> List[Violation]:
    prewarm_abs = os.path.join(root, PREWARM_PATH)
    if not os.path.exists(prewarm_abs):
        return [Violation(
            RULE, PREWARM_PATH, 0,
            "obs/prewarm.py not found — the prewarm tables are the "
            "contract the boundary registrations diff against",
        )]
    with open(prewarm_abs, "r", encoding="utf-8") as f:
        prewarm_text = f.read()
    registrations: List[Tuple[str, str, int]] = []
    scan_root = os.path.join(root, "koordinator_tpu")
    for path in iter_python_files(scan_root):
        rel = os.path.relpath(path, root)
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
            pairs = parse_boundary_registrations(text)
        except (OSError, SyntaxError):
            continue
        for name, line in pairs:
            registrations.append((name, rel, line))
    return diff_prewarm(registrations, prewarm_text)
