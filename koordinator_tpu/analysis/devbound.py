"""Rule ``unregistered-jit-boundary``: serving-path jit boundaries must
register with the XLA launch ledger (ISSUE 19, obs/devprof.py).

Device-time truth only holds if every launch site is attributed: a jit
boundary added under ``solver/``, ``parallel/`` or ``bridge/`` without a
``@devprof.boundary("...")`` decorator silently escapes the compile
ledger, the device-time sampler, the /metrics families and the /healthz
``device`` block — the waterfall then under-reports device time and the
operator chases a phantom host-side gap.  The rule enforces, lexically
and module-locally (same philosophy as the donation/retrace rules):

1. every jitted DEF in a serving-path module carries a
   ``devprof.boundary("<name>")`` decorator;
2. the boundary decorator sits ABOVE the jit decorator (decorators apply
   bottom-up, so the wrapper must receive the jitted callable — below it
   the AOT ``.lower()`` capture has nothing to lower);
3. the boundary name is a string literal (the ledger keys and the lint
   greps both need a static name);
4. ``name = jax.jit(fn)`` call-form assignments are flagged outright —
   the call form cannot carry the decorator; spell it as a decorated def
   or suppress with a reason;
5. a ``shard_map`` / ``shard_map_compat`` launch outside any jitted def
   is its own unattributed device launch and is flagged (the
   version-compat shim in parallel/mesh.py carries the one reasoned
   suppression: its callers register at their own jit boundary).

Modules outside the serving path (tests, harness, obs itself) are out of
scope: their launches never sit on the Score/Assign path the ledger
attributes.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from koordinator_tpu.analysis.core import SourceFile, Violation
from koordinator_tpu.analysis.jitscope import (
    _is_jit_ref,
    _jit_call_spec,
    is_jitted_def,
    jit_assignments,
)

RULE = "unregistered-jit-boundary"

# directory parts that mark a module as serving-path
_SCOPE_PARTS = {"solver", "parallel", "bridge"}

# spellings of the shard-mapped launch entry point the repo uses
_SHARD_MAP_NAMES = {"shard_map", "shard_map_compat", "_shard_map"}


def _in_scope(path: str) -> bool:
    parts = set(path.replace("\\", "/").split("/"))
    return bool(parts & _SCOPE_PARTS)


def _boundary_decorator(deco: ast.AST) -> Optional[ast.Call]:
    """Match ``@devprof.boundary("...")`` (or a bare ``@boundary(...)``
    from a ``from ... import boundary``)."""
    if not isinstance(deco, ast.Call):
        return None
    f = deco.func
    if isinstance(f, ast.Attribute) and f.attr == "boundary":
        return deco
    if isinstance(f, ast.Name) and f.id == "boundary":
        return deco
    return None


def _is_jit_deco(deco: ast.AST) -> bool:
    return _is_jit_ref(deco) or _jit_call_spec(deco) is not None


def _check_def(path: str, node: ast.FunctionDef) -> List[Violation]:
    out: List[Violation] = []
    boundary_at: Optional[int] = None
    jit_at: Optional[int] = None
    boundary_call: Optional[ast.Call] = None
    for i, deco in enumerate(node.decorator_list):
        if boundary_at is None:
            call = _boundary_decorator(deco)
            if call is not None:
                boundary_at, boundary_call = i, call
                continue
        if jit_at is None and _is_jit_deco(deco):
            jit_at = i
    if boundary_at is None:
        out.append(Violation(
            rule=RULE, path=path, line=node.lineno,
            message=f"jitted def {node.name}() is a serving-path launch "
            "site with no @devprof.boundary(...) registration: its "
            "compiles, retraces and device time escape the launch "
            "ledger (docs/OBSERVABILITY.md \"Device-time truth\").  "
            "Register it, or suppress with a reason if it truly never "
            "runs on the Score/Assign path",
        ))
        return out
    if jit_at is not None and boundary_at > jit_at:
        out.append(Violation(
            rule=RULE, path=path, line=node.lineno,
            message=f"{node.name}(): @devprof.boundary sits BELOW the "
            "jit decorator — decorators apply bottom-up, so the ledger "
            "wraps the raw Python function and the AOT compile capture "
            "has nothing to .lower().  Move the boundary decorator "
            "above the jit decorator",
        ))
    args = boundary_call.args if boundary_call is not None else []
    if not args or not (
        isinstance(args[0], ast.Constant) and isinstance(args[0].value, str)
    ):
        out.append(Violation(
            rule=RULE, path=path, line=node.lineno,
            message=f"{node.name}(): devprof.boundary name must be a "
            "string literal — the ledger, the /metrics labels and this "
            "lint all key on a static boundary name",
        ))
    return out


def _registered_jitted_defs(tree: ast.AST) -> List[ast.FunctionDef]:
    return [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and is_jitted_def(n)
    ]


def _shard_map_calls(tree: ast.AST) -> List[ast.Call]:
    out = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        name = None
        if isinstance(f, ast.Name):
            name = f.id
        elif isinstance(f, ast.Attribute):
            name = f.attr
        if name in _SHARD_MAP_NAMES:
            out.append(n)
    return out


def check(source: SourceFile) -> List[Violation]:
    if not _in_scope(source.path):
        return []
    out: List[Violation] = []
    jitted = _registered_jitted_defs(source.tree)
    for node in jitted:
        out.extend(_check_def(source.path, node))
    for name, spec in jit_assignments(source.tree).items():
        out.append(Violation(
            rule=RULE, path=source.path, line=spec.line,
            message=f"{name} = jax.jit(...) call-form boundary cannot "
            "carry a @devprof.boundary registration — spell it as a "
            "decorated def so the launch ledger attributes it, or "
            "suppress with a reason",
        ))
    # shard_map launches must sit lexically inside SOME jitted def (the
    # def-level check above owns whether that def is registered — do not
    # double-report); outside any jit they are unattributed launches.
    inside: Set[Tuple[int, int]] = set()
    for node in jitted:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                inside.add((sub.lineno, sub.col_offset))
    for call in _shard_map_calls(source.tree):
        if (call.lineno, call.col_offset) in inside:
            continue
        out.append(Violation(
            rule=RULE, path=source.path, line=call.lineno,
            message="shard_map launch outside any jitted def: this is "
            "its own device launch with no ledger attribution.  Wrap "
            "it in a registered @devprof.boundary jit boundary, or "
            "suppress with a reason",
        ))
    return out
