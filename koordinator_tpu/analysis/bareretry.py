"""koordlint rule: ``bare-retry`` (ISSUE 11).

A retry loop that sleeps a FIXED constant has three production failure
modes the shared policy (koordinator_tpu/replication/retry.py) exists
to close: synchronized wake-ups re-arrive as a thundering herd at the
moment a restarted peer is coldest (no jitter), a dead peer is polled
at full rate forever (no exponential cap), and the loop turns an
outage into an indistinguishable-from-deadlock hang (no deadline
budget).  The tier's own history is the motivation: the PR-8
replication subscriber redialed on a bare 50 ms sleep until this PR
moved it onto ``BackoffPolicy``.

Shape flagged: inside a ``while``/``for`` loop that also contains an
``except`` handler (the retry-loop signature — the loop is eating
failures and going around again), a call to ``time.sleep(<numeric
literal>)`` (or a bare ``sleep(<literal>)`` from ``from time import
sleep``).  Computed delays (``sleep(backoff.delay_ms(i) / 1000)``,
``event.wait(...)``) are not flagged — the rule targets the provably
fixed cadence, not every pause.

Deliberate fixed-cadence poll loops (a parent-liveness watch, a status
file poll) suppress with a reason::

    time.sleep(0.5)  # koordlint: disable=bare-retry(parent-liveness poll, not a retry)
"""

from __future__ import annotations

import ast
from typing import List

from koordinator_tpu.analysis.core import SourceFile, Violation

RULE = "bare-retry"


def _is_sleep_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "sleep":
        return isinstance(fn.value, ast.Name) and fn.value.id == "time"
    if isinstance(fn, ast.Name) and fn.id == "sleep":
        return True
    return False


def _fixed_delay(node: ast.Call):
    """The numeric literal a sleep call pins, or None when computed."""
    if not node.args or node.keywords:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(
        arg.value, (int, float)
    ) and not isinstance(arg.value, bool):
        return arg.value
    return None


def check(source: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    seen = set()
    for loop in ast.walk(source.tree):
        if not isinstance(loop, (ast.While, ast.For)):
            continue
        has_except = any(
            isinstance(n, ast.ExceptHandler) for n in ast.walk(loop)
        )
        if not has_except:
            continue
        for n in ast.walk(loop):
            if not (isinstance(n, ast.Call) and _is_sleep_call(n)):
                continue
            delay = _fixed_delay(n)
            if delay is None:
                continue
            if n.lineno in seen:
                continue  # nested loops both walk the same call
            seen.add(n.lineno)
            out.append(Violation(
                rule=RULE,
                path=source.path,
                line=n.lineno,
                message=(
                    f"retry loop sleeps a fixed {delay}s — no jitter, "
                    "no exponential cap, no deadline budget; pace it "
                    "through replication.retry.BackoffPolicy (or tag a "
                    "deliberate fixed-cadence poll with a reasoned "
                    "disable)"
                ),
            ))
    return out
