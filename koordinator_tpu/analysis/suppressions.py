"""Suppression audit (ISSUE 17): every live ``koordlint: disable=``
tag, accountable.

``python -m koordinator_tpu.analysis --suppressions`` lists each tag
with file:line, rule and reason.  Two conditions fail the audit:

* **missing reason** — rules in ``REASON_REQUIRED`` (broad-except by
  long-standing review convention, unguarded-shared-state by ISSUE-17
  design: both suppress *races/eaten errors*, so the annotation must
  say why the hazard is not real; unregistered-jit-boundary by ISSUE-19
  design: the tag must say why a launch site legitimately escapes the
  device-time ledger) carry a parenthesised reason;
* **stale** — the suppressed rule no longer fires on the annotated
  line (the raw, unsuppressed pass finds nothing there): the code
  moved or was fixed, and a tag pinned to nothing will silently
  blanket whatever lands on that line next.  Prune it.

A tag on line N covers violations on N and N+1 (the line-above
convention), so staleness checks both.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from koordinator_tpu.analysis.core import (
    _DISABLE_RE,
    _RULE_TOKEN_RE,
    Violation,
    find_repo_root,
    iter_python_files,
    run_repo,
)

RULE = "suppression-audit"

# rules whose suppressions MUST carry a reason
REASON_REQUIRED = frozenset((
    "broad-except", "unguarded-shared-state", "unregistered-jit-boundary",
))


@dataclasses.dataclass(frozen=True)
class Tag:
    path: str
    line: int
    rule: str
    reason: Optional[str]


def parse_tags(path: str, text: str, lang: str = "python") -> List[Tag]:
    """Every ``koordlint: disable=`` tag in one source, WITH reasons
    (core.parse_suppressions discards them)."""
    out: List[Tag] = []

    def record(lineno: int, comment: str) -> None:
        m = _DISABLE_RE.search(comment)
        if not m:
            return
        tail = m.group(1)
        i = 0
        while i < len(tail):
            tok = _RULE_TOKEN_RE.match(tail, i)
            if not tok or not tok.group(1):
                break
            reason = tok.group(2)
            out.append(Tag(
                path, lineno, tok.group(1),
                reason[1:-1].strip() if reason else None,
            ))
            i = tok.end()
            if i < len(tail) and tail[i] == ",":
                i += 1
            else:
                break

    if lang == "python":
        import io
        import tokenize

        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    record(tok.start[0], tok.string)
            return out
        except (tokenize.TokenError, IndentationError, SyntaxError):
            out.clear()
    for lineno, line in enumerate(text.splitlines(), start=1):
        record(lineno, line)
    return out


def collect_repo_tags(root: str) -> List[Tag]:
    """Tags across everything ``run_repo`` scans: the package, bench.py
    and the Go wire sources (wire-contract tags live there)."""
    tags: List[Tag] = []
    paths: List[Tuple[str, str]] = []
    pkg = os.path.join(root, "koordinator_tpu")
    if os.path.isdir(pkg):
        paths.extend((p, "python") for p in iter_python_files(pkg))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        paths.append((bench, "python"))
    go_root = os.path.join(root, "go")
    if os.path.isdir(go_root):
        for dirpath, dirnames, filenames in os.walk(go_root):
            dirnames[:] = [d for d in dirnames if not d.startswith(".")]
            for name in sorted(filenames):
                if name.endswith(".go"):
                    paths.append((os.path.join(dirpath, name), "go"))
    for path, lang in paths:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        tags.extend(parse_tags(os.path.relpath(path, root), text, lang))
    return tags


def audit(root: Optional[str] = None) -> Tuple[List[Tag], List[Violation]]:
    """``(tags, problems)``: every live tag plus the audit failures
    (missing reason on a reason-required rule, stale tag)."""
    root = root or find_repo_root()
    tags = collect_repo_tags(root)
    raw = run_repo(root=root, honor_suppressions=False)
    fired: Dict[Tuple[str, str], Set[int]] = {}
    for v in raw:
        fired.setdefault((v.path, v.rule), set()).add(v.line)
    problems: List[Violation] = []
    for tag in tags:
        if tag.rule in REASON_REQUIRED and not tag.reason:
            problems.append(Violation(
                RULE, tag.path, tag.line,
                f"suppression of {tag.rule!r} carries no reason — "
                "reason-required rules hide races/eaten errors, so the "
                "tag must say why the hazard is not real: "
                f"# koordlint: disable={tag.rule}(reason: ...)",
            ))
        lines = fired.get((tag.path, tag.rule), ())
        if tag.line not in lines and tag.line + 1 not in lines:
            problems.append(Violation(
                RULE, tag.path, tag.line,
                f"stale suppression: {tag.rule!r} no longer fires on "
                "this line (or the line below) — the code moved or was "
                "fixed; prune the tag before it blankets whatever lands "
                "here next",
            ))
    problems.sort(key=lambda v: (v.path, v.line, v.message))
    return tags, problems


def format_report(tags: List[Tag], problems: List[Violation]) -> str:
    lines: List[str] = []
    for tag in sorted(tags, key=lambda t: (t.path, t.line, t.rule)):
        reason = tag.reason if tag.reason else "NO REASON"
        lines.append(f"{tag.path}:{tag.line}: {tag.rule} — {reason}")
    lines.append(f"{len(tags)} live suppression(s)")
    if problems:
        lines.append("")
        for p in problems:
            lines.append(p.format())
        lines.append(f"AUDIT FAILED: {len(problems)} problem(s)")
    else:
        lines.append("audit clean: no stale tags, no missing reasons")
    return "\n".join(lines)
