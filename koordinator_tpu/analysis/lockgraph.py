"""koordlint whole-program pass: the lock-order graph (ISSUE 17).

The reference Koordinator runs its concurrency surface under ``go test
-race``; this module is the static half of our equivalent.  It walks
EVERY repo Python file at once and

* inventories each ``threading.Lock/RLock/Condition`` creation site
  (plain or through the ``obs.lockwitness`` factories) into a canonical
  identity — ``module.Class.attr`` for instance locks,
  ``module.name`` for module-level locks, ``module.func.name`` for
  function-locals;
* derives nested-acquisition edges: a ``with``-block (or a lexical
  ``.acquire()``) on lock A whose body acquires — directly, or through
  a call resolved via the module-local + cross-module method table —
  lock B yields the ordering edge A -> B.  ``Condition.wait`` is
  modelled as release + re-acquire (the re-acquire re-asserts the
  enclosing held-set's edges, tagged so the doc shows the wait seam);
* understands the repo's two higher-order dispatch seams: a
  ``@launch_section`` body runs under the coalescer launch lock, and a
  callable handed to ``run_exclusive``/``run_pipelined`` executes with
  that same lock held;
* emits the derived partial order into the GENERATED
  ``docs/LOCKORDER.md`` and drift-lints it in both directions (the
  metricsdoc pattern: a derived edge missing from the doc, a doc row
  no pass derives, and byte-level staleness all fail);
* fails lint on any cycle in the derived order (``lock-order-cycle``)
  — the static deadlock signal the runtime witness
  (``obs/lockwitness.py``) then validates against real interleavings.

Known approximations, chosen deliberately and validated by the witness:

* ``.acquire()`` holds for the REST of the enclosing block (releases
  are not tracked) — over-approximate, so it can only add edges, never
  hide one;
* calls through unresolvable receivers (parameters, heterogeneous
  collections) contribute no edges — the runtime witness covers those
  interleavings;
* two instances of the same identity (three followers' ``_state_lock``)
  collapse onto one node; identity self-edges are ignored (the
  FreeBSD-witness "dup ok" convention).

All graph functions take a ``{relpath: source}`` mapping so tests can
seed synthetic multi-module programs; ``check_repo`` reads the real
tree.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from koordinator_tpu.analysis.core import Violation, iter_python_files

CYCLE_RULE = "lock-order-cycle"
DRIFT_RULE = "lockorder-doc-drift"

MD_PATH = os.path.join("docs", "LOCKORDER.md")

_LOCK_KINDS = ("Lock", "RLock", "Condition")
_WITNESS_FACTORIES = {
    "witness_lock": "Lock",
    "witness_rlock": "RLock",
    "witness_condition": "Condition",
}

# The witness's own bookkeeping primitives are the instrumentation
# layer, not part of the serving tier's order — the witness must not
# witness itself, statically or at runtime.
_EXCLUDED_MODULES = frozenset(("obs.lockwitness",))

# The repo's higher-order dispatch seams.  A ``@launch_section`` body
# executes under the coalescer launch lock (the decorator is the
# marker lock-held-dispatch already keys on); a callable argument to
# ``run_exclusive``/``run_pipelined`` runs with that lock held.  Both
# are applied only when the referenced identity exists in the
# inventory, so seeded fixtures without a coalescer are unaffected.
_LAUNCH_LOCK_ID = "bridge.coalesce.CoalescingDispatcher._launch_lock"
_SECTION_DECORATORS = {"launch_section": _LAUNCH_LOCK_ID}
_HIGHER_ORDER_SEAMS = {
    "run_exclusive": _LAUNCH_LOCK_ID,
    "run_pipelined": _LAUNCH_LOCK_ID,
}

_THREADING_SENTINEL = ("<threading>", None)

# Method names too generic for the unique-method fallback: calls on
# unresolvable receivers (locals, untyped parameters) resolve through
# the cross-module method table only when exactly ONE class defines the
# name AND the name cannot be a stdlib-collection/IO method — a
# ``frames.append(...)`` on a plain list must never resolve to some
# class's ``append``.
_GENERIC_METHODS = frozenset((
    "append", "add", "get", "put", "pop", "push", "close", "stop",
    "start", "run", "join", "send", "sendall", "recv", "write", "read",
    "update", "clear", "copy", "items", "keys", "values", "extend",
    "remove", "discard", "insert", "index", "count", "sort", "reverse",
    "acquire", "release", "locked", "wait", "notify", "notify_all",
    "submit", "result", "cancel", "set", "unset", "reset", "info",
    "debug", "warning", "error", "exception", "format", "encode",
    "decode", "strip", "split", "splitlines", "setdefault", "flush",
    "seek", "tell", "observe", "record", "emit", "check", "name",
))


def module_name(rel_path: str) -> str:
    """``koordinator_tpu/bridge/server.py`` -> ``bridge.server``;
    ``bench.py`` -> ``bench``; package __init__ collapses onto the
    package name."""
    parts = rel_path.replace(os.sep, "/").split("/")
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[0] == "koordinator_tpu":
        parts = parts[1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else "koordinator_tpu"


@dataclasses.dataclass
class LockSite:
    identity: str
    kind: str  # Lock | RLock | Condition
    path: str
    line: int
    witness_name: Optional[str]  # string handed to a witness_* factory


@dataclasses.dataclass
class Edge:
    src: str
    dst: str
    path: str
    line: int
    via: str


class _Func:
    def __init__(self, node, module: "_Module", qualname: str,
                 cls: Optional["_Class"]):
        self.node = node
        self.module = module
        self.qualname = qualname  # "Class.meth", "func", "func.inner"
        self.cls = cls
        self.nested: Dict[str, "_Func"] = {}
        self.local_locks: Dict[str, str] = {}  # var name -> identity


class _Class:
    def __init__(self, name: str, module: "_Module", node: ast.ClassDef):
        self.name = name
        self.module = module
        self.node = node
        self.methods: Dict[str, _Func] = {}
        self.lock_attrs: Dict[str, str] = {}  # attr -> identity
        self.attr_types: Dict[str, Tuple[str, str]] = {}  # attr -> class ref
        self.base_refs: List[Tuple[str, str]] = []  # resolved after pass 1

    def mro(self, graph: "LockGraph") -> Iterable["_Class"]:
        seen: Set[Tuple[str, str]] = set()
        stack = [self]
        while stack:
            cls = stack.pop(0)
            key = (cls.module.name, cls.name)
            if key in seen:
                continue
            seen.add(key)
            yield cls
            for ref in cls.base_refs:
                base = graph.classes.get(ref)
                if base is not None:
                    stack.append(base)


class _Module:
    def __init__(self, path: str, name: str, tree: ast.Module):
        self.path = path
        self.name = name
        self.tree = tree
        # alias -> (module, symbol|None); symbol None means the alias IS
        # the module.  ("<threading>", None) marks the stdlib threading
        # module itself.
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        self.classes: Dict[str, _Class] = {}
        self.functions: Dict[str, _Func] = {}
        self.module_locks: Dict[str, str] = {}  # name -> identity


class LockGraph:
    def __init__(self) -> None:
        self.locks: Dict[str, LockSite] = {}
        self.edges: Dict[Tuple[str, str], Edge] = {}
        self.modules: Dict[str, _Module] = {}
        self.classes: Dict[Tuple[str, str], _Class] = {}
        self.violations: List[Violation] = []  # inventory-level findings
        # method name -> defining classes (the cross-module method table)
        self.method_index: Dict[str, List[_Class]] = {}

    def adjacency(self) -> Dict[str, Set[str]]:
        adj: Dict[str, Set[str]] = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, set()).add(dst)
        return adj


# ---------------------------------------------------------------------------
# helpers


def _terminal_name(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _strip_pkg(dotted: str) -> str:
    if dotted == "koordinator_tpu":
        return ""
    if dotted.startswith("koordinator_tpu."):
        return dotted[len("koordinator_tpu."):]
    return dotted


def _import_target(module: _Module, node: ast.AST) -> None:
    """Record import aliases for later cross-module resolution."""
    if isinstance(node, ast.Import):
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            if alias.name == "threading":
                module.imports[name] = _THREADING_SENTINEL
            elif alias.asname:
                module.imports[alias.asname] = (_strip_pkg(alias.name), None)
            else:
                # ``import a.b.c`` binds ``a``; dotted chains through it
                # are resolved attribute by attribute, which we skip.
                module.imports[name] = (_strip_pkg(alias.name.split(".")[0]),
                                        None)
    elif isinstance(node, ast.ImportFrom):
        src = node.module or ""
        if node.level:
            # relative import: anchor on this module's package
            pkg = module.name.split(".")
            pkg = pkg[: len(pkg) - node.level] if node.level <= len(pkg) else []
            src = ".".join(pkg + ([src] if src else []))
        else:
            src = _strip_pkg(src)
        for alias in node.names:
            bound = alias.asname or alias.name
            if src == "threading" or (not src and alias.name == "threading"):
                if alias.name in _LOCK_KINDS:
                    module.imports[bound] = ("<threading>", alias.name)
                continue
            module.imports[bound] = (src, alias.name)


def _creation_kind(
    call: ast.Call, module: _Module
) -> Optional[Tuple[str, Optional[str], bool]]:
    """``(kind, witness_name, is_factory)`` if ``call`` creates a lock.

    Recognizes ``threading.Lock()`` (module alias resolved through the
    import table), a bare imported ``Lock()``, and the
    ``obs.lockwitness`` factory forms ``witness_lock("identity")``.
    """
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _LOCK_KINDS:
        if (isinstance(f.value, ast.Name)
                and module.imports.get(f.value.id) == _THREADING_SENTINEL):
            return (f.attr, None, False)
    if isinstance(f, ast.Name):
        ref = module.imports.get(f.id)
        if ref is not None and ref[0] == "<threading>" and ref[1] in _LOCK_KINDS:
            return (ref[1], None, False)
    term = _terminal_name(f)
    if term in _WITNESS_FACTORIES:
        name = _const_str(call.args[0]) if call.args else None
        return (_WITNESS_FACTORIES[term], name, True)
    return None


def _iter_funcs(module: _Module) -> Iterable[_Func]:
    stack: List[_Func] = list(module.functions.values())
    for cls in module.classes.values():
        stack.extend(cls.methods.values())
    while stack:
        fn = stack.pop()
        yield fn
        stack.extend(fn.nested.values())


# ---------------------------------------------------------------------------
# pass 1: symbol tables + inventory


def build_graph(sources: Dict[str, str]) -> LockGraph:
    graph = LockGraph()
    for path in sorted(sources):
        if module_name(path) in _EXCLUDED_MODULES:
            continue
        try:
            tree = ast.parse(sources[path], filename=path)
        except SyntaxError:
            continue  # parse errors belong to the per-file rules
        mod = _Module(path, module_name(path), tree)
        graph.modules[mod.name] = mod
        for node in tree.body:
            _import_target(mod, node)
        _collect_module(graph, mod)
    _resolve_bases(graph)
    for cls in graph.classes.values():
        for meth in cls.methods:
            graph.method_index.setdefault(meth, []).append(cls)
    _build_edges(graph)
    return graph


def _collect_module(graph: LockGraph, mod: _Module) -> None:
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name) and isinstance(node.value, ast.Call):
                made = _creation_kind(node.value, mod)
                if made:
                    identity = f"{mod.name}.{target.id}"
                    _record_lock(graph, identity, made, mod.path,
                                 node.lineno)
                    mod.module_locks[target.id] = identity
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = _Func(node, mod, node.name, None)
            mod.functions[node.name] = fn
            _collect_func(graph, fn)
        elif isinstance(node, ast.ClassDef):
            cls = _Class(node.name, mod, node)
            mod.classes[node.name] = cls
            graph.classes[(mod.name, cls.name)] = cls
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = _Func(item, mod, f"{cls.name}.{item.name}", cls)
                    cls.methods[item.name] = fn
                    _collect_func(graph, fn)
                elif (isinstance(item, ast.Assign) and len(item.targets) == 1
                      and isinstance(item.targets[0], ast.Name)
                      and isinstance(item.value, ast.Call)):
                    made = _creation_kind(item.value, mod)
                    if made:
                        attr = item.targets[0].id
                        identity = f"{mod.name}.{cls.name}.{attr}"
                        _record_lock(graph, identity, made, mod.path,
                                     item.lineno)
                        cls.lock_attrs[attr] = identity


def _collect_func(graph: LockGraph, fn: _Func) -> None:
    """Inventory creations + attr types inside one function body, and
    register nested defs (closures get their own summary units)."""
    mod = fn.module
    for node in _walk_own(fn.node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested = _Func(node, mod, f"{fn.qualname}.{node.name}", fn.cls)
            fn.nested[node.name] = nested
            _collect_func(graph, nested)
            continue
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(node.value, ast.Call):
            continue
        made = _creation_kind(node.value, mod)
        if made is not None:
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self" and fn.cls is not None):
                identity = f"{mod.name}.{fn.cls.name}.{target.attr}"
                _record_lock(graph, identity, made, mod.path, node.lineno)
                fn.cls.lock_attrs.setdefault(target.attr, identity)
            elif isinstance(target, ast.Name):
                identity = f"{mod.name}.{fn.qualname}.{target.id}"
                _record_lock(graph, identity, made, mod.path, node.lineno)
                fn.local_locks[target.id] = identity
            continue
        # attr type: self.x = ClassName(...) / self.x = mod.ClassName(...)
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and fn.cls is not None):
            ref = _class_ref(mod, node.value.func)
            if ref is not None:
                fn.cls.attr_types.setdefault(target.attr, ref)


def _walk_own(fn_node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body; nested function/class bodies are yielded as
    single nodes (callers recurse explicitly), lambda bodies skipped."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _class_ref(mod: _Module, func: ast.AST) -> Optional[Tuple[str, str]]:
    """Resolve a constructor expression to ``(module, ClassName)``."""
    if isinstance(func, ast.Name):
        if func.id in mod.classes:
            return (mod.name, func.id)
        ref = mod.imports.get(func.id)
        if ref is not None and ref[1] is not None:
            return (ref[0], ref[1])
    elif isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        ref = mod.imports.get(func.value.id)
        if ref is not None and ref[1] is None and ref[0] != "<threading>":
            return (ref[0], func.attr)
    return None


def _record_lock(graph: LockGraph, identity: str,
                 made: Tuple[str, Optional[str], bool],
                 path: str, line: int) -> None:
    kind, witness_name, is_factory = made
    site = LockSite(identity, kind, path, line, witness_name)
    graph.locks.setdefault(identity, site)
    if is_factory and witness_name != identity:
        got = repr(witness_name) if witness_name is not None else "no name"
        graph.violations.append(Violation(
            DRIFT_RULE, path, line,
            f"witness factory passes {got} but the derived identity is "
            f"{identity!r} — the runtime witness and the static graph "
            "must agree on lock names",
        ))


def _resolve_bases(graph: LockGraph) -> None:
    for cls in graph.classes.values():
        for base in cls.node.bases:
            ref = _class_ref(cls.module, base)
            if ref is not None:
                cls.base_refs.append(ref)


# ---------------------------------------------------------------------------
# resolution


def _resolve_lock(graph: LockGraph, fn: _Func,
                  expr: ast.AST) -> Optional[str]:
    """Resolve an expression to an inventoried lock identity."""
    mod = fn.module
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        if expr.value.id == "self" and fn.cls is not None:
            for cls in fn.cls.mro(graph):
                if expr.attr in cls.lock_attrs:
                    return cls.lock_attrs[expr.attr]
            return None
        ref = mod.imports.get(expr.value.id)
        if ref is not None and ref[1] is None and ref[0] != "<threading>":
            other = graph.modules.get(ref[0])
            if other is not None:
                return other.module_locks.get(expr.attr)
        return None
    if isinstance(expr, ast.Name):
        probe: Optional[_Func] = fn
        while probe is not None:
            if expr.id in probe.local_locks:
                return probe.local_locks[expr.id]
            probe = _enclosing(probe)
        if expr.id in mod.module_locks:
            return mod.module_locks[expr.id]
        ref = mod.imports.get(expr.id)
        if ref is not None and ref[1] is not None and ref[0] in graph.modules:
            return graph.modules[ref[0]].module_locks.get(ref[1])
    return None


def _enclosing(fn: _Func) -> Optional[_Func]:
    """Parent function of a nested def (resolved by qualname)."""
    if "." not in fn.qualname:
        return None
    parent_qual = fn.qualname.rsplit(".", 1)[0]
    mod = fn.module
    candidates: List[_Func] = list(_iter_funcs(mod))
    for cand in candidates:
        if cand.qualname == parent_qual and cand is not fn:
            return cand
    return None


def _resolve_callable(graph: LockGraph, fn: _Func,
                      expr: ast.AST) -> List[_Func]:
    """Resolve a callable-position expression to function units."""
    mod = fn.module
    if isinstance(expr, ast.Lambda):
        shim = _Func(expr, mod, f"{fn.qualname}.<lambda>", fn.cls)
        shim.local_locks = dict(fn.local_locks)
        return [shim]
    if isinstance(expr, ast.Name):
        probe: Optional[_Func] = fn
        while probe is not None:
            if expr.id in probe.nested:
                return [probe.nested[expr.id]]
            probe = _enclosing(probe)
        if expr.id in mod.functions:
            return [mod.functions[expr.id]]
        ctor = _ctor_targets(graph, _class_ref(mod, expr))
        if ctor:
            return ctor
        ref = mod.imports.get(expr.id)
        if ref is not None and ref[1] is not None and ref[0] in graph.modules:
            other = graph.modules[ref[0]]
            if ref[1] in other.functions:
                return [other.functions[ref[1]]]
        return []
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id == "self" and fn.cls is not None:
                for cls in fn.cls.mro(graph):
                    if expr.attr in cls.methods:
                        return [cls.methods[expr.attr]]
                return []
            ref = mod.imports.get(base.id)
            if ref is not None and ref[1] is None and ref[0] in graph.modules:
                other = graph.modules[ref[0]]
                if expr.attr in other.functions:
                    return [other.functions[expr.attr]]
                if expr.attr in other.classes:
                    return _ctor_targets(graph, (other.name, expr.attr))
                return []
            return _unique_method(graph, expr.attr)
        # self.attr.meth() through the attr-type table
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and fn.cls is not None):
            for cls in fn.cls.mro(graph):
                ref = cls.attr_types.get(base.attr)
                if ref is not None:
                    target_cls = graph.classes.get(ref)
                    if target_cls is not None:
                        for tc in target_cls.mro(graph):
                            if expr.attr in tc.methods:
                                return [tc.methods[expr.attr]]
                    return []
            return _unique_method(graph, expr.attr)
    return []


def _unique_method(graph: LockGraph, name: str) -> List[_Func]:
    """Cross-module method-table fallback for unresolvable receivers:
    resolve only when exactly one class program-wide defines ``name``
    and the name cannot belong to a stdlib collection."""
    if name in _GENERIC_METHODS or name.startswith("__"):
        return []
    owners = graph.method_index.get(name, ())
    if len(owners) == 1:
        return [owners[0].methods[name]]
    return []


def _ctor_targets(graph: LockGraph,
                  ref: Optional[Tuple[str, str]]) -> List[_Func]:
    if ref is None:
        return []
    cls = graph.classes.get(ref)
    if cls is None:
        return []
    for c in cls.mro(graph):
        if "__init__" in c.methods:
            return [c.methods["__init__"]]
    return []


# ---------------------------------------------------------------------------
# may-acquire summaries


def _summary(graph: LockGraph, fn: _Func, memo: Dict[int, Set[str]],
             stack: Set[int]) -> Set[str]:
    key = id(fn.node)
    if key in memo:
        return memo[key]
    if key in stack:
        return set()
    stack.add(key)
    acquired: Set[str] = set()
    for node in _walk_own(fn.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lock = _resolve_lock(graph, fn, item.context_expr)
                if lock is not None:
                    acquired.add(lock)
        elif isinstance(node, ast.Call):
            acquired.update(_call_acquires(graph, fn, node, memo, stack))
    stack.discard(key)
    memo[key] = acquired
    return acquired


def _call_acquires(graph: LockGraph, fn: _Func, call: ast.Call,
                   memo: Dict[int, Set[str]],
                   stack: Set[int]) -> Set[str]:
    out: Set[str] = set()
    term = _terminal_name(call.func)
    if term == "acquire" and isinstance(call.func, ast.Attribute):
        lock = _resolve_lock(graph, fn, call.func.value)
        if lock is not None:
            out.add(lock)
            return out
    for target in _resolve_callable(graph, fn, call.func):
        out.update(_summary(graph, target, memo, stack))
    seam = _HIGHER_ORDER_SEAMS.get(term or "")
    if seam is not None and seam in graph.locks:
        out.add(seam)
        for arg in _seam_fn_args(call):
            for target in _resolve_callable(graph, fn, arg):
                out.update(_summary(graph, target, memo, stack))
    return out


def _seam_fn_args(call: ast.Call) -> List[ast.AST]:
    args: List[ast.AST] = list(call.args)
    args.extend(kw.value for kw in call.keywords if kw.value is not None)
    return args


# ---------------------------------------------------------------------------
# pass 2: edges


def _build_edges(graph: LockGraph) -> None:
    memo: Dict[int, Set[str]] = {}
    for mod_name in sorted(graph.modules):
        mod = graph.modules[mod_name]
        for fn in sorted(_iter_funcs(mod), key=lambda f: f.node.lineno):
            held: List[str] = []
            for deco in getattr(fn.node, "decorator_list", ()):
                section = _SECTION_DECORATORS.get(_terminal_name(deco) or "")
                if section is not None and section in graph.locks:
                    held.append(section)
            _walk_block(graph, fn, list(fn.node.body), held, memo)


def _record_edge(graph: LockGraph, held: Sequence[str], dst: str,
                 path: str, line: int, via: str) -> None:
    for src in held:
        if src == dst:
            continue  # reentrancy / same-identity instances: dup ok
        graph.edges.setdefault((src, dst), Edge(src, dst, path, line, via))


def _walk_block(graph: LockGraph, fn: _Func, stmts: List[ast.stmt],
                held: List[str], memo: Dict[int, Set[str]]) -> None:
    base_depth = len(held)
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # summarized separately; runs elsewhere
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                _scan_exprs(graph, fn, [item.context_expr], held, memo)
                lock = _resolve_lock(graph, fn, item.context_expr)
                if lock is not None:
                    _record_edge(graph, held, lock, fn.module.path,
                                 item.context_expr.lineno, "nested with")
                    held.append(lock)
                    pushed += 1
            _walk_block(graph, fn, list(stmt.body), held, memo)
            del held[len(held) - pushed:]
            continue
        # expressions hanging off this statement (test/iter/value/...)
        exprs = [v for v in ast.iter_child_nodes(stmt)
                 if isinstance(v, ast.expr)]
        acquired = _scan_exprs(graph, fn, exprs, held, memo)
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                _walk_block(graph, fn, list(sub), held, memo)
        for handler in getattr(stmt, "handlers", ()) or ():
            _walk_block(graph, fn, list(handler.body), held, memo)
        # a lexical .acquire() holds for the REST of this block
        held.extend(acquired)
    del held[base_depth:]


def _scan_exprs(graph: LockGraph, fn: _Func, exprs: Sequence[ast.AST],
                held: List[str], memo: Dict[int, Set[str]]) -> List[str]:
    """Record edges for every call inside ``exprs``; returns locks taken
    by lexical ``.acquire()`` calls (to be held for the rest of the
    enclosing block)."""
    acquired: List[str] = []
    stack: List[ast.AST] = list(exprs)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
        if not isinstance(node, ast.Call):
            continue
        term = _terminal_name(node.func)
        if term == "acquire" and isinstance(node.func, ast.Attribute):
            lock = _resolve_lock(graph, fn, node.func.value)
            if lock is not None:
                _record_edge(graph, held, lock, fn.module.path,
                             node.lineno, ".acquire()")
                acquired.append(lock)
            continue
        if term == "wait" and isinstance(node.func, ast.Attribute):
            lock = _resolve_lock(graph, fn, node.func.value)
            if (lock is not None and lock in held
                    and graph.locks[lock].kind == "Condition"):
                # wait releases ONLY the condition: everything else the
                # thread holds — including locks taken AFTER entering
                # the cond block — stays held across the park, so the
                # re-acquire orders every one of them before the cond
                # (the held-after-cond case is the classic hidden
                # inversion against a plain ``with cond:`` elsewhere)
                outer = [h for h in held if h != lock]
                _record_edge(graph, outer, lock, fn.module.path,
                             node.lineno, "Condition.wait reacquire")
                continue
        stk: Set[int] = set()
        dsts: Set[str] = set()
        for target in _resolve_callable(graph, fn, node.func):
            dsts.update(_summary(graph, target, memo, stk))
        via = f"calls {term}()" if term else "call"
        for dst in sorted(dsts):
            _record_edge(graph, held, dst, fn.module.path, node.lineno, via)
        seam = _HIGHER_ORDER_SEAMS.get(term or "")
        if seam is not None and seam in graph.locks:
            _record_edge(graph, held, seam, fn.module.path, node.lineno,
                         f"calls {term}()")
            for arg in _seam_fn_args(node):
                for target in _resolve_callable(graph, fn, arg):
                    for dst in sorted(_summary(graph, target, memo, set())):
                        _record_edge(
                            graph, [seam], dst, fn.module.path, node.lineno,
                            f"runs under {term}()")
                        _record_edge(graph, held, dst, fn.module.path,
                                     node.lineno, f"calls {term}()")
    return acquired


# ---------------------------------------------------------------------------
# cycles


def find_cycles(graph: LockGraph) -> List[Violation]:
    """Tarjan SCC over the derived order; every non-trivial component is
    a potential deadlock and fails ``lock-order-cycle``."""
    adj = graph.adjacency()
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(adj.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp: List[str] = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(sorted(comp))

    for v in sorted(set(adj) | {d for ds in adj.values() for d in ds}):
        if v not in index:
            strongconnect(v)

    out: List[Violation] = []
    for comp in sccs:
        cycle = _concrete_cycle(adj, comp)
        hops = []
        for a, b in zip(cycle, cycle[1:]):
            edge = graph.edges[(a, b)]
            hops.append(f"{a} -> {b} ({edge.path}:{edge.line}, {edge.via})")
        first = graph.edges[(cycle[0], cycle[1])]
        out.append(Violation(
            CYCLE_RULE, first.path, first.line,
            "lock-order cycle: " + "; ".join(hops)
            + " — two threads entering from different ends deadlock; "
            "break the cycle or restructure so one order holds globally",
        ))
    return out


def _concrete_cycle(adj: Dict[str, Set[str]],
                    comp: List[str]) -> List[str]:
    """One concrete cycle through an SCC, for the violation message."""
    members = set(comp)
    start = comp[0]
    path = [start]
    seen = {start}
    node = start
    while True:
        nxt = sorted(n for n in adj.get(node, ()) if n in members)
        if not nxt:
            return [start, start]
        step = next((n for n in nxt if n == start), None)
        if step is not None:
            path.append(start)
            return path
        step = next((n for n in nxt if n not in seen), nxt[0])
        if step in seen:
            # close on the first repeat
            return path[path.index(step):] + [step]
        path.append(step)
        seen.add(step)
        node = step


# ---------------------------------------------------------------------------
# LOCKORDER.md generation + drift lint


_HEADER = """# Lock order — GENERATED, do not edit

Derived by `koordinator_tpu/analysis/lockgraph.py`; regenerate with
`python -m koordinator_tpu.analysis --write-lockorder`.  The
`lockorder-doc-drift` rule fails lint when this file and the derived
graph disagree in either direction; `lock-order-cycle` fails on any
cycle, and the runtime witness (`KOORD_LOCK_WITNESS=1`,
`obs/lockwitness.py`) raises on any real interleaving that contradicts
an order below.

An edge "A before B" means some code path acquires B while holding A;
the partial order is everything deadlock-freedom requires — two
threads may never close a cycle against it.
"""


def generate_lockorder_md(graph: LockGraph) -> str:
    lines = [_HEADER]
    lines.append("## Inventory\n")
    lines.append("| lock | kind | defined at |")
    lines.append("| --- | --- | --- |")
    for identity in sorted(graph.locks):
        site = graph.locks[identity]
        lines.append(
            f"| `{identity}` | {site.kind} | {site.path}:{site.line} |"
        )
    lines.append("")
    lines.append("## Acquisition order (A before B)\n")
    lines.append("| first | then | witnessed at | via |")
    lines.append("| --- | --- | --- | --- |")
    for key in sorted(graph.edges):
        e = graph.edges[key]
        lines.append(
            f"| `{e.src}` | `{e.dst}` | {e.path}:{e.line} | {e.via} |"
        )
    lines.append("")
    return "\n".join(lines)


def parse_doc_rows(
    md_text: str,
) -> Tuple[Dict[str, Tuple[str, int]], Dict[Tuple[str, str], int]]:
    """``(locks, edges)`` parsed back out of a LOCKORDER.md body:
    ``locks[identity] = (kind, line)``; ``edges[(a, b)] = line``."""
    import re

    lock_re = re.compile(r"^\|\s*`([^`]+)`\s*\|\s*(\w+)\s*\|\s*[^|`]+\|$")
    edge_re = re.compile(r"^\|\s*`([^`]+)`\s*\|\s*`([^`]+)`\s*\|")
    locks: Dict[str, Tuple[str, int]] = {}
    edges: Dict[Tuple[str, str], int] = {}
    for lineno, line in enumerate(md_text.splitlines(), start=1):
        stripped = line.strip()
        m = edge_re.match(stripped)
        if m:
            edges[(m.group(1), m.group(2))] = lineno
            continue
        m = lock_re.match(stripped)
        if m and m.group(2) in _LOCK_KINDS:
            locks[m.group(1)] = (m.group(2), lineno)
    return locks, edges


def diff_lockorder_doc(graph: LockGraph, md_text: Optional[str],
                       md_path: str = MD_PATH) -> List[Violation]:
    if md_text is None:
        return [Violation(
            DRIFT_RULE, md_path, 0,
            "docs/LOCKORDER.md not found — the generated lock order is "
            "the contract the runtime witness enforces; run "
            "`python -m koordinator_tpu.analysis --write-lockorder`",
        )]
    doc_locks, doc_edges = parse_doc_rows(md_text)
    out: List[Violation] = []
    for identity in sorted(graph.locks):
        site = graph.locks[identity]
        doc = doc_locks.get(identity)
        if doc is None:
            out.append(Violation(
                DRIFT_RULE, site.path, site.line,
                f"lock {identity!r} is inventoried but missing from the "
                f"{md_path} inventory table — regenerate with "
                "--write-lockorder",
            ))
        elif doc[0] != site.kind:
            out.append(Violation(
                DRIFT_RULE, md_path, doc[1],
                f"lock {identity!r} documented as {doc[0]} but created as "
                f"{site.kind} — regenerate with --write-lockorder",
            ))
    for identity, (_kind, lineno) in sorted(doc_locks.items()):
        if identity not in graph.locks:
            out.append(Violation(
                DRIFT_RULE, md_path, lineno,
                f"doc inventories lock {identity!r} that no creation site "
                "defines — regenerate with --write-lockorder",
            ))
    for key in sorted(graph.edges):
        if key not in doc_edges:
            e = graph.edges[key]
            out.append(Violation(
                DRIFT_RULE, e.path, e.line,
                f"derived order {key[0]} -> {key[1]} is missing from the "
                f"{md_path} order table — regenerate with "
                "--write-lockorder",
            ))
    for key, lineno in sorted(doc_edges.items()):
        if key not in graph.edges:
            out.append(Violation(
                DRIFT_RULE, md_path, lineno,
                f"doc orders {key[0]} -> {key[1]} but no code path "
                "derives that edge — regenerate with --write-lockorder",
            ))
    if not out and md_text != generate_lockorder_md(graph):
        out.append(Violation(
            DRIFT_RULE, md_path, 0,
            "generated content is stale (sites or prose moved even "
            "though the row sets match) — regenerate with "
            "--write-lockorder",
        ))
    return out


# ---------------------------------------------------------------------------
# repo entry points


def collect_sources(root: str) -> Dict[str, str]:
    sources: Dict[str, str] = {}
    scan_root = os.path.join(root, "koordinator_tpu")
    paths: List[str] = []
    if os.path.isdir(scan_root):
        paths.extend(iter_python_files(scan_root))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        paths.append(bench)
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            sources[os.path.relpath(path, root)] = f.read()
    return sources


def check_sources(sources: Dict[str, str],
                  md_text: Optional[str]) -> List[Violation]:
    """Test seam: cycles + witness-name drift + doc drift over synthetic
    sources."""
    graph = build_graph(sources)
    out = list(graph.violations)
    out.extend(find_cycles(graph))
    out.extend(diff_lockorder_doc(graph, md_text))
    return out


def check_repo(root: str) -> List[Violation]:
    graph = build_graph(collect_sources(root))
    out = list(graph.violations)
    out.extend(find_cycles(graph))
    md_path = os.path.join(root, MD_PATH)
    md_text: Optional[str] = None
    if os.path.exists(md_path):
        with open(md_path, "r", encoding="utf-8") as f:
            md_text = f.read()
    out.extend(diff_lockorder_doc(graph, md_text))
    return out


def repo_graph(root: str) -> LockGraph:
    return build_graph(collect_sources(root))


def static_order(root: str) -> Set[Tuple[str, str]]:
    """The derived edge set, for the runtime witness."""
    return set(repo_graph(root).edges)


def write_lockorder(root: str) -> str:
    """Regenerate docs/LOCKORDER.md in place; returns the path."""
    graph = repo_graph(root)
    path = os.path.join(root, MD_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(generate_lockorder_md(graph))
    return path
