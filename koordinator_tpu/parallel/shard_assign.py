"""Explicitly-scheduled multi-chip greedy assignment (shard_map).

``greedy_assign`` (solver/greedy.py) is a sequential scan over pods; under
plain GSPMD sharding every scan step's argmax-over-nodes and node-state
update make the compiler infer cross-device communication, which scales
badly with step count.  This module instead partitions the scan body by
hand with ``jax.shard_map``:

* node state (allocatable / usage / requested / estimated) is sharded
  across ALL mesh devices along the node axis — the cluster spreads over
  the combined HBM;
* pod rows and the quota table are replicated (quota updates are computed
  identically on every device);
* each scan step does local Filter+Score on its node shard, then exactly
  ONE collective — a ``lax.pmax`` of a packed (score, node-index) key —
  to agree on the winning node, then a local masked update on the owning
  shard.

The packed key encodes ``score * N_total + (N_total-1 - node_index)`` so a
single max picks the highest score with the LOWEST node index — the same
tie-break as ``jnp.argmax`` in the scan path, giving bit-identical
placements (tests/test_parallel.py asserts parity vs greedy_assign).

Reference analog: the Score fan-out at
``pkg/scheduler/frameworkext/framework_extender.go:216`` parallelizes one
pod's scoring over 16 goroutines; here the whole cycle's node dimension is
parallelized over the device mesh with one ICI collective per pod.
"""

from __future__ import annotations

import dataclasses as dc
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG
from koordinator_tpu.constraints.gang import gang_satisfaction
from koordinator_tpu.model.snapshot import ClusterSnapshot
from koordinator_tpu.ops.fit import nonzero_requests
from koordinator_tpu.ops.loadaware import (
    loadaware_node_masks,
    select_score_usage,
)
from koordinator_tpu.model.snapshot import PriorityClass
from koordinator_tpu.solver.greedy import (
    STATUS_ASSIGNED,
    STATUS_UNSCHEDULABLE,
    STATUS_WAIT_GANG,
    CycleResult,
    queue_order,
    step_feasible_scores,
)

# scores are bounded by plugin weights * MAX_NODE_SCORE (tiny); this
# sentinel for infeasible nodes leaves the packed key far from i64 limits
_NEG = jnp.int64(-(2**40))


def _pad_nodes_to(snap: ClusterSnapshot, multiple: int) -> ClusterSnapshot:
    """Pad the node axis to a multiple of the device count with invalid
    rows (valid=False keeps them unchoosable)."""
    nodes = snap.nodes
    N = nodes.allocatable.shape[0]
    pad = (-N) % multiple
    if pad == 0:
        return snap
    pad2 = lambda a: jnp.pad(a, ((0, pad), (0, 0)))
    pad1 = lambda a: jnp.pad(a, (0, pad))
    return dc.replace(
        snap,
        nodes=dc.replace(
            nodes,
            allocatable=pad2(nodes.allocatable),
            requested=pad2(nodes.requested),
            usage=pad2(nodes.usage),
            metric_fresh=pad1(nodes.metric_fresh),
            valid=pad1(nodes.valid),
        ),
    )


@partial(jax.jit, static_argnames=("cfg", "mesh", "has_mask", "has_scores"))
def _assign_sharded(
    snapshot: ClusterSnapshot,
    extra_mask,
    extra_scores,
    *,
    cfg: CycleConfig,
    mesh: Mesh,
    has_mask: bool,
    has_scores: bool,
):
    pods, nodes, quotas = snapshot.pods, snapshot.nodes, snapshot.quotas
    N = nodes.allocatable.shape[0]
    axes = tuple(mesh.axis_names)
    ax = axes if len(axes) > 1 else axes[0]

    order = queue_order(pods.priority, pods.valid)
    score_requests = nonzero_requests(pods.requests)

    # LoadAware masks + score-usage selection (aggregated/prod profiles,
    # load_aware.go:150-226,291-311) are node-local: compute once host-side
    # and shard them with the node axis
    mask_default, mask_prod = loadaware_node_masks(nodes, cfg)
    if not cfg.enable_loadaware:
        mask_default = jnp.ones_like(mask_default)
        mask_prod = mask_default
    node_ok_default = nodes.valid & mask_default
    node_ok_prod = nodes.valid & mask_prod
    usage_np, usage_prod = select_score_usage(nodes, cfg)
    prod_sensitive = cfg.enable_loadaware and (
        usage_prod is not None
        or bool(dict(cfg.loadaware.prod_usage_thresholds))
    )
    if usage_prod is None:
        usage_prod = usage_np
    is_prod_pods = pods.priority_class == int(PriorityClass.PROD)

    node_spec = P(ax, None)
    flag_spec = P(ax)
    rep = P()
    pn_spec = P(None, ax)  # [P, N] extended-plugin tensors: shard nodes

    operands = [
        nodes.allocatable,
        nodes.requested,
        usage_np,
        usage_prod,
        node_ok_default,
        node_ok_prod,
        nodes.metric_fresh,
        order,
        pods.requests,
        score_requests,
        pods.estimated,
        pods.quota_id,
        pods.valid,
        is_prod_pods,
        quotas.runtime,
        quotas.limited,
        quotas.used,
    ]
    in_specs = [
        node_spec, node_spec, node_spec, node_spec, flag_spec, flag_spec,
        flag_spec, rep, rep, rep, rep, rep, rep, rep, rep, rep, rep,
    ]
    if has_mask:
        operands.append(extra_mask)
        in_specs.append(pn_spec)
    if has_scores:
        operands.append(extra_scores)
        in_specs.append(pn_spec)

    def body(
        alloc, req0, usage, uprod, node_ok_def, node_ok_pr, fresh,
        order, preq, psreq, pest, pqid, pvalid, pprod, qrt, qlim, quse0,
        *extras,
    ):
        xmask = extras[0] if has_mask else None
        xscores = extras[-1] if has_scores else None
        n_loc = alloc.shape[0]
        offset = lax.axis_index(ax).astype(jnp.int64) * n_loc
        gidx = offset + jnp.arange(n_loc, dtype=jnp.int64)

        def step(state, p):
            node_requested, node_estimated, quota_used = state
            req = preq[p]
            est = pest[p]
            qid = pqid[p]
            q = jnp.maximum(qid, 0)
            if prod_sensitive:
                node_ok_p = jnp.where(pprod[p], node_ok_pr, node_ok_def)
                usage_p = jnp.where(pprod[p], uprod, usage)
            else:
                node_ok_p = node_ok_def
                usage_p = usage

            # same step semantics as greedy_assign, on the local node shard
            feasible, total = step_feasible_scores(
                node_requested,
                node_estimated,
                quota_used,
                alloc,
                usage_p,
                fresh,
                node_ok_p,
                req,
                psreq[p],
                est,
                qid,
                pvalid[p],
                qrt,
                qlim,
                cfg,
            )
            if xmask is not None:
                feasible = feasible & xmask[p]
            if xscores is not None:
                total = total + xscores[p]

            masked = jnp.where(feasible, total, _NEG)
            # ONE collective per step: packed (score, lowest-index) max
            key = masked * N + (N - 1 - gidx)
            gkey = lax.pmax(jnp.max(key), ax)
            best_score = gkey // N  # floor div decodes negatives too
            chosen = (N - 1 - (gkey - best_score * N)).astype(jnp.int32)
            any_feasible = best_score > (_NEG // 2)
            chosen = jnp.where(any_feasible, chosen, -1)

            local = chosen - offset.astype(jnp.int32)
            hit = (local >= 0) & (local < n_loc) & any_feasible
            onehot = (jnp.arange(n_loc) == local) & hit
            node_requested = node_requested + jnp.where(
                onehot[:, None], req[None, :], 0
            )
            node_estimated = node_estimated + jnp.where(
                onehot[:, None], est[None, :], 0
            )
            quota_used = jnp.where(
                any_feasible & (qid >= 0), quota_used.at[q].add(req), quota_used
            )
            return (node_requested, node_estimated, quota_used), chosen

        init = (req0, jnp.zeros_like(req0), quse0)
        (nreq, nest, quse), chosen_in_order = lax.scan(step, init, order)
        return chosen_in_order, nreq, nest, quse

    chosen_in_order, node_requested, node_estimated, quota_used = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(rep, node_spec, node_spec, rep),
        check_vma=False,
    )(*operands)

    Pcap = pods.capacity
    assignment = jnp.full((Pcap,), -1, jnp.int32).at[order].set(chosen_in_order)
    status = jnp.where(assignment >= 0, STATUS_ASSIGNED, STATUS_UNSCHEDULABLE)
    assigned = (assignment >= 0) & pods.valid
    _, pod_gang_ok = gang_satisfaction(
        assignment, pods.valid, pods.gang_id, snapshot.gangs.min_member
    )
    status = jnp.where(assigned & ~pod_gang_ok, STATUS_WAIT_GANG, status)
    return CycleResult(
        assignment=assignment,
        status=status.astype(jnp.int32),
        node_requested=node_requested,
        node_estimated=node_estimated,
        quota_used=quota_used,
        path="shard",
    )


def greedy_assign_sharded(
    snapshot: ClusterSnapshot,
    mesh: Mesh,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    extra_mask: Optional[jnp.ndarray] = None,
    extra_scores: Optional[jnp.ndarray] = None,
) -> CycleResult:
    """Sequential-parity greedy assignment with node state sharded over
    every device of ``mesh`` and one collective per pod step.

    Placements are bit-identical with solver.greedy.greedy_assign;
    ``node_requested``/``node_estimated`` come back node-sharded over the
    mesh and trimmed to the snapshot's node bucket.
    """
    if extra_scores is not None:
        # the packed key multiplies scores by N; plugin scores are tiny by
        # construction, but extra_scores is caller-supplied — values at the
        # sentinel's magnitude would decode as infeasible (or overflow the
        # key), silently breaking parity, so reject them loudly instead
        hi = int(jnp.max(jnp.abs(extra_scores)))
        if hi >= 2**31:
            raise ValueError(
                f"extra_scores magnitude {hi} too large for the packed-key "
                "collective (must be < 2^31); use solver.greedy_assign"
            )
    n_dev = mesh.size
    orig_n = snapshot.nodes.allocatable.shape[0]
    snapshot = _pad_nodes_to(snapshot, n_dev)
    padded_n = snapshot.nodes.allocatable.shape[0]
    if extra_mask is not None and extra_mask.shape[1] != padded_n:
        extra_mask = jnp.pad(
            extra_mask, ((0, 0), (0, padded_n - extra_mask.shape[1]))
        )
    if extra_scores is not None and extra_scores.shape[1] != padded_n:
        extra_scores = jnp.pad(
            extra_scores, ((0, 0), (0, padded_n - extra_scores.shape[1]))
        )
    result = _assign_sharded(
        snapshot,
        extra_mask,
        extra_scores,
        cfg=cfg,
        mesh=mesh,
        has_mask=extra_mask is not None,
        has_scores=extra_scores is not None,
    )
    if result.node_requested.shape[0] != orig_n:
        result = CycleResult(
            assignment=result.assignment,
            status=result.status,
            node_requested=result.node_requested[:orig_n],
            node_estimated=result.node_estimated[:orig_n],
            quota_used=result.quota_used,
            path=result.path,
        )
    return result
