"""Explicitly-scheduled multi-chip greedy assignment (shard_map).

``greedy_assign`` (solver/greedy.py) is a sequential scan over pods; under
plain GSPMD sharding every scan step's argmax-over-nodes and node-state
update make the compiler infer cross-device communication, which scales
badly with step count.  This module instead partitions the scan body by
hand with ``jax.shard_map``:

* node state (allocatable / usage / requested / estimated) is sharded
  across ALL mesh devices along the node axis — the cluster spreads over
  the combined HBM;
* pod rows and the quota table are replicated (quota updates are computed
  identically on every device);
* each scan step does local Filter+Score on its node shard, then exactly
  ONE collective — a ``lax.pmax`` of a packed (score, node-index) key —
  to agree on the winning node, then a local masked update on the owning
  shard.

The packed key encodes ``score * N_total + (N_total-1 - node_index)`` so a
single max picks the highest score with the LOWEST node index — the same
tie-break as ``jnp.argmax`` in the scan path, giving bit-identical
placements (tests/test_parallel.py asserts parity vs greedy_assign).

Reference analog: the Score fan-out at
``pkg/scheduler/frameworkext/framework_extender.go:216`` parallelizes one
pod's scoring over 16 goroutines; here the whole cycle's node dimension is
parallelized over the device mesh with one ICI collective per pod.
"""

from __future__ import annotations

import dataclasses as dc
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from koordinator_tpu.config import (
    CycleConfig,
    DEFAULT_CYCLE_CONFIG,
)
from koordinator_tpu.constraints.gang import gang_satisfaction
from koordinator_tpu.model.snapshot import ClusterSnapshot
from koordinator_tpu.obs import devprof
from koordinator_tpu.ops.fit import nonzero_requests
from koordinator_tpu.ops.loadaware import (
    loadaware_node_masks,
    select_score_usage,
)
from koordinator_tpu.model.snapshot import PriorityClass
from koordinator_tpu.solver.greedy import (
    STATUS_ASSIGNED,
    STATUS_UNSCHEDULABLE,
    STATUS_WAIT_GANG,
    CycleResult,
    queue_order,
    step_feasible_scores,
)

# the packed-key encode/decode, the cross-shard top-M merge and the
# in-wave certification are the ONE shared implementation
# (solver/wave.py) this path and the single-chip wave_assign both
# consume — no copy-pasted math; the shard_map version-compat shim is
# shared with the resident scatter (parallel/mesh.py)
from koordinator_tpu.parallel.mesh import shard_map_compat as _shard_map
from koordinator_tpu.solver.wave import (
    is_most_allocated,
    merge_topm,
    merge_topm_keys,
    pack_keys,
    decode_key,
    resolve_wave,
    score_feasible,
)


def _pad_nodes_to(snap: ClusterSnapshot, multiple: int) -> ClusterSnapshot:
    """Pad the node axis to a multiple of the device count with invalid
    rows (valid=False keeps them unchoosable)."""
    nodes = snap.nodes
    N = nodes.allocatable.shape[0]
    pad = (-N) % multiple
    if pad == 0:
        return snap
    pad2 = lambda a: jnp.pad(a, ((0, pad), (0, 0)))
    pad1 = lambda a: jnp.pad(a, (0, pad))
    return dc.replace(
        snap,
        nodes=dc.replace(
            nodes,
            allocatable=pad2(nodes.allocatable),
            requested=pad2(nodes.requested),
            usage=pad2(nodes.usage),
            metric_fresh=pad1(nodes.metric_fresh),
            valid=pad1(nodes.valid),
        ),
    )


def _cycle_operands(
    snapshot, cfg, ax, order_operand, extra_mask, extra_scores,
    has_mask, has_scores,
):
    """Shared shard_map prologue for both sharded entry points (per-pod
    and wave): LoadAware masks + score-usage selection
    (load_aware.go:150-226,291-311, node-local so computed host-side and
    sharded with the node axis), the operand list, and partition specs.
    Returns (operands, in_specs, prod_sensitive)."""
    pods, nodes, quotas = snapshot.pods, snapshot.nodes, snapshot.quotas
    score_requests = nonzero_requests(pods.requests)

    mask_default, mask_prod = loadaware_node_masks(nodes, cfg)
    if not cfg.enable_loadaware:
        mask_default = jnp.ones_like(mask_default)
        mask_prod = mask_default
    node_ok_default = nodes.valid & mask_default
    node_ok_prod = nodes.valid & mask_prod
    usage_np, usage_prod = select_score_usage(nodes, cfg)
    prod_sensitive = cfg.enable_loadaware and (
        usage_prod is not None
        or bool(dict(cfg.loadaware.prod_usage_thresholds))
    )
    if usage_prod is None:
        usage_prod = usage_np
    is_prod_pods = pods.priority_class == int(PriorityClass.PROD)

    node_spec = P(ax, None)
    flag_spec = P(ax)
    rep = P()
    pn_spec = P(None, ax)  # [P, N] extended-plugin tensors: shard nodes

    operands = [
        nodes.allocatable,
        nodes.requested,
        usage_np,
        usage_prod,
        node_ok_default,
        node_ok_prod,
        nodes.metric_fresh,
        order_operand,
        pods.requests,
        score_requests,
        pods.estimated,
        pods.quota_id,
        pods.valid,
        is_prod_pods,
        quotas.runtime,
        quotas.limited,
        quotas.used,
    ]
    in_specs = [
        node_spec, node_spec, node_spec, node_spec, flag_spec, flag_spec,
        flag_spec, rep, rep, rep, rep, rep, rep, rep, rep, rep, rep,
    ]
    if has_mask:
        operands.append(extra_mask)
        in_specs.append(pn_spec)
    if has_scores:
        operands.append(extra_scores)
        in_specs.append(pn_spec)
    return operands, in_specs, prod_sensitive


@devprof.boundary("parallel.shard_assign._assign_sharded")
@partial(jax.jit, static_argnames=("cfg", "mesh", "has_mask", "has_scores"))
def _assign_sharded(
    snapshot: ClusterSnapshot,
    extra_mask,
    extra_scores,
    *,
    cfg: CycleConfig,
    mesh: Mesh,
    has_mask: bool,
    has_scores: bool,
):
    pods, nodes, quotas = snapshot.pods, snapshot.nodes, snapshot.quotas
    N = nodes.allocatable.shape[0]
    axes = tuple(mesh.axis_names)
    ax = axes if len(axes) > 1 else axes[0]

    order = queue_order(pods.priority, pods.valid)
    operands, in_specs, prod_sensitive = _cycle_operands(
        snapshot, cfg, ax, order, extra_mask, extra_scores,
        has_mask, has_scores,
    )
    node_spec = P(ax, None)
    rep = P()

    def body(
        alloc, req0, usage, uprod, node_ok_def, node_ok_pr, fresh,
        order, preq, psreq, pest, pqid, pvalid, pprod, qrt, qlim, quse0,
        *extras,
    ):
        xmask = extras[0] if has_mask else None
        xscores = extras[-1] if has_scores else None
        n_loc = alloc.shape[0]
        offset = lax.axis_index(ax).astype(jnp.int64) * n_loc
        gidx = offset + jnp.arange(n_loc, dtype=jnp.int64)

        def step(state, p):
            node_requested, node_estimated, quota_used = state
            req = preq[p]
            est = pest[p]
            qid = pqid[p]
            q = jnp.maximum(qid, 0)
            if prod_sensitive:
                node_ok_p = jnp.where(pprod[p], node_ok_pr, node_ok_def)
                usage_p = jnp.where(pprod[p], uprod, usage)
            else:
                node_ok_p = node_ok_def
                usage_p = usage

            # same step semantics as greedy_assign, on the local node shard
            feasible, total = step_feasible_scores(
                node_requested,
                node_estimated,
                quota_used,
                alloc,
                usage_p,
                fresh,
                node_ok_p,
                req,
                psreq[p],
                est,
                qid,
                pvalid[p],
                qrt,
                qlim,
                cfg,
            )
            if xmask is not None:
                feasible = feasible & xmask[p]
            if xscores is not None:
                total = total + xscores[p]

            # ONE collective per step: packed (score, lowest-index) max
            key = pack_keys(total, feasible, gidx, N)
            gkey = lax.pmax(jnp.max(key), ax)
            best_score, chosen = decode_key(gkey, N)
            any_feasible = score_feasible(best_score)
            chosen = jnp.where(any_feasible, chosen, -1)

            local = chosen - offset.astype(jnp.int32)
            hit = (local >= 0) & (local < n_loc) & any_feasible
            onehot = (jnp.arange(n_loc) == local) & hit
            node_requested = node_requested + jnp.where(
                onehot[:, None], req[None, :], 0
            )
            node_estimated = node_estimated + jnp.where(
                onehot[:, None], est[None, :], 0
            )
            quota_used = jnp.where(
                any_feasible & (qid >= 0), quota_used.at[q].add(req), quota_used
            )
            return (node_requested, node_estimated, quota_used), chosen

        init = (req0, jnp.zeros_like(req0), quse0)
        (nreq, nest, quse), chosen_in_order = lax.scan(step, init, order)
        return chosen_in_order, nreq, nest, quse

    chosen_in_order, node_requested, node_estimated, quota_used = _shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(rep, node_spec, node_spec, rep),
        check_vma=False,
    )(*operands)

    Pcap = pods.capacity
    assignment = jnp.full((Pcap,), -1, jnp.int32).at[order].set(chosen_in_order)
    status = jnp.where(assignment >= 0, STATUS_ASSIGNED, STATUS_UNSCHEDULABLE)
    assigned = (assignment >= 0) & pods.valid
    _, pod_gang_ok = gang_satisfaction(
        assignment, pods.valid, pods.gang_id, snapshot.gangs.min_member
    )
    status = jnp.where(assigned & ~pod_gang_ok, STATUS_WAIT_GANG, status)
    return CycleResult(
        assignment=assignment,
        status=status.astype(jnp.int32),
        node_requested=node_requested,
        node_estimated=node_estimated,
        quota_used=quota_used,
        path="shard",
    )


@devprof.boundary("parallel.shard_assign._assign_waves")
@partial(
    jax.jit,
    static_argnames=("cfg", "mesh", "has_mask", "has_scores", "wave", "top_m"),
)
def _assign_waves(
    snapshot: ClusterSnapshot,
    extra_mask,
    extra_scores,
    *,
    cfg: CycleConfig,
    mesh: Mesh,
    has_mask: bool,
    has_scores: bool,
    wave: int,
    top_m: int,
):
    """Round-based sharded cycle: O(P/prefix) collectives instead of O(P).

    Each round, every shard scores the next ``wave`` pods against its
    frozen node shard and contributes its local top-``top_m`` candidates
    (packed key + the candidate's node-state rows) to ONE ``all_gather``.
    Every device then runs the same deterministic in-wave resolution:

    * quota admission is node-invariant, so it is rechecked exactly
      against the replicated in-wave quota state (a blocked pod commits
      as unschedulable, never needing a rescan);
    * a pod whose candidate node was committed-to earlier in the wave has
      that candidate's key recomputed from the gathered state rows plus
      the in-wave delta (commit targets and pod vectors are replicated,
      so every device derives the identical key);
    * scores only decrease as load is added (LeastAllocated), and packed
      keys are unique, so any node outside the pod's top-``top_m``
      candidates stays strictly below the frozen ``top_m``-th key k_M —
      a pod's choice is therefore EXACT (bit-identical with the
      sequential scan) whenever its best current candidate key is still
      >= k_M.  The first pod in the wave that cannot be certified ends
      the commit prefix; it and everything after rerun next round
      against fresh state;
    * under ``MostAllocated`` (round-4 review #5) scores INCREASE as
      load is added, so the k_M lower-bound argument inverts.  The exact
      symmetric certificate rides the CLOSED candidate universe: every
      in-wave commit lands on some wave pod's gathered candidate, so the
      union of all wave pods' per-shard top-M candidates (whose full
      state rows ride the same all_gather) is the ONLY set of nodes
      whose keys can move within the round.  Each pod re-keys that
      whole universe exactly (frozen rows + the replicated in-wave
      commit deltas) and certifies when the universe best >= its own
      frozen global k_M: any node outside the pod's frozen top-M has
      frozen key <= k_M and — receiving no in-wave commits — can never
      rise above it, while packed-key uniqueness turns the boundary
      case into membership.  Pod 0 of each round has no earlier in-wave
      commits, so its frozen keys ARE current and it always commits —
      liveness is unchanged.

    Measured on the 10k x 2k benchmark snapshot: wave=32/top_m=4 commits
    ~20 pods per collective (500 rounds vs 10,000 per-pod collectives).

    Reference analog: the per-pod Score fan-out bounded by 16 goroutines
    (``frameworkext/framework_extender.go:216``); here the fan-out is the
    device mesh and the round batching bounds the collective count.
    """
    pods, nodes, quotas = snapshot.pods, snapshot.nodes, snapshot.quotas
    N = nodes.allocatable.shape[0]
    PCAP = pods.capacity
    W = wave
    # the local top-M runs on each shard's node slice, so M is bounded by
    # the PER-SHARD node count (a 16-node cluster over 8 shards has 2-node
    # slices; fuzz-found)
    M = max(1, min(top_m, N // mesh.size))
    axes = tuple(mesh.axis_names)
    ax = axes if len(axes) > 1 else axes[0]

    order = queue_order(pods.priority, pods.valid)
    order_pad = jnp.concatenate([order, jnp.zeros((W,), order.dtype)])
    operands, in_specs, prod_sensitive = _cycle_operands(
        snapshot, cfg, ax, order_pad, extra_mask, extra_scores,
        has_mask, has_scores,
    )
    node_spec = P(ax, None)
    rep = P()

    # MostAllocated needs the upper-bound certificate (docstring bullet 4)
    most_alloc = is_most_allocated(cfg)

    def body(
        alloc, req0, usage, uprod, node_ok_def, node_ok_pr, fresh,
        order_pad, preq, psreq, pest, pqid, pvalid, pprod, qrt, qlim, quse0,
        *extras,
    ):
        xmask = extras[0] if has_mask else None
        xscores = extras[-1] if has_scores else None
        n_loc = alloc.shape[0]
        offset = lax.axis_index(ax).astype(jnp.int64) * n_loc
        gidx = offset + jnp.arange(n_loc, dtype=jnp.int64)
        iota_w = jnp.arange(W)

        def one_pod_keys(nreq, nest, p):
            """Frozen [n_loc] packed keys for pod p (quota handled in the
            replicated resolution, so qid=-1 here)."""
            if prod_sensitive:
                ok_p = jnp.where(pprod[p], node_ok_pr, node_ok_def)
                usage_p = jnp.where(pprod[p], uprod, usage)
            else:
                ok_p = node_ok_def
                usage_p = usage
            feasible, total = step_feasible_scores(
                nreq, nest, quse0, alloc, usage_p, fresh, ok_p,
                preq[p], psreq[p], pest[p], jnp.int32(-1), pvalid[p],
                qrt, qlim, cfg,
            )
            if xmask is not None:
                feasible = feasible & xmask[p]
            if xscores is not None:
                total = total + xscores[p]
            return pack_keys(total, feasible, gidx, N)

        def wave_round(carry):
            ptr, nreq, nest, quse, chosen_buf, nwaves = carry
            ps = lax.dynamic_slice(order_pad, (ptr,), (W,))
            wvalid = (ptr + iota_w) < PCAP
            preq_wave = preq[ps]  # [W, R]
            pest_wave = pest[ps]

            keys_loc = jax.vmap(lambda p: one_pod_keys(nreq, nest, p))(ps)
            lvals, lidx = lax.top_k(keys_loc, M)  # [W, M]
            gid = offset + lidx.astype(jnp.int64)

            if most_alloc:
                # the closed candidate universe (see docstring): this
                # shard's contribution is the union of its W pods' local
                # top-M rows, keyed by NODE (duplicates are harmless —
                # identical rows produce identical keys)
                uni_idx = lidx.reshape(-1)  # [W*M] local slots
                uni_gid = offset + uni_idx.astype(jnp.int64)

            if most_alloc:
                # universe payload: node-keyed rows for the closed
                # candidate set + the frozen per-pod keys (k_M only)
                payload = dict(
                    key=lvals,  # [W, M]
                    u_gid=uni_gid,  # [W*M]
                    u_alloc=alloc[uni_idx],
                    u_nreq=nreq[uni_idx],
                    u_nest=nest[uni_idx],
                    u_usage=usage[uni_idx],
                    u_okd=node_ok_def[uni_idx],
                    u_fresh=fresh[uni_idx],
                    u_xval=(
                        xscores[ps[:, None], uni_idx[None, :]]
                        if xscores is not None
                        else jnp.zeros((W, W * M), jnp.int64)
                    ),
                    u_xfeas=(
                        xmask[ps[:, None], uni_idx[None, :]]
                        if xmask is not None
                        else jnp.ones((W, W * M), bool)
                    ),
                )
                if prod_sensitive:
                    # the prod-usage variants ride only when some pod can
                    # actually select them (trace-time flag) — otherwise
                    # they would double the universe rows in the ONE
                    # collective this design exists to minimize
                    payload["u_uprod"] = uprod[uni_idx]
                    payload["u_okp"] = node_ok_pr[uni_idx]
            else:
                if prod_sensitive:
                    usage_rows = jnp.where(
                        pprod[ps][:, None, None], uprod[lidx], usage[lidx]
                    )
                    ok_rows = jnp.where(
                        pprod[ps][:, None], node_ok_pr[lidx], node_ok_def[lidx]
                    )
                else:
                    usage_rows = usage[lidx]
                    ok_rows = node_ok_def[lidx]
                payload = dict(
                    key=lvals,
                    gid=gid,
                    alloc=alloc[lidx],
                    nreq=nreq[lidx],
                    nest=nest[lidx],
                    usage=usage_rows,
                    ok=ok_rows,
                    fresh=fresh[lidx],
                    xval=(
                        xscores[ps[:, None], lidx]
                        if xscores is not None
                        else jnp.zeros((W, M), jnp.int64)
                    ),
                    xfeas=(
                        xmask[ps[:, None], lidx]
                        if xmask is not None
                        else jnp.ones((W, M), bool)
                    ),
                )
            # the ONE collective of the round
            gathered = lax.all_gather(payload, ax)  # leading [S, ...]

            if most_alloc:
                # frozen per-pod global top-M keys (k_M certification
                # bar), via the shared cross-shard merge
                cand_key = merge_topm_keys(gathered["key"], M)
                R_ = alloc.shape[1]
                u_gid = gathered["u_gid"].reshape(-1)  # [U = S*W*M]
                U = u_gid.shape[0]
                universe = dict(
                    gid=u_gid,
                    alloc=gathered["u_alloc"].reshape(U, R_),
                    nreq=gathered["u_nreq"].reshape(U, R_),
                    nest=gathered["u_nest"].reshape(U, R_),
                    usage=gathered["u_usage"].reshape(U, R_),
                    okd=gathered["u_okd"].reshape(U),
                    fresh=gathered["u_fresh"].reshape(U),
                    # [S, W, W*M] -> [W, U] aligned with u_gid's (s, k)
                    # order
                    xval=jnp.moveaxis(
                        gathered["u_xval"], 0, 1
                    ).reshape(W, U),
                    xfeas=jnp.moveaxis(
                        gathered["u_xfeas"], 0, 1
                    ).reshape(W, U),
                )
                if prod_sensitive:
                    universe["uprod"] = gathered["u_uprod"].reshape(U, R_)
                    universe["okp"] = gathered["u_okp"].reshape(U)
                cand = None
            else:
                # the shared cross-shard top-M merge (solver/wave.py):
                # global candidates + their state rows, [W, M]
                cand_key, cand = merge_topm(gathered, M)
                universe = None

            # the SHARED certification resolver (solver/wave.py): commit
            # targets and pod vectors are replicated, so every device
            # derives the identical prefix
            choices, committed, done, quse_new, ncommit = resolve_wave(
                cand_key,
                cand=cand,
                universe=universe,
                preq_wave=preq_wave,
                pest_wave=pest_wave,
                psreq_wave=psreq[ps],
                pqid_wave=pqid[ps],
                pvalid_wave=pvalid[ps],
                pprod_wave=pprod[ps],
                wvalid=wvalid,
                qrt=qrt,
                qlim=qlim,
                quse=quse,
                cfg=cfg,
                n_total=N,
                prod_sensitive=prod_sensitive,
            )

            # apply the committed prefix to the local shard state
            local = choices - offset
            mine = committed & (local >= 0) & (local < n_loc)
            onehot = (
                (local[:, None] == jnp.arange(n_loc)[None, :]) & mine[:, None]
            ).astype(jnp.int64)
            nreq = nreq + jnp.einsum("wn,wr->nr", onehot, preq_wave)
            nest = nest + jnp.einsum("wn,wr->nr", onehot, pest_wave)

            write = jnp.where(
                done, choices.astype(jnp.int32), jnp.int32(-1)
            )
            # positions not committed this round keep their buffer value
            # (they will be rewritten when their round comes)
            window = lax.dynamic_slice(chosen_buf, (ptr,), (W,))
            window = jnp.where(done, write, window)
            chosen_buf = lax.dynamic_update_slice(chosen_buf, window, (ptr,))

            ptr = ptr + ncommit
            return (ptr, nreq, nest, quse_new, chosen_buf, nwaves + 1)

        def cond(carry):
            return carry[0] < PCAP

        init = (
            jnp.int64(0),
            req0,
            jnp.zeros_like(req0),
            quse0,
            jnp.full((PCAP + W,), -1, jnp.int32),
            jnp.int64(0),
        )
        ptr, nreq, nest, quse, chosen_buf, nwaves = lax.while_loop(
            cond, wave_round, init
        )
        return chosen_buf[:PCAP], nreq, nest, quse, nwaves

    (chosen_in_order, node_requested, node_estimated, quota_used, nwaves) = (
        _shard_map(
            body,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=(rep, node_spec, node_spec, rep, rep),
            check_vma=False,
        )(*operands)
    )

    Pcap = pods.capacity
    assignment = jnp.full((Pcap,), -1, jnp.int32).at[order].set(chosen_in_order)
    status = jnp.where(assignment >= 0, STATUS_ASSIGNED, STATUS_UNSCHEDULABLE)
    assigned = (assignment >= 0) & pods.valid
    _, pod_gang_ok = gang_satisfaction(
        assignment, pods.valid, pods.gang_id, snapshot.gangs.min_member
    )
    status = jnp.where(assigned & ~pod_gang_ok, STATUS_WAIT_GANG, status)
    return (
        CycleResult(
            assignment=assignment,
            status=status.astype(jnp.int32),
            node_requested=node_requested,
            node_estimated=node_estimated,
            quota_used=quota_used,
            rounds=nwaves,
            path="shard",
        ),
        nwaves,
    )


def greedy_assign_waves(
    snapshot: ClusterSnapshot,
    mesh: Mesh,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    extra_mask: Optional[jnp.ndarray] = None,
    extra_scores: Optional[jnp.ndarray] = None,
    wave: int = 32,
    top_m: int = 4,
    spans=None,
    candidates: Optional[jnp.ndarray] = None,
):
    """Round-based sharded assignment (see _assign_waves): bit-identical
    with greedy_assign, one all_gather per round instead of one pmax per
    pod.  Returns (CycleResult, collective_round_count).

    ``candidates``: an optional [P, C] sparse candidate-index map
    (ISSUE 16, solver/candidates.py — ascending real node ids, pad
    slots >= N).  Expanded host-side into a [P, N] membership mask and
    ANDed into ``extra_mask`` BEFORE the node padding, so the wave
    rounds only ever pick a pod's candidate nodes while the
    cross-shard gang/quota reduction rides the existing top-M merge
    unchanged — no new traced parameters, no new compiled shapes.
    Exact whenever the lists are non-overflowed (every feasible node
    is a member; see ``check_candidate_overflow``).

    Both fit strategies certify exactly: LeastAllocated through the
    frozen k_M lower bound (scores non-increasing in committed load),
    MostAllocated through the symmetric frozen upper bound on
    non-candidate nodes (round-4 review #5; see the _assign_waves
    docstring).  The reference parallelizes Score identically for both
    (``frameworkext/framework_extender.go:216``,
    ``plugins/nodenumaresource/most_allocated.go``).

    ``spans``: optional ``obs.spans.SpanRecorder``.  Only the HOST-side
    stages are timed (pad/prep vs the sharded rounds' dispatch) — the
    recorder never enters ``_assign_waves``' traced body, so the spans
    add no host syncs and no retraces; round counts come from the
    result the device already returns."""
    if extra_scores is not None:
        hi = int(jnp.max(jnp.abs(extra_scores)))
        if hi >= 2**31:
            raise ValueError(
                f"extra_scores magnitude {hi} too large for the packed-key "
                "collective (must be < 2^31); use solver.greedy_assign"
            )
    from koordinator_tpu.obs.spans import maybe_span

    with maybe_span(spans, "shard_prep"):
        n_dev = mesh.size
        orig_n = snapshot.nodes.allocatable.shape[0]
        if candidates is not None:
            from koordinator_tpu.solver.candidates import (
                candidate_membership_mask,
            )

            member = candidate_membership_mask(candidates, orig_n)
            extra_mask = (
                member if extra_mask is None else extra_mask & member
            )
        snapshot = _pad_nodes_to(snapshot, n_dev)
        padded_n = snapshot.nodes.allocatable.shape[0]
        if extra_mask is not None and extra_mask.shape[1] != padded_n:
            extra_mask = jnp.pad(
                extra_mask, ((0, 0), (0, padded_n - extra_mask.shape[1]))
            )
        if extra_scores is not None and extra_scores.shape[1] != padded_n:
            extra_scores = jnp.pad(
                extra_scores, ((0, 0), (0, padded_n - extra_scores.shape[1]))
            )
    with maybe_span(spans, "shard_rounds"):
        result, nwaves = _assign_waves(
            snapshot,
            extra_mask,
            extra_scores,
            cfg=cfg,
            mesh=mesh,
            has_mask=extra_mask is not None,
            has_scores=extra_scores is not None,
            wave=wave,
            top_m=top_m,
        )
    if result.node_requested.shape[0] != orig_n:
        result = dc.replace(
            result,
            node_requested=result.node_requested[:orig_n],
            node_estimated=result.node_estimated[:orig_n],
        )
    return result, int(nwaves)


def greedy_assign_sharded(
    snapshot: ClusterSnapshot,
    mesh: Mesh,
    cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
    extra_mask: Optional[jnp.ndarray] = None,
    extra_scores: Optional[jnp.ndarray] = None,
) -> CycleResult:
    """Sequential-parity greedy assignment with node state sharded over
    every device of ``mesh`` and one collective per pod step.

    Placements are bit-identical with solver.greedy.greedy_assign;
    ``node_requested``/``node_estimated`` come back node-sharded over the
    mesh and trimmed to the snapshot's node bucket.
    """
    if extra_scores is not None:
        # the packed key multiplies scores by N; plugin scores are tiny by
        # construction, but extra_scores is caller-supplied — values at the
        # sentinel's magnitude would decode as infeasible (or overflow the
        # key), silently breaking parity, so reject them loudly instead
        hi = int(jnp.max(jnp.abs(extra_scores)))
        if hi >= 2**31:
            raise ValueError(
                f"extra_scores magnitude {hi} too large for the packed-key "
                "collective (must be < 2^31); use solver.greedy_assign"
            )
    n_dev = mesh.size
    orig_n = snapshot.nodes.allocatable.shape[0]
    snapshot = _pad_nodes_to(snapshot, n_dev)
    padded_n = snapshot.nodes.allocatable.shape[0]
    if extra_mask is not None and extra_mask.shape[1] != padded_n:
        extra_mask = jnp.pad(
            extra_mask, ((0, 0), (0, padded_n - extra_mask.shape[1]))
        )
    if extra_scores is not None and extra_scores.shape[1] != padded_n:
        extra_scores = jnp.pad(
            extra_scores, ((0, 0), (0, padded_n - extra_scores.shape[1]))
        )
    result = _assign_sharded(
        snapshot,
        extra_mask,
        extra_scores,
        cfg=cfg,
        mesh=mesh,
        has_mask=extra_mask is not None,
        has_scores=extra_scores is not None,
    )
    if result.node_requested.shape[0] != orig_n:
        result = CycleResult(
            assignment=result.assignment,
            status=result.status,
            node_requested=result.node_requested[:orig_n],
            node_estimated=result.node_estimated[:orig_n],
            quota_used=result.quota_used,
            path=result.path,
        )
    return result
