"""Multi-chip scale-out of the scoring/assignment tensors.

The reference scales the Score phase by fanning goroutines over nodes on one
machine (``frameworkext/framework_extender.go:216``).  The TPU-native scale
axis is a ``jax.sharding.Mesh``:

* ``pods`` mesh axis — data-parallel analog: each chip scores a slice of the
  pending-pod batch.
* ``nodes`` mesh axis — model-parallel analog: node state (allocatable /
  requested / usage) is sharded so clusters larger than one chip's HBM
  spread across ICI neighbors; argmax-over-nodes becomes an XLA collective.

One ``pods x nodes`` score tensor sharded over a 2-D mesh keeps all
collectives on ICI (scaling-book recipe: annotate shardings, let XLA insert
the collectives).  The sequential greedy scan shards node state over the
whole mesh and keeps per-pod rows replicated — each scan step's
argmax(masked score) then runs as a sharded reduce.
"""

from __future__ import annotations

from dataclasses import replace as dataclass_replace
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.model.snapshot import ClusterSnapshot

# the one mesh axis of the RESIDENT cluster: node rows spread over it,
# pod rows and the gang/quota tables replicate (ISSUE 7).  Distinct from
# make_mesh's 2-D scoring mesh: the resident snapshot's capacity axis is
# nodes — that is the tensor that outgrows one chip's HBM first (the
# 100k x 10k fp32 cost tensor is ~4 GB; the node tables scale with it).
CLUSTER_AXIS = "nodes"

# the POD mesh axis of the sparse candidate engine (ISSUE 16,
# solver/candidates.py): the [P, C] candidate-index and candidate-score
# tensors split over POD rows — each device builds and scores its own
# pods' candidate lists against a REPLICATED node table, so the sparse
# pipeline runs with zero collectives.  Orthogonal to CLUSTER_AXIS: the
# dense residency scales the node axis, the sparse engine scales pods.
POD_AXIS = "pods"


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=False):
    """Version-compat shard_map: ``jax.shard_map`` (with its ``check_vma``
    kwarg) graduated from ``jax.experimental.shard_map.shard_map`` (whose
    equivalent kwarg is ``check_rep``); the installed jax may carry either.
    Shared by parallel/shard_assign.py and solver/resident.py — the one
    compat shim."""
    if hasattr(jax, "shard_map"):
        # koordlint: disable=unregistered-jit-boundary(reason: version-compat shim, not a launch site — every caller sits inside its own registered devprof.boundary jit boundary)
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    # koordlint: disable=unregistered-jit-boundary(reason: version-compat shim, not a launch site — every caller sits inside its own registered devprof.boundary jit boundary)
    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def cluster_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """The 1-D resident-snapshot mesh: every device holds one node-axis
    shard of the cluster.  ``devices`` defaults to all visible devices;
    pass a prefix (``jax.devices()[:k]``) to shard over fewer chips."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (CLUSTER_AXIS,))


def pow2_device_count(n: int) -> int:
    """Largest power of two <= ``n`` (>= 1).  Node buckets are powers of
    two, so only a power-of-two mesh size is guaranteed to divide every
    geometry — a 6-device cluster mesh would never activate (the
    resident state falls back to single-chip placement on every
    bucket); daemons round their device count down through this before
    building the mesh."""
    n = max(1, int(n))
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def node_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """NamedSharding for a node-major tensor ([N], [N, R], [N, A, R]):
    leading axis split over the cluster mesh, trailing axes whole."""
    return NamedSharding(
        mesh, P(CLUSTER_AXIS, *([None] * (ndim - 1)))
    )


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def score_sharding(mesh: Mesh) -> NamedSharding:
    """NamedSharding of the resident [P, N] score/feasible tensors
    (ISSUE 9): the NODE axis (axis 1) splits over the cluster mesh —
    column j lives on the device owning node j's snapshot rows, so the
    incremental dirty-column rescore is shard-local exactly like the
    delta scatter, and the persistent score tensor's HBM cost divides
    by the mesh like the node tables it derives from."""
    return NamedSharding(mesh, P(None, CLUSTER_AXIS))


def snapshot_partition_specs(snap: ClusterSnapshot):
    """A pytree of bare ``PartitionSpec``s matching ``snap``
    leaf-for-leaf — the mesh-independent half of the placement policy
    (:func:`snapshot_shardings` binds it to a mesh).  Consumed as
    ``shard_map`` in/out specs by the incremental score engine
    (solver/incremental.py), so the rescore partitions the snapshot
    exactly as it is resident — no hidden resharding program."""
    node = lambda a: P(CLUSTER_AXIS, *([None] * (np.ndim(a) - 1)))
    rep = lambda a: P()
    return _snapshot_spec_tree(snap, node, rep)


def snapshot_shardings(snap: ClusterSnapshot, mesh: Mesh):
    """A pytree of ``NamedSharding`` specs matching ``snap`` leaf-for-leaf:
    node tensors sharded along the cluster axis, pod rows and the
    gang/quota tables replicated.  ``jax.tree_util.tree_map`` over
    ``(specs, snap)`` is how a complete snapshot lands mesh-resident
    (:func:`shard_cluster_snapshot`, the embedded-API path);
    bridge/state.py builds its resident leaves incrementally through
    the same ``node_sharding``/``replicated_sharding`` policy, and
    tests/test_mesh_resident.py asserts the two stay in lockstep —
    this function (with :func:`snapshot_partition_specs`, the same
    classification over bare ``PartitionSpec``s) is the one canonical
    statement of which leaf gets which spec."""
    node = lambda a: node_sharding(mesh, np.ndim(a))
    rep = lambda a: replicated_sharding(mesh)
    return _snapshot_spec_tree(snap, node, rep)


def _snapshot_spec_tree(snap: ClusterSnapshot, node, rep):
    """The per-leaf placement classification shared by
    :func:`snapshot_shardings` and :func:`snapshot_partition_specs`:
    ``node``/``rep`` map each array leaf to its spec."""
    nodes = snap.nodes
    return ClusterSnapshot(
        nodes=dataclass_replace(
            nodes,
            allocatable=node(nodes.allocatable),
            requested=node(nodes.requested),
            usage=node(nodes.usage),
            metric_fresh=node(nodes.metric_fresh),
            valid=node(nodes.valid),
            agg_usage=(
                None if nodes.agg_usage is None else node(nodes.agg_usage)
            ),
            agg_fresh=(
                None if nodes.agg_fresh is None else node(nodes.agg_fresh)
            ),
            prod_usage=(
                None if nodes.prod_usage is None else node(nodes.prod_usage)
            ),
            accel_type=(
                None if nodes.accel_type is None else node(nodes.accel_type)
            ),
        ),
        pods=jax.tree_util.tree_map(rep, snap.pods),
        gangs=jax.tree_util.tree_map(rep, snap.gangs),
        quotas=jax.tree_util.tree_map(rep, snap.quotas),
        # the throughput matrix (ISSUE 15) is a small [C, A] side table
        # every shard's gather reads: replicated, like the pod rows
        throughput=(
            None if snap.throughput is None else rep(snap.throughput)
        ),
    )


def shard_cluster_snapshot(snap: ClusterSnapshot, mesh: Mesh) -> ClusterSnapshot:
    """Place ``snap`` mesh-resident: one ``device_put`` per leaf with its
    :func:`snapshot_shardings` spec.  The node bucket must divide evenly
    over the mesh (buckets are powers of two — pick a power-of-two device
    count, or a prefix)."""
    n = snap.nodes.allocatable.shape[0]
    if n % mesh.size:
        raise ValueError(
            f"node bucket {n} does not divide over {mesh.size} devices; "
            "resize the mesh to a power-of-two prefix"
        )
    return jax.tree_util.tree_map(
        lambda spec, leaf: jax.device_put(leaf, spec),
        snapshot_shardings(snap, mesh),
        snap,
    )


def pod_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """The 1-D pod-axis mesh of the sparse candidate engine (ISSUE 16):
    every device owns a pod-row slice of the [P, C] candidate tensors.
    ``devices`` defaults to all visible devices; pass a power-of-two
    prefix (``jax.devices()[:pow2_device_count(n)]``) so the pod bucket
    (always a power of two) divides evenly."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (POD_AXIS,))


def sparse_score_sharding(mesh: Mesh) -> NamedSharding:
    """NamedSharding of the sparse [P, C] candidate-index / score /
    feasible tensors: the POD axis (axis 0) splits over the pod mesh —
    the transpose of the dense residency's :func:`score_sharding`
    (``P(None, "nodes")``), because the sparse engine's scale axis is
    pods (C is a small static width, never worth splitting)."""
    return NamedSharding(mesh, P(POD_AXIS, None))


def snapshot_pod_partition_specs(snap: ClusterSnapshot):
    """Bare ``PartitionSpec``s placing ``snap`` for the sparse engine's
    pod-parallel shard_map: POD rows split over :data:`POD_AXIS`, node
    tables and the gang/quota/throughput side tables replicated (every
    device gathers arbitrary node rows for its own pods' candidate
    lists, so the node table must be whole on every device).  The
    mirror-image classification of :func:`snapshot_partition_specs`."""
    pod = lambda a: P(POD_AXIS, *([None] * (np.ndim(a) - 1)))
    rep = lambda a: P()
    nodes = snap.nodes
    return ClusterSnapshot(
        nodes=jax.tree_util.tree_map(rep, nodes),
        pods=dataclass_replace(
            snap.pods,
            requests=pod(snap.pods.requests),
            estimated=pod(snap.pods.estimated),
            priority_class=pod(snap.pods.priority_class),
            qos=pod(snap.pods.qos),
            priority=pod(snap.pods.priority),
            gang_id=pod(snap.pods.gang_id),
            quota_id=pod(snap.pods.quota_id),
            valid=pod(snap.pods.valid),
            workload_class=(
                None if snap.pods.workload_class is None
                else pod(snap.pods.workload_class)
            ),
            sensitivity=(
                None if snap.pods.sensitivity is None
                else pod(snap.pods.sensitivity)
            ),
        ),
        gangs=jax.tree_util.tree_map(rep, snap.gangs),
        quotas=jax.tree_util.tree_map(rep, snap.quotas),
        throughput=(
            None if snap.throughput is None else rep(snap.throughput)
        ),
    )


def _factor2(n: int):
    """Split n into (a, b) with a*b = n, as square as possible."""
    a = int(np.floor(np.sqrt(n)))
    while n % a:
        a -= 1
    return a, n // a


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    dp, tp = _factor2(len(devices))
    return Mesh(np.asarray(devices).reshape(dp, tp), ("pods", "nodes"))


def shard_snapshot_for_scoring(snap: ClusterSnapshot, mesh: Mesh) -> ClusterSnapshot:
    """Shard pods over the 'pods' axis and nodes over the 'nodes' axis.

    The resulting ``score_cycle`` output [P, N] is sharded over both mesh
    axes with zero communication (pure SPMD map).
    """
    pod2 = NamedSharding(mesh, P("pods", None))
    pod1 = NamedSharding(mesh, P("pods"))
    node2 = NamedSharding(mesh, P("nodes", None))
    node1 = NamedSharding(mesh, P("nodes"))
    rep = NamedSharding(mesh, P())

    pods = snap.pods
    nodes = snap.nodes
    return ClusterSnapshot(
        nodes=dataclass_replace(
            nodes,
            allocatable=jax.device_put(nodes.allocatable, node2),
            requested=jax.device_put(nodes.requested, node2),
            usage=jax.device_put(nodes.usage, node2),
            metric_fresh=jax.device_put(nodes.metric_fresh, node1),
            valid=jax.device_put(nodes.valid, node1),
            accel_type=(
                None if nodes.accel_type is None
                else jax.device_put(nodes.accel_type, node1)
            ),
        ),
        pods=dataclass_replace(
            pods,
            requests=jax.device_put(pods.requests, pod2),
            estimated=jax.device_put(pods.estimated, pod2),
            priority_class=jax.device_put(pods.priority_class, pod1),
            qos=jax.device_put(pods.qos, pod1),
            priority=jax.device_put(pods.priority, pod1),
            gang_id=jax.device_put(pods.gang_id, pod1),
            quota_id=jax.device_put(pods.quota_id, pod1),
            valid=jax.device_put(pods.valid, pod1),
        ),
        gangs=jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), snap.gangs),
        quotas=jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), snap.quotas),
        throughput=(
            None if snap.throughput is None
            else jax.device_put(snap.throughput, rep)
        ),
    )


def shard_snapshot_for_assign(snap: ClusterSnapshot, mesh: Mesh) -> ClusterSnapshot:
    """Shard node state across ALL mesh devices; replicate pod rows.

    The greedy scan's carried node state lives sharded; each step's
    argmax-over-nodes is a sharded reduce over ICI.
    """
    all_axes = ("pods", "nodes")
    node2 = NamedSharding(mesh, P(all_axes, None))
    node1 = NamedSharding(mesh, P(all_axes))
    rep = NamedSharding(mesh, P())

    nodes = snap.nodes
    return ClusterSnapshot(
        nodes=dataclass_replace(
            nodes,
            allocatable=jax.device_put(nodes.allocatable, node2),
            requested=jax.device_put(nodes.requested, node2),
            usage=jax.device_put(nodes.usage, node2),
            metric_fresh=jax.device_put(nodes.metric_fresh, node1),
            valid=jax.device_put(nodes.valid, node1),
            accel_type=(
                None if nodes.accel_type is None
                else jax.device_put(nodes.accel_type, node1)
            ),
        ),
        pods=jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), snap.pods),
        gangs=jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), snap.gangs),
        quotas=jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), snap.quotas),
        throughput=(
            None if snap.throughput is None
            else jax.device_put(snap.throughput, rep)
        ),
    )


