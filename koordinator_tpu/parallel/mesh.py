"""Multi-chip scale-out of the scoring/assignment tensors.

The reference scales the Score phase by fanning goroutines over nodes on one
machine (``frameworkext/framework_extender.go:216``).  The TPU-native scale
axis is a ``jax.sharding.Mesh``:

* ``pods`` mesh axis — data-parallel analog: each chip scores a slice of the
  pending-pod batch.
* ``nodes`` mesh axis — model-parallel analog: node state (allocatable /
  requested / usage) is sharded so clusters larger than one chip's HBM
  spread across ICI neighbors; argmax-over-nodes becomes an XLA collective.

One ``pods x nodes`` score tensor sharded over a 2-D mesh keeps all
collectives on ICI (scaling-book recipe: annotate shardings, let XLA insert
the collectives).  The sequential greedy scan shards node state over the
whole mesh and keeps per-pod rows replicated — each scan step's
argmax(masked score) then runs as a sharded reduce.
"""

from __future__ import annotations

from dataclasses import replace as dataclass_replace
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.model.snapshot import ClusterSnapshot


def _factor2(n: int):
    """Split n into (a, b) with a*b = n, as square as possible."""
    a = int(np.floor(np.sqrt(n)))
    while n % a:
        a -= 1
    return a, n // a


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    dp, tp = _factor2(len(devices))
    return Mesh(np.asarray(devices).reshape(dp, tp), ("pods", "nodes"))


def shard_snapshot_for_scoring(snap: ClusterSnapshot, mesh: Mesh) -> ClusterSnapshot:
    """Shard pods over the 'pods' axis and nodes over the 'nodes' axis.

    The resulting ``score_cycle`` output [P, N] is sharded over both mesh
    axes with zero communication (pure SPMD map).
    """
    pod2 = NamedSharding(mesh, P("pods", None))
    pod1 = NamedSharding(mesh, P("pods"))
    node2 = NamedSharding(mesh, P("nodes", None))
    node1 = NamedSharding(mesh, P("nodes"))
    rep = NamedSharding(mesh, P())

    pods = snap.pods
    nodes = snap.nodes
    return ClusterSnapshot(
        nodes=dataclass_replace(
            nodes,
            allocatable=jax.device_put(nodes.allocatable, node2),
            requested=jax.device_put(nodes.requested, node2),
            usage=jax.device_put(nodes.usage, node2),
            metric_fresh=jax.device_put(nodes.metric_fresh, node1),
            valid=jax.device_put(nodes.valid, node1),
        ),
        pods=dataclass_replace(
            pods,
            requests=jax.device_put(pods.requests, pod2),
            estimated=jax.device_put(pods.estimated, pod2),
            priority_class=jax.device_put(pods.priority_class, pod1),
            qos=jax.device_put(pods.qos, pod1),
            priority=jax.device_put(pods.priority, pod1),
            gang_id=jax.device_put(pods.gang_id, pod1),
            quota_id=jax.device_put(pods.quota_id, pod1),
            valid=jax.device_put(pods.valid, pod1),
        ),
        gangs=jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), snap.gangs),
        quotas=jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), snap.quotas),
    )


def shard_snapshot_for_assign(snap: ClusterSnapshot, mesh: Mesh) -> ClusterSnapshot:
    """Shard node state across ALL mesh devices; replicate pod rows.

    The greedy scan's carried node state lives sharded; each step's
    argmax-over-nodes is a sharded reduce over ICI.
    """
    all_axes = ("pods", "nodes")
    node2 = NamedSharding(mesh, P(all_axes, None))
    node1 = NamedSharding(mesh, P(all_axes))
    rep = NamedSharding(mesh, P())

    nodes = snap.nodes
    return ClusterSnapshot(
        nodes=dataclass_replace(
            nodes,
            allocatable=jax.device_put(nodes.allocatable, node2),
            requested=jax.device_put(nodes.requested, node2),
            usage=jax.device_put(nodes.usage, node2),
            metric_fresh=jax.device_put(nodes.metric_fresh, node1),
            valid=jax.device_put(nodes.valid, node1),
        ),
        pods=jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), snap.pods),
        gangs=jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), snap.gangs),
        quotas=jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), snap.quotas),
    )


