from koordinator_tpu.parallel.mesh import (  # noqa: F401
    CLUSTER_AXIS,
    cluster_mesh,
    make_mesh,
    node_sharding,
    pow2_device_count,
    replicated_sharding,
    score_sharding,
    shard_cluster_snapshot,
    shard_map_compat,
    shard_snapshot_for_scoring,
    shard_snapshot_for_assign,
    snapshot_partition_specs,
    snapshot_shardings,
)
from koordinator_tpu.parallel.shard_assign import (  # noqa: F401
    greedy_assign_sharded,
    greedy_assign_waves,
)
