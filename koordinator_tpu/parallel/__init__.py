from koordinator_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    shard_snapshot_for_scoring,
    shard_snapshot_for_assign,
)
from koordinator_tpu.parallel.shard_assign import (  # noqa: F401
    greedy_assign_sharded,
    greedy_assign_waves,
)
