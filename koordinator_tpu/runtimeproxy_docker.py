"""koord-runtime-proxy docker path: an HTTP reverse proxy for dockerd.

Reference ``pkg/runtimeproxy/server/docker``: the proxy serves the docker
API between kubelet (dockershim) and dockerd, intercepting
``POST /(vX.Y/)?containers/create`` (``server.go:64``) to run the hook
chain and merge cgroup mutations into the request's HostConfig before
forwarding; every other request passes through the reverse proxy
untouched (``pkg/util/httputil`` reverse proxy).
"""

from __future__ import annotations

import http.client
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Mapping, Optional, Tuple

from koordinator_tpu.httpserving import HTTPLifecycle
from koordinator_tpu.koordlet.runtimehooks import ContainerContext, HookRegistry
from koordinator_tpu.runtimeproxy import FailurePolicy

_CREATE_RE = re.compile(r"^/(v\d\.\d+/)?containers/create$")

_HOP_HEADERS = {
    "connection",
    "keep-alive",
    "proxy-authenticate",
    "proxy-authorization",
    "te",
    "trailers",
    "transfer-encoding",
    "upgrade",
    "host",
    "content-length",
}


class DockerProxyServer:
    """HTTP interposer in front of a dockerd endpoint (host, port)."""

    def __init__(
        self,
        registry: HookRegistry,
        backend: Tuple[str, int],
        host: str = "127.0.0.1",
        port: int = 0,
        failure_policy: FailurePolicy = FailurePolicy.IGNORE,
    ):
        self.registry = registry
        self.backend = backend
        self.failure_policy = failure_policy
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _proxy(self, body: Optional[bytes]):
                try:
                    conn = http.client.HTTPConnection(
                        *outer.backend, timeout=30
                    )
                    headers = {
                        k: v
                        for k, v in self.headers.items()
                        if k.lower() not in _HOP_HEADERS
                    }
                    conn.request(
                        self.command, self.path, body=body, headers=headers
                    )
                    resp = conn.getresponse()
                except OSError as exc:
                    # backend down: a structured 502, not a TCP reset
                    self._error(502, f"runtime backend unavailable: {exc}")
                    return
                length = resp.getheader("Content-Length")
                self.send_response(resp.status)
                for k, v in resp.getheaders():
                    if k.lower() not in _HOP_HEADERS:
                        self.send_header(k, v)
                if length is not None:
                    self.send_header("Content-Length", length)
                    self.end_headers()
                    remaining = int(length)
                    while remaining > 0:
                        chunk = resp.read(min(65536, remaining))
                        if not chunk:
                            break
                        self.wfile.write(chunk)
                        remaining -= len(chunk)
                else:
                    # unbounded/streaming endpoint (events, logs?follow):
                    # stream chunks through, close-delimited — never buffer
                    # the whole body (it may never end)
                    self.send_header("Connection", "close")
                    self.end_headers()
                    while True:
                        chunk = resp.read(65536)
                        if not chunk:
                            break
                        self.wfile.write(chunk)
                        self.wfile.flush()
                    self.close_connection = True
                conn.close()

            def _error(self, code: int, message: str):
                data = json.dumps({"message": message}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._proxy(None)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                if _CREATE_RE.match(self.path.split("?")[0]):
                    try:
                        body = outer._intercept_create(body)
                    except Exception as exc:  # FAIL policy: structured 500
                        self._error(500, f"hook chain failed: {exc}")
                        return
                self._proxy(body)

            do_DELETE = do_GET

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http = HTTPLifecycle(self._httpd)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "DockerProxyServer":
        self._http.start()
        return self

    def stop(self):
        self._http.stop()

    # -- create interception (docker/handler.go HandleCreateContainer) --
    def _intercept_create(self, body: bytes) -> bytes:
        try:
            doc = json.loads(body or b"{}")
        except ValueError:
            return body  # passthrough on unparseable body
        labels = doc.get("Labels") or {}
        # explicit JSON null must not crash the interposer
        host_config = doc.get("HostConfig") or {}
        doc["HostConfig"] = host_config
        ctx = ContainerContext(
            pod_uid=labels.get("io.kubernetes.pod.uid", ""),
            container_name=labels.get("io.kubernetes.container.name", ""),
            qos=labels.get("koordinator.sh/qosClass", ""),
            pod_labels=dict(labels),
            # dockershim stores pod annotations as "annotation."-prefixed
            # labels; annotation-reading hooks (cpuset, device env) need
            # them back under their bare keys
            pod_annotations={
                k[len("annotation."):]: v
                for k, v in labels.items()
                if k.startswith("annotation.")
            },
            cgroup_dir=host_config.get("CgroupParent", ""),
            cfs_quota_us=host_config.get("CpuQuota"),
            cpu_shares=host_config.get("CpuShares"),
            cpuset_cpus=host_config.get("CpusetCpus"),
            memory_limit_bytes=host_config.get("Memory"),
        )
        try:
            self.registry.run("PreCreateContainer", ctx)
        except Exception:
            if self.failure_policy == FailurePolicy.FAIL:
                raise
            return body  # Ignore: forward the original request untouched
        if ctx.cfs_quota_us is not None:
            host_config["CpuQuota"] = ctx.cfs_quota_us
        if ctx.cpu_shares is not None:
            host_config["CpuShares"] = ctx.cpu_shares
        if ctx.cpuset_cpus is not None:
            host_config["CpusetCpus"] = ctx.cpuset_cpus
        if ctx.memory_limit_bytes is not None:
            host_config["Memory"] = ctx.memory_limit_bytes
        env = doc.get("Env") or []
        doc["Env"] = env
        for k, v in ctx.env.items():
            env.append(f"{k}={v}")
        return json.dumps(doc).encode()
