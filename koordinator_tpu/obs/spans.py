"""Low-overhead monotonic span recorder for the TPU scoring pipeline.

One *cycle* is the unit of correlation: everything that happens between
two Assign (or Score) completions — delta-Sync decodes, device
scatters, dispatch, readback — accumulates on the current
:class:`CycleSpans` under one explicit ``cycle_id``, and ``commit()``
turns it into a plain-dict record for the metric families and the
flight recorder (obs/flight.py).

Design constraints (the acceptance criteria of ISSUE 4):

* **No host syncs, no retraces.**  The recorder only ever touches
  host-side Python scalars: ``begin_span``/``end_span`` read a
  monotonic clock and append to a list; ``note()`` stores values the
  caller already materialized.  Nothing here imports jax, and calling
  the span API inside jitted code is rejected statically by koordlint's
  ``host-sync-in-jit`` rule (a span inside a traced function would
  record trace time once and then never run again — the same trap as a
  bare ``print``).  Device-derived stats (``rounds``, ``path``,
  ``wave_ms``) enter through ``note()`` AFTER the caller materialized
  the result, never from inside the device program.
* **Bounded memory.**  A cycle caps its span count; a serve loop that
  never commits (Score-only traffic was the hazard) cannot grow without
  bound — overflowing spans are counted, not stored.
* **Leak-proof spans.**  ``span()`` is the context-manager form and the
  only one most call sites should use; raw ``begin_span`` callers must
  end the span on every exit path (enforced by koordlint's
  ``span-leak`` rule: try/finally or the context manager).
* **Thread-safe recorder.**  Since the coalescing dispatch engine
  (ISSUE 5, bridge/coalesce.py) split the servicer's single lock, RPC
  bodies no longer serialize the recorder for free: a Score batch
  leader, a pipelined Sync commit and an Assign device section can all
  touch the current cycle.  :class:`SpanRecorder` therefore guards its
  public API with a small RLock (host-side, ~100ns — invisible next to
  a device launch); :class:`CycleSpans` itself stays lock-free and is
  only reached through the recorder.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional

# hard cap on spans buffered per cycle: a runaway instrumentation loop
# must cost a counter bump, not memory
MAX_SPANS_PER_CYCLE = 256


class CycleSpans:
    """Span + note accumulator for one cycle.  Plain lists, no locks:
    the owner (ScorerServicer) already serializes RPC bodies."""

    __slots__ = (
        "cycle_id", "snapshot_id", "started_unix", "_t0", "_clock",
        "spans", "notes", "error", "overflow",
    )

    def __init__(self, cycle_id: str, clock=time.perf_counter,
                 wall_clock=time.time):
        self.cycle_id = cycle_id
        self.snapshot_id: Optional[str] = None
        self.started_unix = wall_clock()
        self._clock = clock
        self._t0 = clock()
        # each span is [name, start_s, end_s|None] in monotonic seconds
        # relative to the cycle's _t0
        self.spans: List[list] = []
        self.notes: Dict[str, object] = {}
        self.error: Optional[str] = None
        self.overflow = 0

    def begin(self, name: str) -> int:
        """Open a span; returns the handle ``end()`` closes.  A handle
        of -1 means the cycle's span buffer is full (the matching
        ``end(-1)`` is a no-op, so callers never branch)."""
        if len(self.spans) >= MAX_SPANS_PER_CYCLE:
            self.overflow += 1
            return -1
        self.spans.append([name, self._clock() - self._t0, None])
        return len(self.spans) - 1

    def end(self, handle: int) -> None:
        # the upper bound guards a handle minted by a PREVIOUS cycle
        # (begin before a commit, end after): closing a stranger's span
        # — or crashing the RPC on IndexError — is worse than dropping
        # the stale end
        if handle < 0 or handle >= len(self.spans):
            return
        self.spans[handle][2] = self._clock() - self._t0

    def add_measured(self, name: str, dur_s: float) -> None:
        """Record an already-measured stage as a closed span ending now.

        The coalescing pipeline measures some stages OUTSIDE the lock
        that guards this recorder (a Sync's protobuf->numpy decode, a
        batch leader's shared dispatch/readback) and attaches them at
        the commit point; the start is back-computed and clamped to the
        cycle origin (a decode can legitimately begin before the cycle
        it lands on exists)."""
        if len(self.spans) >= MAX_SPANS_PER_CYCLE:
            self.overflow += 1
            return
        end = self._clock() - self._t0
        self.spans.append([name, max(0.0, end - max(0.0, dur_s)), end])

    def to_record(self) -> Dict[str, object]:
        """Flight-recorder/bench shape: durations in milliseconds; a
        span that never ended carries ``dur_ms: None`` (visible, not
        invented)."""
        return {
            "cycle_id": self.cycle_id,
            "snapshot_id": self.snapshot_id,
            "started_unix": self.started_unix,
            "spans": [
                {
                    "name": name,
                    "start_ms": round(start * 1000.0, 3),
                    "dur_ms": (
                        round((end - start) * 1000.0, 3)
                        if end is not None else None
                    ),
                }
                for name, start, end in self.spans
            ],
            "notes": dict(self.notes),
            "error": self.error,
            "span_overflow": self.overflow,
        }


class _SpanContext:
    """Tiny re-usable with-block over begin/end.  Not @contextmanager:
    a generator frame per span is measurable overhead on the warm path."""

    __slots__ = ("_recorder", "_name", "_handle")

    def __init__(self, recorder: "SpanRecorder", name: str):
        self._recorder = recorder
        self._name = name
        self._handle = -1

    def __enter__(self) -> "_SpanContext":
        self._handle = self._recorder.begin_span(self._name)
        return self

    def __exit__(self, *exc) -> bool:
        self._recorder.end_span(self._handle)
        return False


class CycleScope:
    """One RPC's private cycle (ISSUE 6, the span-correlation fix).

    Under the coalescing pipeline several RPCs run concurrently, and
    two confirmed record blurs came from them sharing the recorder's
    ONE open cycle: an Assign adopting (and relabeling) a cycle another
    RPC was still stamping, and a displaced Assign's stamps landing on
    the pending cycle awaiting a different client's correlation.
    ``SpanRecorder.open_scope`` detaches a cycle into this wrapper —
    atomically claiming the pending cycle when the RPC is its rightful
    correlator, minting a fresh one otherwise — so concurrent RPCs can
    never stamp or relabel each other's records.  Replies were always
    unaffected; this makes the cycle *records* exact too.

    The API mirrors the recorder's span surface (``span``/``note``/
    ``add_measured``/``begin_span``/``end_span``), so recorder-typed
    call sites (``maybe_span``, ``_assign_cycle``, the shard path)
    accept a scope unchanged.  ``commit()`` returns the record; a scope
    is single-shot and never re-enters the recorder."""

    __slots__ = ("_cycle", "_lock")

    def __init__(self, cycle: CycleSpans):
        self._cycle = cycle
        self._lock = threading.RLock()

    @property
    def cycle_id(self) -> str:
        return self._cycle.cycle_id

    @property
    def snapshot_id(self) -> Optional[str]:
        return self._cycle.snapshot_id

    def begin_span(self, name: str) -> int:
        with self._lock:
            return self._cycle.begin(name)

    def end_span(self, handle: int) -> None:
        with self._lock:
            self._cycle.end(handle)

    def add_measured(self, name: str, dur_s: float) -> None:
        with self._lock:
            self._cycle.add_measured(name, dur_s)

    def span(self, name: str) -> "_SpanContext":
        return _SpanContext(self, name)

    def note(self, key: str, value) -> None:
        with self._lock:
            self._cycle.notes[key] = value

    def commit(self, error: Optional[str] = None) -> Dict[str, object]:
        with self._lock:
            if error is not None:
                self._cycle.error = error
            return self._cycle.to_record()


class SpanRecorder:
    """Owns the current cycle and mints cycle ids ("c<epoch>-<seq>",
    correlating with the sidecar's "s<epoch>-<gen>" snapshot ids)."""

    def __init__(self, epoch: str = "", clock=time.perf_counter,
                 wall_clock=time.time):
        self.epoch = epoch
        self._clock = clock
        self._wall_clock = wall_clock
        self._seq = 0
        self._cycle: Optional[CycleSpans] = None
        # reentrant: commit() calls current(); the lock makes each call
        # atomic against the coalescer's concurrent batch leaders
        self._lock = threading.RLock()

    # -- cycle lifecycle --
    def has_pending(self) -> bool:
        """Whether an uncommitted cycle is already accumulating spans
        (e.g. a delta-Sync waiting for the Assign that correlates it)."""
        with self._lock:
            return self._cycle is not None

    def current(self, snapshot_id: Optional[str] = None,
                cycle_id: Optional[str] = None) -> CycleSpans:
        """The open cycle, created on first touch.  ``cycle_id`` adopts
        a caller-supplied correlation id (the AssignRequest's) for the
        open cycle; ``snapshot_id`` stamps the resident snapshot it ran
        against."""
        with self._lock:
            if self._cycle is None:
                self._seq += 1
                self._cycle = CycleSpans(
                    cycle_id or f"c{self.epoch}-{self._seq}",
                    clock=self._clock, wall_clock=self._wall_clock,
                )
            elif cycle_id:
                self._cycle.cycle_id = cycle_id
            if snapshot_id is not None:
                self._cycle.snapshot_id = snapshot_id
            return self._cycle

    def commit(self, error: Optional[str] = None) -> Dict[str, object]:
        """Close the current cycle and return its record (an empty cycle
        is created if nothing was recorded, so commit() is total)."""
        with self._lock:
            cycle = self.current()
            if error is not None:
                cycle.error = error
            record = cycle.to_record()
            self._cycle = None
            return record

    def open_scope(
        self,
        snapshot_id: Optional[str] = None,
        cycle_id: Optional[str] = None,
        adopt_pending: bool = True,
    ) -> CycleScope:
        """Detach a cycle into a private :class:`CycleScope`.

        With ``adopt_pending`` (the correlating RPC — e.g. the Assign
        that closes a Sync→Score→Assign flow) the pending cycle, if
        any, is claimed ATOMICALLY: it leaves the recorder in the same
        lock hold, so a concurrent RPC can neither relabel it nor land
        stray stamps on it, and the next ``current()`` starts fresh.
        ``adopt_pending=False`` (a sibling RPC racing the correlator)
        always mints a fresh cycle and leaves the pending one alone."""
        with self._lock:
            if adopt_pending and self._cycle is not None:
                cycle = self._cycle
                self._cycle = None
                if cycle_id:
                    cycle.cycle_id = cycle_id
            else:
                self._seq += 1
                cycle = CycleSpans(
                    cycle_id or f"c{self.epoch}-{self._seq}",
                    clock=self._clock, wall_clock=self._wall_clock,
                )
            if snapshot_id is not None:
                cycle.snapshot_id = snapshot_id
            return CycleScope(cycle)

    # -- span API --
    def begin_span(self, name: str) -> int:
        with self._lock:
            return self.current().begin(name)

    def end_span(self, handle: int) -> None:
        with self._lock:
            if self._cycle is not None:
                self._cycle.end(handle)

    def add_measured(self, name: str, dur_s: float) -> None:
        """Attach a stage measured outside the recorder (see
        ``CycleSpans.add_measured``) to the current cycle."""
        with self._lock:
            self.current().add_measured(name, dur_s)

    def pending_spans(self) -> int:
        """Span count buffered on the open cycle (0 when none) — the
        backlog-flush threshold check, made atomic for the coalescer."""
        with self._lock:
            return len(self._cycle.spans) if self._cycle is not None else 0

    def span(self, name: str) -> _SpanContext:
        """``with recorder.span("dispatch"): ...`` — the leak-proof
        form (koordlint span-leak enforces raw begin/end callers use
        try/finally)."""
        return _SpanContext(self, name)

    def note(self, key: str, value) -> None:
        """Attach a device-derived or config stat to the current cycle.
        ``value`` must already be a host-side Python scalar/str — pass
        ``int(np.asarray(x))`` results, never live tracers."""
        with self._lock:
            self.current().notes[key] = value


_NULL_CONTEXT = contextlib.nullcontext()


def maybe_span(recorder: Optional[SpanRecorder], name: str):
    """``with maybe_span(spans, "stage"):`` for recorder-optional call
    sites (bridge/state.py, parallel/shard_assign.py take ``spans=None``
    by default) — leak-proof by construction, no handle bookkeeping."""
    if recorder is None:
        return _NULL_CONTEXT
    return recorder.span(name)
