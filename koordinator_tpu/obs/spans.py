"""Low-overhead monotonic span recorder for the TPU scoring pipeline.

One *cycle* is the unit of correlation: everything that happens between
two Assign (or Score) completions — delta-Sync decodes, device
scatters, dispatch, readback — accumulates on the current
:class:`CycleSpans` under one explicit ``cycle_id``, and ``commit()``
turns it into a plain-dict record for the metric families and the
flight recorder (obs/flight.py).

Design constraints (the acceptance criteria of ISSUE 4):

* **No host syncs, no retraces.**  The recorder only ever touches
  host-side Python scalars: ``begin_span``/``end_span`` read a
  monotonic clock and append to a list; ``note()`` stores values the
  caller already materialized.  Nothing here imports jax, and calling
  the span API inside jitted code is rejected statically by koordlint's
  ``host-sync-in-jit`` rule (a span inside a traced function would
  record trace time once and then never run again — the same trap as a
  bare ``print``).  Device-derived stats (``rounds``, ``path``,
  ``wave_ms``) enter through ``note()`` AFTER the caller materialized
  the result, never from inside the device program.
* **Bounded memory.**  A cycle caps its span count; a serve loop that
  never commits (Score-only traffic was the hazard) cannot grow without
  bound — overflowing spans are counted, not stored.
* **Leak-proof spans.**  ``span()`` is the context-manager form and the
  only one most call sites should use; raw ``begin_span`` callers must
  end the span on every exit path (enforced by koordlint's
  ``span-leak`` rule: try/finally or the context manager).
* **Thread-safe recorder.**  Since the coalescing dispatch engine
  (ISSUE 5, bridge/coalesce.py) split the servicer's single lock, RPC
  bodies no longer serialize the recorder for free: a Score batch
  leader, a pipelined Sync commit and an Assign device section can all
  touch the current cycle.  :class:`SpanRecorder` therefore guards its
  public API with a small RLock (host-side, ~100ns — invisible next to
  a device launch); :class:`CycleSpans` itself stays lock-free and is
  only reached through the recorder.
"""

from __future__ import annotations

import contextlib
import time
import uuid
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.obs.lockwitness import witness_rlock

# hard cap on spans buffered per cycle: a runaway instrumentation loop
# must cost a counter bump, not memory
MAX_SPANS_PER_CYCLE = 256

# the one cross-trace link type (ISSUE 14): the many RPC spans of a
# coalesced batch — and every memo/brownout serve — reference the ONE
# launch/readback span that produced the shared bytes
LINK_FANIN = "fanin"


def mint_trace_id() -> str:
    """32-hex trace id, minted ONCE per logical client request; every
    retry/failover attempt keeps it, so the attempts assemble into one
    tree (obs/assemble.py)."""
    return uuid.uuid4().hex


def mint_span_id() -> str:
    """16-hex span id for spans minted outside a SpanRecorder (client
    shims; servers use ``SpanRecorder.mint_span_id`` so ids stay
    deterministic under a pinned epoch — the golden-fixture contract)."""
    return uuid.uuid4().hex[:16]


class TraceSpan:
    """One exportable span of a cross-process distributed trace
    (ISSUE 14).  Unlike the cycle-scoped stage spans below, a TraceSpan
    carries an identity — ``(trace_id, span_id)`` — a parent link, and
    fan-in links to spans in OTHER traces, so the offline assembler
    (``python -m koordinator_tpu.obs.assemble``) can merge per-process
    exports into one whole-request tree.

    Single-shot: ``end()`` (or ``abort()``) finalizes the span exactly
    once and hands the OTLP-shaped record to ``sink`` (the process's
    SpanExporter, obs/export.py).  Host-side Python scalars only — the
    same no-host-sync contract as the rest of this module.  Call sites
    that create one MUST end or abort it on every exit path (koordlint's
    ``span-leak`` rule checks ``start_trace_span`` callers statically);
    the context-manager form is leak-proof by construction."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "kind",
        "start_unix", "_clock", "_t0", "dur_ms", "error",
        "attrs", "links", "_sink", "_done",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str] = None,
        kind: str = "server",
        sink=None,
        attrs: Optional[Dict[str, object]] = None,
        clock=time.perf_counter,
        wall_clock=time.time,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id or None
        self.kind = kind
        self.start_unix = wall_clock()
        self._clock = clock
        self._t0 = clock()
        self.dur_ms: Optional[float] = None
        self.error: Optional[str] = None
        self.attrs: Dict[str, object] = dict(attrs or {})
        self.links: List[Dict[str, str]] = []
        self._sink = sink
        self._done = False

    @property
    def ref(self) -> Tuple[str, str]:
        """The cross-process handle other spans link to."""
        return (self.trace_id, self.span_id)

    def set_attr(self, key: str, value) -> None:
        """Host-side scalars only (the ``note()`` contract)."""
        self.attrs[key] = value

    def link(self, trace_id: str, span_id: str,
             link_type: str = LINK_FANIN) -> None:
        """Reference a span that may live in a DIFFERENT trace — the
        fan-in shape: N coalesced RPC spans -> one launch span."""
        self.links.append({
            "traceId": trace_id, "spanId": span_id, "type": link_type,
        })

    def link_ref(self, ref: Optional[Tuple[str, str]],
                 link_type: str = LINK_FANIN) -> None:
        """``link()`` over a stored ``(trace_id, span_id)`` ref (memo /
        brownout entries store these); None is a no-op so cache entries
        produced by an untraced launch need no branching."""
        if ref is not None:
            self.link(ref[0], ref[1], link_type)

    def end(self, error: Optional[str] = None) -> None:
        """Finalize and export.  Idempotent: the first end/abort wins,
        so a ``finally: span.end()`` after an except-path ``abort()``
        cannot double-export."""
        if self._done:
            return
        self._done = True
        self.dur_ms = (self._clock() - self._t0) * 1000.0
        if error is not None:
            self.error = error
        sink = self._sink
        if sink is not None:
            sink(self.to_record())

    def abort(self, exc: BaseException) -> None:
        self.end(error=f"{exc!r:.200}")

    def __enter__(self) -> "TraceSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.abort(exc)
        else:
            self.end()
        return False

    def to_record(self) -> Dict[str, object]:
        """The OTLP-shaped JSON-line body obs/export.py appends — flat
        camelCase keys, nanosecond wall stamps, links with the fan-in
        type in their attributes (obs/assemble.py is the reader)."""
        start_ns = int(self.start_unix * 1e9)
        dur_ns = int((self.dur_ms or 0.0) * 1e6)
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentSpanId": self.parent_id,
            "name": self.name,
            "kind": self.kind,
            "startTimeUnixNano": start_ns,
            "endTimeUnixNano": start_ns + dur_ns,
            "durMs": round(self.dur_ms or 0.0, 3),
            "status": (
                {"code": "ERROR", "message": self.error}
                if self.error is not None else {"code": "OK"}
            ),
            "attributes": dict(self.attrs),
            "links": list(self.links),
        }


class ClientTraceOp:
    """Client side of one logical RPC (ISSUE 14): ONE trace id, a root
    op span, and one child span per ATTEMPT — so a retried-then-shed-
    then-served request is one trace with one span per attempt.  Used
    by bridge/client.py; lives here so the id/record shapes have one
    home."""

    __slots__ = ("trace_id", "root", "attempts", "_sink")

    def __init__(self, name: str, sink=None):
        self._sink = sink
        self.trace_id = mint_trace_id()
        self.attempts = 0
        self.root = TraceSpan(
            name, self.trace_id, mint_span_id(), kind="client", sink=sink,
        )

    def attempt(self, target: str = "") -> TraceSpan:
        """A child span for the next attempt; the caller stamps its id
        as the request's ``parent_span`` and must end/abort it."""
        self.attempts += 1
        span = TraceSpan(
            f"{self.root.name}.attempt", self.trace_id, mint_span_id(),
            parent_id=self.root.span_id, kind="client", sink=self._sink,
            attrs={"attempt": self.attempts},
        )
        if target:
            span.set_attr("target", target)
        return span

    def finish(self, error: Optional[BaseException] = None) -> None:
        self.root.set_attr("attempts", self.attempts)
        if error is not None:
            self.root.abort(error)
        else:
            self.root.end()


class CycleSpans:
    """Span + note accumulator for one cycle.  Plain lists, no locks:
    the owner (ScorerServicer) already serializes RPC bodies."""

    __slots__ = (
        "cycle_id", "snapshot_id", "trace_id", "started_unix", "_t0",
        "_clock", "spans", "notes", "error", "overflow",
    )

    def __init__(self, cycle_id: str, clock=time.perf_counter,
                 wall_clock=time.time):
        self.cycle_id = cycle_id
        self.snapshot_id: Optional[str] = None
        # distributed-trace correlation (ISSUE 14): the trace id of the
        # request this cycle served, when the client sent one — the
        # flight-recorder record carries it so a bad cycle found in a
        # dump is addressable in the assembled trace tree (and vice
        # versa)
        self.trace_id: Optional[str] = None
        self.started_unix = wall_clock()
        self._clock = clock
        self._t0 = clock()
        # each span is [name, start_s, end_s|None] in monotonic seconds
        # relative to the cycle's _t0
        self.spans: List[list] = []
        self.notes: Dict[str, object] = {}
        self.error: Optional[str] = None
        self.overflow = 0

    def begin(self, name: str) -> int:
        """Open a span; returns the handle ``end()`` closes.  A handle
        of -1 means the cycle's span buffer is full (the matching
        ``end(-1)`` is a no-op, so callers never branch)."""
        if len(self.spans) >= MAX_SPANS_PER_CYCLE:
            self.overflow += 1
            return -1
        self.spans.append([name, self._clock() - self._t0, None])
        return len(self.spans) - 1

    def end(self, handle: int) -> None:
        # the upper bound guards a handle minted by a PREVIOUS cycle
        # (begin before a commit, end after): closing a stranger's span
        # — or crashing the RPC on IndexError — is worse than dropping
        # the stale end
        if handle < 0 or handle >= len(self.spans):
            return
        self.spans[handle][2] = self._clock() - self._t0

    def add_measured(self, name: str, dur_s: float) -> None:
        """Record an already-measured stage as a closed span ending now.

        The coalescing pipeline measures some stages OUTSIDE the lock
        that guards this recorder (a Sync's protobuf->numpy decode, a
        batch leader's shared dispatch/readback) and attaches them at
        the commit point; the start is back-computed and clamped to the
        cycle origin (a decode can legitimately begin before the cycle
        it lands on exists)."""
        if len(self.spans) >= MAX_SPANS_PER_CYCLE:
            self.overflow += 1
            return
        end = self._clock() - self._t0
        self.spans.append([name, max(0.0, end - max(0.0, dur_s)), end])

    def to_record(self) -> Dict[str, object]:
        """Flight-recorder/bench shape: durations in milliseconds; a
        span that never ended carries ``dur_ms: None`` (visible, not
        invented)."""
        return {
            "cycle_id": self.cycle_id,
            "snapshot_id": self.snapshot_id,
            "trace_id": self.trace_id,
            "started_unix": self.started_unix,
            "spans": [
                {
                    "name": name,
                    "start_ms": round(start * 1000.0, 3),
                    "dur_ms": (
                        round((end - start) * 1000.0, 3)
                        if end is not None else None
                    ),
                }
                for name, start, end in self.spans
            ],
            "notes": dict(self.notes),
            "error": self.error,
            "span_overflow": self.overflow,
        }


class _SpanContext:
    """Tiny re-usable with-block over begin/end.  Not @contextmanager:
    a generator frame per span is measurable overhead on the warm path."""

    __slots__ = ("_recorder", "_name", "_handle")

    def __init__(self, recorder: "SpanRecorder", name: str):
        self._recorder = recorder
        self._name = name
        self._handle = -1

    def __enter__(self) -> "_SpanContext":
        self._handle = self._recorder.begin_span(self._name)
        return self

    def __exit__(self, *exc) -> bool:
        self._recorder.end_span(self._handle)
        return False


class CycleScope:
    """One RPC's private cycle (ISSUE 6, the span-correlation fix).

    Under the coalescing pipeline several RPCs run concurrently, and
    two confirmed record blurs came from them sharing the recorder's
    ONE open cycle: an Assign adopting (and relabeling) a cycle another
    RPC was still stamping, and a displaced Assign's stamps landing on
    the pending cycle awaiting a different client's correlation.
    ``SpanRecorder.open_scope`` detaches a cycle into this wrapper —
    atomically claiming the pending cycle when the RPC is its rightful
    correlator, minting a fresh one otherwise — so concurrent RPCs can
    never stamp or relabel each other's records.  Replies were always
    unaffected; this makes the cycle *records* exact too.

    The API mirrors the recorder's span surface (``span``/``note``/
    ``add_measured``/``begin_span``/``end_span``), so recorder-typed
    call sites (``maybe_span``, ``_assign_cycle``, the shard path)
    accept a scope unchanged.  ``commit()`` returns the record; a scope
    is single-shot and never re-enters the recorder."""

    __slots__ = ("_cycle", "_lock")

    def __init__(self, cycle: CycleSpans):
        self._cycle = cycle
        self._lock = witness_rlock("obs.spans.CycleScope._lock")

    @property
    def cycle_id(self) -> str:
        return self._cycle.cycle_id

    @property
    def snapshot_id(self) -> Optional[str]:
        return self._cycle.snapshot_id

    @property
    def trace_id(self) -> Optional[str]:
        return self._cycle.trace_id

    def begin_span(self, name: str) -> int:
        with self._lock:
            return self._cycle.begin(name)

    def end_span(self, handle: int) -> None:
        with self._lock:
            self._cycle.end(handle)

    def add_measured(self, name: str, dur_s: float) -> None:
        with self._lock:
            self._cycle.add_measured(name, dur_s)

    def span(self, name: str) -> "_SpanContext":
        return _SpanContext(self, name)

    def note(self, key: str, value) -> None:
        with self._lock:
            self._cycle.notes[key] = value

    def commit(self, error: Optional[str] = None) -> Dict[str, object]:
        with self._lock:
            if error is not None:
                self._cycle.error = error
            return self._cycle.to_record()


class SpanRecorder:
    """Owns the current cycle and mints cycle ids ("c<epoch>-<seq>",
    correlating with the sidecar's "s<epoch>-<gen>" snapshot ids)."""

    def __init__(self, epoch: str = "", clock=time.perf_counter,
                 wall_clock=time.time):
        self.epoch = epoch
        self._clock = clock
        self._wall_clock = wall_clock
        self._seq = 0
        # distributed-trace span ids (ISSUE 14): counter-based and
        # epoch-prefixed like cycle ids, so a pinned epoch makes them
        # deterministic (the golden-fixture regen contract); the sink
        # is the process's exporter, wired by CycleTelemetry
        self._span_seq = 0
        self.trace_sink = None
        self._cycle: Optional[CycleSpans] = None
        # reentrant: commit() calls current(); the lock makes each call
        # atomic against the coalescer's concurrent batch leaders
        self._lock = witness_rlock("obs.spans.SpanRecorder._lock")

    # -- cycle lifecycle --
    def has_pending(self) -> bool:
        """Whether an uncommitted cycle is already accumulating spans
        (e.g. a delta-Sync waiting for the Assign that correlates it)."""
        with self._lock:
            return self._cycle is not None

    # -- distributed-trace spans (ISSUE 14) --
    def mint_span_id(self) -> str:
        """Deterministic under a pinned epoch: "sp<epoch>-<n>" (the
        cycle-id convention), so golden-fixture regens stay
        byte-identical."""
        with self._lock:
            self._span_seq += 1
            return f"sp{self.epoch}-{self._span_seq}"

    def start_trace_span(
        self,
        name: str,
        trace_id: str,
        parent_id: Optional[str] = None,
        kind: str = "server",
        attrs: Optional[Dict[str, object]] = None,
    ) -> Optional[TraceSpan]:
        """Open one exportable distributed-trace span, or None when
        ``trace_id`` is empty (tracing off for this request: the
        untraced path pays one truthiness check and nothing else).
        The caller MUST end or abort the returned span on every exit
        path — koordlint's ``span-leak`` rule enforces the try/finally
        (or with-block) shape statically."""
        if not trace_id:
            return None
        return TraceSpan(
            name, trace_id, self.mint_span_id(), parent_id=parent_id,
            kind=kind, sink=self.trace_sink, attrs=attrs,
            clock=self._clock, wall_clock=self._wall_clock,
        )

    def current(self, snapshot_id: Optional[str] = None,
                cycle_id: Optional[str] = None) -> CycleSpans:
        """The open cycle, created on first touch.  ``cycle_id`` adopts
        a caller-supplied correlation id (the AssignRequest's) for the
        open cycle; ``snapshot_id`` stamps the resident snapshot it ran
        against."""
        with self._lock:
            if self._cycle is None:
                self._seq += 1
                self._cycle = CycleSpans(
                    cycle_id or f"c{self.epoch}-{self._seq}",
                    clock=self._clock, wall_clock=self._wall_clock,
                )
            elif cycle_id:
                self._cycle.cycle_id = cycle_id
            if snapshot_id is not None:
                self._cycle.snapshot_id = snapshot_id
            return self._cycle

    def commit(self, error: Optional[str] = None) -> Dict[str, object]:
        """Close the current cycle and return its record (an empty cycle
        is created if nothing was recorded, so commit() is total)."""
        with self._lock:
            cycle = self.current()
            if error is not None:
                cycle.error = error
            record = cycle.to_record()
            self._cycle = None
            return record

    def open_scope(
        self,
        snapshot_id: Optional[str] = None,
        cycle_id: Optional[str] = None,
        adopt_pending: bool = True,
        trace_id: Optional[str] = None,
    ) -> CycleScope:
        """Detach a cycle into a private :class:`CycleScope`.

        With ``adopt_pending`` (the correlating RPC — e.g. the Assign
        that closes a Sync→Score→Assign flow) the pending cycle, if
        any, is claimed ATOMICALLY: it leaves the recorder in the same
        lock hold, so a concurrent RPC can neither relabel it nor land
        stray stamps on it, and the next ``current()`` starts fresh.
        ``adopt_pending=False`` (a sibling RPC racing the correlator)
        always mints a fresh cycle and leaves the pending one alone."""
        with self._lock:
            if adopt_pending and self._cycle is not None:
                cycle = self._cycle
                self._cycle = None
                if cycle_id:
                    cycle.cycle_id = cycle_id
            else:
                self._seq += 1
                cycle = CycleSpans(
                    cycle_id or f"c{self.epoch}-{self._seq}",
                    clock=self._clock, wall_clock=self._wall_clock,
                )
            if snapshot_id is not None:
                cycle.snapshot_id = snapshot_id
            if trace_id:
                cycle.trace_id = trace_id
            return CycleScope(cycle)

    # -- span API --
    def begin_span(self, name: str) -> int:
        with self._lock:
            return self.current().begin(name)

    def end_span(self, handle: int) -> None:
        with self._lock:
            if self._cycle is not None:
                self._cycle.end(handle)

    def add_measured(self, name: str, dur_s: float) -> None:
        """Attach a stage measured outside the recorder (see
        ``CycleSpans.add_measured``) to the current cycle."""
        with self._lock:
            self.current().add_measured(name, dur_s)

    def pending_spans(self) -> int:
        """Span count buffered on the open cycle (0 when none) — the
        backlog-flush threshold check, made atomic for the coalescer."""
        with self._lock:
            return len(self._cycle.spans) if self._cycle is not None else 0

    def span(self, name: str) -> _SpanContext:
        """``with recorder.span("dispatch"): ...`` — the leak-proof
        form (koordlint span-leak enforces raw begin/end callers use
        try/finally)."""
        return _SpanContext(self, name)

    def note(self, key: str, value) -> None:
        """Attach a device-derived or config stat to the current cycle.
        ``value`` must already be a host-side Python scalar/str — pass
        ``int(np.asarray(x))`` results, never live tracers."""
        with self._lock:
            self.current().notes[key] = value


_NULL_CONTEXT = contextlib.nullcontext()


def maybe_span(recorder: Optional[SpanRecorder], name: str):
    """``with maybe_span(spans, "stage"):`` for recorder-optional call
    sites (bridge/state.py, parallel/shard_assign.py take ``spans=None``
    by default) — leak-proof by construction, no handle bookkeeping."""
    if recorder is None:
        return _NULL_CONTEXT
    return recorder.span(name)
