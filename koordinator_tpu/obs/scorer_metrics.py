"""Scorer metric families over the koordlet Prometheus-text registry.

One place declares every family the bridge daemon exports on /metrics
(koordlet/metrics.py renders the exposition text; the registration is
idempotent, so a restarted daemon re-registering is a no-op).  Families:

====================================== ========= ==========================
family                                 kind      labels
====================================== ========= ==========================
koord_scorer_cycle_latency_ms          histogram path, wave
koord_scorer_cycle_rounds              gauge     path
koord_scorer_rounds_total              counter   path
koord_scorer_cycles_total              counter   path
koord_scorer_cycle_errors_total        counter   stage
koord_scorer_sync_total                counter   kind (delta|full|mixed|scalar)
koord_scorer_sync_tensors_total        counter   kind (delta|full)
koord_scorer_jit_cache_miss_total      counter   kind (trace|compile)
koord_scorer_snapshot_generation       gauge     —
koord_scorer_resident_epoch            gauge     epoch (value always 1)
koord_scorer_resident_warm             gauge     — (last Sync: 1 warm/0 cold)
koord_scorer_kernel_demotions_total    counter   —
koord_scorer_uds_frames_total          counter   method
koord_scorer_uds_malformed_total       counter   reason
koord_scorer_uds_errors_total          counter   —
koord_scorer_coalesce_queue_delay_ms   histogram —
koord_scorer_coalesce_batch_occupancy  histogram —
koord_scorer_coalesce_batches_total    counter   —
koord_scorer_coalesce_requests_total   counter   —
koord_scorer_coalesce_window_ms        gauge     —
koord_scorer_coalesce_device_idle_ms   gauge     — (cumulative)
koord_scorer_assign_memo_total         counter   result (hit|miss)
koord_scorer_score_memo_total          counter   result (hit|miss)
koord_scorer_score_incr_total          counter   result (incr|full|fallback)
koord_scorer_incr_cols                 histogram —
koord_scorer_term_total                counter   term (heterogeneity|sensitivity|packing)
koord_scorer_shed_total                counter   method (score|assign)
koord_scorer_shed_band_total           counter   band (koord-prod|mid|batch|free|none)
koord_scorer_deadline_expired_total    counter   stage (queue|gather)
koord_scorer_degraded_total            counter   rpc (score)
koord_scorer_breaker_state             gauge     state (closed|half-open|open)
koord_scorer_breaker_transitions_total counter   to (closed|half-open|open)
koord_scorer_breaker_rejected_total    counter   method (score|assign)
koord_scorer_replica_role              gauge     role (leader|follower)
koord_scorer_replica_frames_total      counter   result (applied|stale|resync|error)
koord_scorer_replica_lag_ms            gauge     —
koord_scorer_replica_resyncs_total     counter   reason (gap|epoch|decode|apply|connect)
koord_scorer_replica_followers         gauge     — (leader: live subscribers)
koord_scorer_journal_frames_total      counter   op (append|replay|compact|truncate)
koord_scorer_journal_append_us         histogram —
koord_scorer_journal_position          gauge     — (last journaled generation)
koord_scorer_journal_bytes             gauge     — (journal file size)
koord_scorer_journal_compaction_stamp  gauge     — (us since epoch, last compaction)
koord_scorer_failover_total            counter   event (promoted|warm_restart)
koord_scorer_retry_total               counter   op (subscribe|resume)
koord_scorer_trace_cycle_ms            histogram band, rpc
koord_scorer_trace_spans_total         counter   kind (client|server|internal|consumer)
koord_scorer_trace_export_dropped_total counter  reason (closed|rate|bytes|encode|io)
koord_scorer_candidate_refresh_total   counter   reason (dirty|stale|cold)
koord_scorer_candidate_width           gauge     — (configured C; 0 = dense)
koord_scorer_lock_witness_edges_total  counter   result (observed|inversion)
koord_scorer_relay_position            gauge     — (hops from the root leader)
koord_scorer_relay_forwarded_total     counter   — (frames re-published)
koord_scorer_replica_hop_lag_ms        gauge     hop
koord_scorer_repl_send_batch_frames    histogram —
koord_scorer_repl_compress_total       counter   op (encode|decode)
koord_scorer_autoscale_events_total    counter   action (scale_up|scale_down)
koord_scorer_autoscale_replicas        gauge     — (autoscaler's target size)
koord_scorer_devprof_compiles_total    counter   boundary, backend
koord_scorer_devprof_compile_ms_total  counter   boundary, backend
koord_scorer_devprof_device_us         histogram boundary
koord_scorer_devprof_retrace_total     counter   boundary
koord_scorer_prewarm_signatures_total  counter   result (compiled|skipped|failed)
koord_scorer_prewarm_compile_ms_total  counter   —
koord_scorer_prewarm_pending           gauge     —
====================================== ========= ==========================

The ``koord_scorer_coalesce_*`` families observe the coalescing
dispatch engine (ISSUE 5/6, bridge/coalesce.py): how long a Score
request waited in the gather queue before its batch launched, and how
many requests shared each device launch — occupancy near 1 under heavy
concurrency means the engine is not batching (gather window too small,
or the clients are actually serial).  ISSUE 6's pipelined engine adds
the current adaptive gather window (``_window_ms``; moves with the
observed inter-arrival EWMA, clamped) and the cumulative wall time the
device sat idle while work was queued (``_device_idle_ms``; the
double-buffered pipeline exists to hold this near zero — watch its
RATE, a flat line is a saturated pipeline).  ``assign_memo_total``
counts Assign RPCs served from the (snapshot id, CycleConfig) result
memo vs. those that ran a device cycle; ``score_memo_total`` is the
Score-side twin (ISSUE 7 satellite) — requests served as sliced
prefixes of a memoized padded top-k readback vs. those that launched.

The ``koord_scorer_shed_total`` and ``koord_scorer_replica_*`` families
observe the replicated serving tier (ISSUE 8).  ``shed_total`` counts
read RPCs the admission gate refused with RESOURCE_EXHAUSTED (its RATE
under load is the overload signal; zero under the configured depth).
On a follower, ``replica_frames_total`` partitions every replication
frame by outcome (``applied`` extends the chain; ``stale`` is a
duplicate/late frame a reordering transport re-delivered — dropped,
not applied; ``resync`` detected a discontinuity; ``error`` failed
frame decode), ``replica_lag_ms`` is the last applied frame's
commit-to-apply wall delay against the leader's stamp, and
``replica_resyncs_total`` says WHY each one-shot full resync ran — a
growing ``gap`` rate means the transport (or a slow follower's dropped
subscription) is lossy.  On the leader, ``replica_followers`` gauges
live subscriptions.

The jit cache-miss counter is fed by
``analysis.retrace_guard.watch_cache_misses`` — the runtime companion of
the koordlint retrace rules — so a warm Sync/Assign stream that starts
retracing shows up on the scrape, not only in a failed budget test.
"""

from __future__ import annotations

from typing import Mapping, Optional

from koordinator_tpu.koordlet.metrics import DEFAULT_BUCKETS_MS, MetricsRegistry
from koordinator_tpu.replication.admission import band_label

CYCLE_LATENCY = "koord_scorer_cycle_latency_ms"
CYCLE_ROUNDS = "koord_scorer_cycle_rounds"
ROUNDS_TOTAL = "koord_scorer_rounds_total"
CYCLES_TOTAL = "koord_scorer_cycles_total"
CYCLE_ERRORS = "koord_scorer_cycle_errors_total"
SYNC_TOTAL = "koord_scorer_sync_total"
SYNC_TENSORS = "koord_scorer_sync_tensors_total"
JIT_CACHE_MISS = "koord_scorer_jit_cache_miss_total"
SNAPSHOT_GENERATION = "koord_scorer_snapshot_generation"
RESIDENT_EPOCH = "koord_scorer_resident_epoch"
RESIDENT_WARM = "koord_scorer_resident_warm"
DEMOTIONS_TOTAL = "koord_scorer_kernel_demotions_total"
UDS_FRAMES = "koord_scorer_uds_frames_total"
UDS_MALFORMED = "koord_scorer_uds_malformed_total"
UDS_ERRORS = "koord_scorer_uds_errors_total"
COALESCE_QUEUE_DELAY = "koord_scorer_coalesce_queue_delay_ms"
COALESCE_OCCUPANCY = "koord_scorer_coalesce_batch_occupancy"
COALESCE_BATCHES = "koord_scorer_coalesce_batches_total"
COALESCE_REQUESTS = "koord_scorer_coalesce_requests_total"
COALESCE_WINDOW = "koord_scorer_coalesce_window_ms"
COALESCE_DEVICE_IDLE = "koord_scorer_coalesce_device_idle_ms"
ASSIGN_MEMO = "koord_scorer_assign_memo_total"
SCORE_MEMO = "koord_scorer_score_memo_total"
SCORE_INCR = "koord_scorer_score_incr_total"
INCR_COLS = "koord_scorer_incr_cols"
TERM_TOTAL = "koord_scorer_term_total"
SHED_TOTAL = "koord_scorer_shed_total"
SHED_BAND = "koord_scorer_shed_band_total"
DEADLINE_EXPIRED = "koord_scorer_deadline_expired_total"
DEGRADED_TOTAL = "koord_scorer_degraded_total"
BREAKER_STATE = "koord_scorer_breaker_state"
BREAKER_TRANSITIONS = "koord_scorer_breaker_transitions_total"
BREAKER_REJECTED = "koord_scorer_breaker_rejected_total"
REPLICA_ROLE = "koord_scorer_replica_role"
REPLICA_FRAMES = "koord_scorer_replica_frames_total"
REPLICA_LAG = "koord_scorer_replica_lag_ms"
REPLICA_RESYNCS = "koord_scorer_replica_resyncs_total"
REPLICA_FOLLOWERS = "koord_scorer_replica_followers"
JOURNAL_FRAMES = "koord_scorer_journal_frames_total"
JOURNAL_APPEND_US = "koord_scorer_journal_append_us"
JOURNAL_POSITION = "koord_scorer_journal_position"
JOURNAL_BYTES = "koord_scorer_journal_bytes"
JOURNAL_COMPACTION_STAMP = "koord_scorer_journal_compaction_stamp"
FAILOVER_TOTAL = "koord_scorer_failover_total"
RETRY_TOTAL = "koord_scorer_retry_total"
TRACE_CYCLE = "koord_scorer_trace_cycle_ms"
TRACE_SPANS = "koord_scorer_trace_spans_total"
TRACE_EXPORT_DROPPED = "koord_scorer_trace_export_dropped_total"
CANDIDATE_REFRESH = "koord_scorer_candidate_refresh_total"
CANDIDATE_WIDTH = "koord_scorer_candidate_width"
LOCK_WITNESS_EDGES = "koord_scorer_lock_witness_edges_total"
RELAY_POSITION = "koord_scorer_relay_position"
RELAY_FORWARDED = "koord_scorer_relay_forwarded_total"
REPLICA_HOP_LAG = "koord_scorer_replica_hop_lag_ms"
SEND_BATCH_FRAMES = "koord_scorer_repl_send_batch_frames"
REPL_COMPRESS = "koord_scorer_repl_compress_total"
AUTOSCALE_EVENTS = "koord_scorer_autoscale_events_total"
AUTOSCALE_REPLICAS = "koord_scorer_autoscale_replicas"
DEVPROF_COMPILES = "koord_scorer_devprof_compiles_total"
DEVPROF_COMPILE_MS = "koord_scorer_devprof_compile_ms_total"
DEVPROF_DEVICE_US = "koord_scorer_devprof_device_us"
DEVPROF_RETRACE = "koord_scorer_devprof_retrace_total"
PREWARM_SIGNATURES = "koord_scorer_prewarm_signatures_total"
PREWARM_COMPILE_MS = "koord_scorer_prewarm_compile_ms_total"
PREWARM_PENDING = "koord_scorer_prewarm_pending"

# occupancy is a count-of-requests-per-launch, not a latency: its own
# power-of-two buckets (the dispatcher caps batches at 16 by default;
# 32/64 leave headroom for tuned deployments)
_OCCUPANCY_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, float("inf"))

# dirty-column counts per incremental Score launch: power-of-two-ish
# buckets matching the delta scatter's pad buckets (0 = a row-only or
# quota-only delta stream rescored no columns at all)
_INCR_COLS_BUCKETS = (
    1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0, float("inf"),
)

_FAMILIES = (
    (CYCLE_LATENCY, "histogram",
     "end-to-end Assign/Score cycle latency on the bridge, by device "
     "path and wave width"),
    (CYCLE_ROUNDS, "gauge",
     "sequential device rounds of the last wave-batched cycle (~P/wave "
     "certified-prefix rounds vs P per-pod steps)"),
    (ROUNDS_TOTAL, "counter", "cumulative wave-cycle rounds, by path"),
    (CYCLES_TOTAL, "counter", "completed scoring cycles, by device path"),
    (CYCLE_ERRORS, "counter", "cycles that raised, by pipeline stage"),
    (SYNC_TOTAL, "counter",
     "Sync frames by how their tensors rode the wire (delta/full/mixed)"),
    (SYNC_TENSORS, "counter", "synced tensors by encoding (delta/full)"),
    (JIT_CACHE_MISS, "counter",
     "jit cache misses observed process-wide (trace) and those that "
     "reached XLA (compile); a warm stream must not grow this"),
    (SNAPSHOT_GENERATION, "gauge",
     "generation of the resident snapshot (the <gen> of s<epoch>-<gen>)"),
    (RESIDENT_EPOCH, "gauge",
     "per-boot epoch of the resident snapshot as a label; value is "
     "always 1"),
    (RESIDENT_WARM, "gauge",
     "1 when the last Sync landed on the resident device tensors "
     "(warm), 0 when it dropped residency (cold)"),
    (DEMOTIONS_TOTAL, "counter",
     "Pallas kernel shape-bucket demotions to a fallback path"),
    (UDS_FRAMES, "counter", "raw-UDS request frames served, by method"),
    (UDS_MALFORMED, "counter",
     "malformed raw-UDS frames (oversized, unknown method, truncated "
     "mid-frame), by reason"),
    (UDS_ERRORS, "counter", "raw-UDS requests answered with an error frame"),
    (COALESCE_QUEUE_DELAY, "histogram",
     "time a Score request waited in the coalescer's gather queue "
     "before its batch launched"),
    (COALESCE_OCCUPANCY, "histogram",
     "Score requests sharing one coalesced device launch"),
    (COALESCE_BATCHES, "counter", "coalesced Score launches"),
    (COALESCE_REQUESTS, "counter",
     "Score requests served through the coalescer (requests/batches = "
     "mean occupancy)"),
    (COALESCE_WINDOW, "gauge",
     "current adaptive gather window (EWMA of inter-arrival gaps, "
     "clamped; 0 = launch immediately)"),
    (COALESCE_DEVICE_IDLE, "gauge",
     "cumulative wall time the device sat idle with work queued; the "
     "pipelined dispatcher holds the rate near zero"),
    (ASSIGN_MEMO, "counter",
     "Assign RPCs served from the (snapshot, config) result memo (hit) "
     "vs. ran a device cycle (miss)"),
    (SCORE_MEMO, "counter",
     "Score requests served as sliced prefixes of the memoized "
     "(snapshot, config, k-bucket) top-k readback (hit) vs. launched "
     "a device batch (miss)"),
    (SCORE_INCR, "counter",
     "Score launches by engine outcome: incr rescored only the dirty "
     "columns/rows of the resident score tensor, full had no resident "
     "tensor to advance (cold/first score), fallback had one but full-"
     "rescored (dirty ratio past --score-incr-max-ratio, or an "
     "incremental-launch failure)"),
    (INCR_COLS, "histogram",
     "dirty node columns recomputed per incremental Score launch "
     "(O(P x d) of the O(P x N) a full rescore pays)"),
    (TERM_TOTAL, "counter",
     "fused scoring-term activations by term name, one per device "
     "Score launch with the term enabled (ISSUE 15: heterogeneity/"
     "sensitivity/packing ride the ONE pods x nodes launch)"),
    (SHED_TOTAL, "counter",
     "read RPCs the admission gate refused with RESOURCE_EXHAUSTED "
     "(queue depth at the band's rung of --max-inflight), by method; "
     "in-flight work completes untouched"),
    (SHED_BAND, "counter",
     "admission sheds by priority band (ISSUE 13 band ladder: free "
     "sheds at half the configured depth, batch/mid in between, prod "
     "and unbanded legacy clients only at the full depth); under an "
     "overload storm the free/batch rates climb while prod stays ~0"),
    (DEADLINE_EXPIRED, "counter",
     "requests whose propagated deadline budget expired before any "
     "device work ran, by stage: queue = already expired at RPC "
     "entry, gather = evicted by the batch leader at gather time; "
     "either way the request never occupied a launch slot"),
    (DEGRADED_TOTAL, "counter",
     "replies served STALE from the brownout cache while the circuit "
     "breaker was open (explicit degraded flag on the reply, "
     "staleness bounded by --brownout-max-lag generations), by rpc"),
    (BREAKER_STATE, "gauge",
     "circuit breaker state as a label (closed|half-open|open); the "
     "current state's series is 1, the others 0"),
    (BREAKER_TRANSITIONS, "counter",
     "circuit breaker state transitions, by destination state (to= "
     "open is a trip or a failed half-open probe; to=closed is a "
     "successful probe recovering the device path)"),
    (BREAKER_REJECTED, "counter",
     "requests the open breaker failed fast with UNAVAILABLE + "
     "retry-after instead of queueing behind a failing device "
     "(Assign always; Score when the brownout cache could not serve "
     "it within the staleness bound), by method"),
    (REPLICA_ROLE, "gauge",
     "replication role of this daemon as a label (leader|follower); "
     "value is always 1"),
    (REPLICA_FRAMES, "counter",
     "replication frames by outcome on a follower: applied extends "
     "the s<epoch>-<gen> chain, stale was a duplicate/late redelivery "
     "(dropped), resync detected a discontinuity, error failed decode"),
    (REPLICA_LAG, "gauge",
     "commit-to-apply wall delay of the last applied replication "
     "frame against the leader's stamp"),
    (REPLICA_RESYNCS, "counter",
     "one-shot full resyncs a follower performed, by trigger "
     "(gap|epoch|decode|apply|connect)"),
    (REPLICA_FOLLOWERS, "gauge",
     "live replication subscriptions on the leader"),
    (JOURNAL_FRAMES, "counter",
     "durable frame-journal operations (ISSUE 11): append wrote one "
     "committed frame, replay applied one on boot, compact rewrote the "
     "file as one full-state frame, truncate cut a torn/corrupt tail"),
    (JOURNAL_APPEND_US, "histogram",
     "wall time one journal append added to the Sync commit path "
     "(encode + write + flush); the durability tax on the one writer"),
    (JOURNAL_POSITION, "gauge",
     "generation of the last journaled frame (the <gen> the journal "
     "can recover to); must track koord_scorer_snapshot_generation"),
    (JOURNAL_BYTES, "gauge",
     "journal file size; sawtooths with --journal-compact-every"),
    (JOURNAL_COMPACTION_STAMP, "gauge",
     "wall clock (us since the unix epoch) of the last journal "
     "compaction; a stale stamp under write load means compaction "
     "is failing and the journal grows without bound"),
    (FAILOVER_TOTAL, "counter",
     "crash-tolerance transitions: promoted = this follower became "
     "the leader (SIGUSR2/admin RPC), warm_restart = this daemon "
     "resumed its s<epoch>-<gen> chain from the journal on boot"),
    (RETRY_TOTAL, "counter",
     "backed-off retries through the shared replication.retry policy, "
     "by operation (subscribe = follower redial; resume = a "
     "subscription served from the journal instead of a full frame)"),
    (TRACE_CYCLE, "histogram",
     "client-observed latency of one trace-replay step (ISSUE 12, "
     "harness/trace.py), by priority band (koord-prod|mid|batch|free; "
     "infra = node/quota events) and rpc (sync|score|assign|cycle = "
     "the whole step); the obs/slo.py SLO gate judges its per-band "
     "p99s in bench.py --config trace"),
    (TRACE_SPANS, "counter",
     "distributed-trace spans completed and handed to the exporter "
     "(ISSUE 14), by span kind: client = shim op/attempt spans, "
     "server = RPC spans, internal = launch/readback spans, consumer "
     "= replica-apply/journal-replay spans; zero while no client "
     "stamps a trace_id"),
    (TRACE_EXPORT_DROPPED, "counter",
     "spans the export sink dropped instead of writing (ISSUE 14), by "
     "reason (closed|rate|bytes|encode|io); any nonzero rate means "
     "assembled traces are INCOMPLETE — widen the bound or stop the "
     "span storm before trusting a tree"),
    (CANDIDATE_REFRESH, "counter",
     "sparse candidate-list builds/refreshes (ISSUE 16), by reason: "
     "cold = no resident lists (full blocked build), dirty = lazy "
     "merge-refresh of the entries a warm commit invalidated, stale = "
     "forced full rebuild after --candidate-max-stale merges; a warm "
     "delta stream should run mostly dirty with a bounded stale rate — "
     "a climbing cold rate means commits keep losing row attribution"),
    (CANDIDATE_WIDTH, "gauge",
     "configured sparse candidate width C (the [P, C] serving shape); "
     "0 while the dense engines serve"),
    (LOCK_WITNESS_EDGES, "counter",
     "distinct lock-acquisition edges the runtime witness "
     "(KOORD_LOCK_WITNESS=1, obs/lockwitness.py) recorded, by result: "
     "observed = consistent with the derived order in "
     "docs/LOCKORDER.md, inversion = closed a cycle against it (a "
     "schedulable deadlock; the witness also raises); 0 when witness "
     "mode is off"),
    (RELAY_POSITION, "gauge",
     "this daemon's depth in the relay tree (ISSUE 18): 0 = the root "
     "leader, 1 = a direct follower, 2 = behind one relay, ...; a "
     "relay both applies its parent's stream and re-publishes it on "
     "its own .repl socket"),
    (RELAY_FORWARDED, "counter",
     "replication frames this relay re-published verbatim to its own "
     "subscribers (applied delta frames forwarded byte-identically; "
     "full frames are served from the relay's OWN state, never "
     "forwarded)"),
    (REPLICA_HOP_LAG, "gauge",
     "commit-to-apply wall delay of the last applied frame, labeled "
     "by this replica's hop distance from the root leader — a deep "
     "chain's lag amplification shows per level, not just in the "
     "aggregate koord_scorer_replica_lag_ms"),
    (SEND_BATCH_FRAMES, "histogram",
     "queued replication frames coalesced into one sender wakeup/"
     "syscall on the publisher (frames per wakeup; the batch is "
     "bounded by --repl-batch-bytes, not a frame count)"),
    (REPL_COMPRESS, "counter",
     "full replication frames that crossed the wire zlib-compressed "
     "(KIND_FULL_Z), by op: encode = the publisher compressed one for "
     "a z-capable subscriber, decode = a subscriber inflated one; "
     "journal bytes stay uncompressed"),
    (AUTOSCALE_EVENTS, "counter",
     "elastic replica-tier scaling decisions the autoscaler acted on "
     "(ISSUE 18), by action (scale_up|scale_down); hysteresis and the "
     "cooldown window keep this a step function, not a flap"),
    (AUTOSCALE_REPLICAS, "gauge",
     "the autoscaler's current target follower count (what it is "
     "holding the tier at, between --autoscale-min and "
     "--autoscale-max)"),
    (DEVPROF_COMPILES, "counter",
     "XLA programs the launch ledger (obs/devprof.py) captured through "
     "the AOT path, by jit boundary and backend platform — each is one "
     "(boundary, shape signature) compile-ledger row"),
    (DEVPROF_COMPILE_MS, "counter",
     "cumulative XLA compile wall-time the ledger attributed, by "
     "boundary and backend; divide by devprof_compiles_total for the "
     "mean compile cost of that boundary's programs"),
    (DEVPROF_DEVICE_US, "histogram",
     "sampled per-launch device execution time (dispatch to "
     "block_until_ready on the launch's own outputs), by boundary; "
     "sampling is 1-in-N (--devprof-sample), so multiply counts by N "
     "to estimate launch totals"),
    (DEVPROF_RETRACE, "counter",
     "attributed retraces: a registered boundary minted a NEW program "
     "for a shape signature after its first — the per-boundary "
     "breakdown of koord_scorer_jit_cache_miss_total the ledger names "
     "in /healthz and the report CLI"),
    (PREWARM_SIGNATURES, "counter",
     "signatures the boot-time AOT prewarm thread (ISSUE 20, "
     "obs/prewarm.py) processed from <state-dir>/prewarm.pkl, by "
     "result: compiled replayed through lower().compile(), skipped "
     "had no replay spec or no resolvable boundary, failed raised "
     "(code/backend drift since capture — the live path still "
     "compiles inline)"),
    (PREWARM_COMPILE_MS, "counter",
     "cumulative compile wall-time the prewarm thread spent replaying "
     "persisted signatures; with a warm persistent XLA cache this "
     "collapses to trace time only"),
    (PREWARM_PENDING, "gauge",
     "replayable signatures the prewarm thread has not reached yet "
     "(0 = prewarm done; a request arriving for a pending signature "
     "just compiles inline, exactly as an unprewarmed boot)"),
)

# journal appends are MICROsecond-scale (a header pack + one buffered
# write); the default ms latency buckets would collapse them into the
# first bucket
_JOURNAL_APPEND_BUCKETS = (
    10.0, 50.0, 100.0, 500.0, 1_000.0, 5_000.0, 20_000.0, 100_000.0,
    float("inf"),
)

# sampled device launches span ~100 us (a warm delta scatter) to
# multiple seconds (a cold-start dense cycle on CPU): wider-than-journal
# microsecond buckets
_DEVPROF_US_BUCKETS = (
    100.0, 1_000.0, 5_000.0, 20_000.0, 100_000.0, 500_000.0,
    2_000_000.0, 10_000_000.0, float("inf"),
)

# per-family bucket overrides (histograms default to DEFAULT_BUCKETS_MS)
_BUCKET_OVERRIDES = {
    COALESCE_OCCUPANCY: _OCCUPANCY_BUCKETS,
    INCR_COLS: _INCR_COLS_BUCKETS,
    JOURNAL_APPEND_US: _JOURNAL_APPEND_BUCKETS,
    # frames-per-wakeup is a count, like coalesce occupancy
    SEND_BATCH_FRAMES: _OCCUPANCY_BUCKETS,
    DEVPROF_DEVICE_US: _DEVPROF_US_BUCKETS,
}


class ScorerMetrics:
    """Typed facade over the registry for the scorer families.  All
    methods take host-side Python scalars only — values must be
    materialized BEFORE they reach here (never call from jitted code;
    koordlint's host-sync rule enforces that statically)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()
        for name, kind, help_text in _FAMILIES:
            self.registry.register(
                name, kind, help_text,
                buckets=(
                    _BUCKET_OVERRIDES.get(name, DEFAULT_BUCKETS_MS)
                    if kind == "histogram" else None
                ),
            )

    # -- cycle completion --
    def observe_cycle(
        self,
        latency_ms: float,
        path: str,
        wave: int,
        rounds: Optional[int] = None,
    ) -> None:
        labels = {"path": path or "unknown", "wave": str(int(wave))}
        self.registry.histogram_observe(CYCLE_LATENCY, latency_ms, labels)
        self.registry.counter_add(
            CYCLES_TOTAL, 1, {"path": path or "unknown"}
        )
        if rounds is not None:
            self.registry.gauge_set(
                CYCLE_ROUNDS, rounds, {"path": path or "unknown"}
            )
            self.registry.counter_add(
                ROUNDS_TOTAL, rounds, {"path": path or "unknown"}
            )

    def count_cycle_error(self, stage: str) -> None:
        self.registry.counter_add(CYCLE_ERRORS, 1, {"stage": stage})

    # -- sync --
    def record_sync(self, info: Mapping[str, object]) -> None:
        """``info`` is bridge/state.py apply_sync's summary dict."""
        delta = int(info.get("delta_tensors", 0))
        full = int(info.get("full_tensors", 0))
        if delta and full:
            kind = "mixed"
        elif delta:
            kind = "delta"
        elif full:
            kind = "full"
        else:
            # scalar-columns-only frame (freshness/priority churn): no
            # tensors rode the wire at all — don't claim a delta
            kind = "scalar"
        self.registry.counter_add(SYNC_TOTAL, 1, {"kind": kind})
        if delta:
            self.registry.counter_add(SYNC_TENSORS, delta, {"kind": "delta"})
        if full:
            self.registry.counter_add(SYNC_TENSORS, full, {"kind": "full"})
        self.registry.gauge_set(
            RESIDENT_WARM, 1 if info.get("path") == "warm" else 0
        )

    def set_snapshot(self, epoch: str, generation: int) -> None:
        self.registry.gauge_set(SNAPSHOT_GENERATION, generation)
        self.registry.gauge_set(RESIDENT_EPOCH, 1, {"epoch": epoch})

    # -- feeds --
    def count_jit_miss(self, kind: str) -> None:
        self.registry.counter_add(JIT_CACHE_MISS, 1, {"kind": kind})

    def count_demotion(self) -> None:
        self.registry.counter_add(DEMOTIONS_TOTAL, 1)

    def count_uds_frame(self, method: str) -> None:
        self.registry.counter_add(UDS_FRAMES, 1, {"method": method})

    def count_uds_malformed(self, reason: str) -> None:
        self.registry.counter_add(UDS_MALFORMED, 1, {"reason": reason})

    def count_uds_error(self) -> None:
        self.registry.counter_add(UDS_ERRORS, 1)

    def record_coalesce(self, batch_size: int, queue_delays_ms) -> None:
        """One coalesced launch: how many requests shared it and how
        long each waited in the gather queue.  Called by the batch
        leader AFTER the stacked readback (never under the device
        lock's critical path a follower is waiting on)."""
        self.registry.counter_add(COALESCE_BATCHES, 1)
        self.registry.counter_add(COALESCE_REQUESTS, int(batch_size))
        self.registry.histogram_observe(COALESCE_OCCUPANCY, float(batch_size))
        for delay_ms in queue_delays_ms:
            self.registry.histogram_observe(
                COALESCE_QUEUE_DELAY, float(delay_ms)
            )

    def set_coalesce_window(self, window_ms: float) -> None:
        self.registry.gauge_set(COALESCE_WINDOW, float(window_ms))

    def set_device_idle(self, idle_ms: float) -> None:
        self.registry.gauge_set(COALESCE_DEVICE_IDLE, float(idle_ms))

    def count_assign_memo(self, result: str) -> None:
        self.registry.counter_add(ASSIGN_MEMO, 1, {"result": result})

    def count_score_memo(self, result: str, n: int = 1) -> None:
        self.registry.counter_add(SCORE_MEMO, int(n), {"result": result})

    # -- incremental score engine (ISSUE 9) --
    def count_score_incr(self, result: str) -> None:
        """One Score LAUNCH's engine outcome (incr|full|fallback) —
        per launch, not per coalesced request: the engine decision is
        batch-scoped."""
        self.registry.counter_add(SCORE_INCR, 1, {"result": result})

    def observe_incr_cols(self, cols: int) -> None:
        self.registry.histogram_observe(INCR_COLS, float(cols))

    def count_term(self, term: str, n: int = 1) -> None:
        """One fused scoring term's activation on a device Score launch
        (ISSUE 15) — per launch per enabled term, so the series ratio
        term_total / score launches proves the terms rode the ONE
        launch instead of extra per-plugin passes."""
        self.registry.counter_add(TERM_TOTAL, n, {"term": term})

    # -- sparse candidate engine (ISSUE 16) --
    def count_candidate_refresh(self, reason: str, n: int = 1) -> None:
        """One sparse candidate-list build/refresh, by reason
        (cold|dirty|stale) — per launch that rebuilt or re-merged, not
        per coalesced request; a launch that reused clean resident
        lists counts nothing."""
        self.registry.counter_add(
            CANDIDATE_REFRESH, int(n), {"reason": reason}
        )

    def set_candidate_width(self, width: int) -> None:
        self.registry.gauge_set(CANDIDATE_WIDTH, int(width))

    def count_lock_witness_edge(self, result: str) -> None:
        """One distinct witness edge; ``result`` is ``observed`` or
        ``inversion`` (obs/lockwitness.py)."""
        self.registry.counter_add(LOCK_WITNESS_EDGES, 1, {"result": result})

    # -- replicated serving tier (ISSUE 8) --
    def count_shed(self, method: str, band: str = "") -> None:
        self.registry.counter_add(SHED_TOTAL, 1, {"method": method})
        self.registry.counter_add(
            SHED_BAND, 1, {"band": band_label(band)}
        )

    # -- degradation ladder (ISSUE 13) --
    def count_deadline_expired(self, stage: str, n: int = 1) -> None:
        self.registry.counter_add(
            DEADLINE_EXPIRED, int(n), {"stage": stage}
        )

    def count_degraded(self, rpc: str, n: int = 1) -> None:
        self.registry.counter_add(DEGRADED_TOTAL, int(n), {"rpc": rpc})

    def set_breaker_state(self, state: str) -> None:
        """Flip the state gauge: the current state's series reads 1,
        every other state's 0 (so a scrape always sees exactly one)."""
        for s in ("closed", "half-open", "open"):
            self.registry.gauge_set(
                BREAKER_STATE, 1 if s == state else 0, {"state": s}
            )

    def count_breaker_transition(self, to: str) -> None:
        self.registry.counter_add(BREAKER_TRANSITIONS, 1, {"to": to})

    def count_breaker_rejected(self, method: str) -> None:
        self.registry.counter_add(
            BREAKER_REJECTED, 1, {"method": method}
        )

    def set_replica_role(self, role: str) -> None:
        self.registry.gauge_set(REPLICA_ROLE, 1, {"role": role})

    def count_replica_frame(self, result: str) -> None:
        self.registry.counter_add(REPLICA_FRAMES, 1, {"result": result})

    def set_replica_lag(self, lag_ms: float) -> None:
        self.registry.gauge_set(REPLICA_LAG, float(lag_ms))

    def count_replica_resync(self, reason: str) -> None:
        self.registry.counter_add(REPLICA_RESYNCS, 1, {"reason": reason})

    def set_replica_followers(self, n: int) -> None:
        self.registry.gauge_set(REPLICA_FOLLOWERS, int(n))

    # -- relay tree + elastic tier (ISSUE 18) --
    def set_relay_position(self, depth: int) -> None:
        self.registry.gauge_set(RELAY_POSITION, int(depth))

    def count_relay_forwarded(self, n: int = 1) -> None:
        self.registry.counter_add(RELAY_FORWARDED, int(n))

    def set_replica_hop_lag(self, hop: int, lag_ms: float) -> None:
        self.registry.gauge_set(
            REPLICA_HOP_LAG, float(lag_ms), {"hop": str(int(hop))}
        )

    def observe_send_batch(self, n_frames: int) -> None:
        """Frames one publisher sender wakeup coalesced into a single
        syscall (1 = no coalescing happened on that wakeup)."""
        self.registry.histogram_observe(SEND_BATCH_FRAMES, float(n_frames))

    def count_replica_compress(self, op: str) -> None:
        self.registry.counter_add(REPL_COMPRESS, 1, {"op": op})

    def count_autoscale_event(self, action: str) -> None:
        self.registry.counter_add(AUTOSCALE_EVENTS, 1, {"action": action})

    def set_autoscale_replicas(self, n: int) -> None:
        self.registry.gauge_set(AUTOSCALE_REPLICAS, int(n))

    # -- crash tolerance: journal / failover / retry (ISSUE 11) --
    def count_journal(self, op: str, n: int = 1) -> None:
        self.registry.counter_add(JOURNAL_FRAMES, int(n), {"op": op})

    def observe_journal_append_us(self, us: float) -> None:
        self.registry.histogram_observe(JOURNAL_APPEND_US, float(us))

    def set_journal_position(self, generation: int) -> None:
        self.registry.gauge_set(JOURNAL_POSITION, int(generation))

    def set_journal_bytes(self, n: int) -> None:
        self.registry.gauge_set(JOURNAL_BYTES, int(n))

    def set_journal_compaction_stamp(self, stamp_us: int) -> None:
        self.registry.gauge_set(JOURNAL_COMPACTION_STAMP, int(stamp_us))

    def count_failover(self, event: str) -> None:
        self.registry.counter_add(FAILOVER_TOTAL, 1, {"event": event})

    def count_retry(self, op: str) -> None:
        self.registry.counter_add(RETRY_TOTAL, 1, {"op": op})

    # -- distributed tracing (ISSUE 14) --
    def count_trace_span(self, kind: str) -> None:
        self.registry.counter_add(TRACE_SPANS, 1, {"kind": kind})

    def count_trace_export_dropped(self, reason: str) -> None:
        self.registry.counter_add(
            TRACE_EXPORT_DROPPED, 1, {"reason": reason}
        )

    # -- device-time truth (ISSUE 19): fed by obs/devprof.py through
    # its weakref metrics sink; all values arrive as host scalars the
    # ledger already materialized --
    def devprof_compile(
        self, boundary: str, backend: str, compile_ms: float
    ) -> None:
        labels = {"boundary": boundary, "backend": backend or "unknown"}
        self.registry.counter_add(DEVPROF_COMPILES, 1, labels)
        self.registry.counter_add(
            DEVPROF_COMPILE_MS, float(compile_ms), labels
        )

    def devprof_device_us(self, boundary: str, us: float) -> None:
        self.registry.histogram_observe(
            DEVPROF_DEVICE_US, float(us), {"boundary": boundary}
        )

    def devprof_retrace(self, boundary: str) -> None:
        self.registry.counter_add(DEVPROF_RETRACE, 1, {"boundary": boundary})

    # -- AOT signature prewarm (ISSUE 20) --
    def count_prewarm(self, result: str) -> None:
        self.registry.counter_add(
            PREWARM_SIGNATURES, 1, {"result": result}
        )

    def add_prewarm_compile_ms(self, ms: float) -> None:
        self.registry.counter_add(PREWARM_COMPILE_MS, float(ms))

    def set_prewarm_pending(self, pending: int) -> None:
        self.registry.gauge_set(PREWARM_PENDING, int(pending))

    # -- trace-driven replay (ISSUE 12) --
    def observe_trace_cycle(self, band: str, rpc: str, ms: float) -> None:
        """One replay step's client-observed latency: ``rpc`` is the
        individual RPC (sync/score/assign) or ``cycle`` for the whole
        step, ``band`` the priority band of the workload the step
        schedules (``infra`` for node/quota events)."""
        self.registry.histogram_observe(
            TRACE_CYCLE, float(ms), {"band": band or "infra", "rpc": rpc}
        )
