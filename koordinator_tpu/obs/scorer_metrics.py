"""Scorer metric families over the koordlet Prometheus-text registry.

One place declares every family the bridge daemon exports on /metrics
(koordlet/metrics.py renders the exposition text; the registration is
idempotent, so a restarted daemon re-registering is a no-op).  Families:

====================================== ========= ==========================
family                                 kind      labels
====================================== ========= ==========================
koord_scorer_cycle_latency_ms          histogram path, wave
koord_scorer_cycle_rounds              gauge     path
koord_scorer_rounds_total              counter   path
koord_scorer_cycles_total              counter   path
koord_scorer_cycle_errors_total        counter   stage
koord_scorer_sync_total                counter   kind (delta|full|mixed|scalar)
koord_scorer_sync_tensors_total        counter   kind (delta|full)
koord_scorer_jit_cache_miss_total      counter   kind (trace|compile)
koord_scorer_snapshot_generation       gauge     —
koord_scorer_resident_epoch            gauge     epoch (value always 1)
koord_scorer_resident_warm             gauge     — (last Sync: 1 warm/0 cold)
koord_scorer_kernel_demotions_total    counter   —
koord_scorer_uds_frames_total          counter   method
koord_scorer_uds_malformed_total       counter   reason
koord_scorer_uds_errors_total          counter   —
====================================== ========= ==========================

The jit cache-miss counter is fed by
``analysis.retrace_guard.watch_cache_misses`` — the runtime companion of
the koordlint retrace rules — so a warm Sync/Assign stream that starts
retracing shows up on the scrape, not only in a failed budget test.
"""

from __future__ import annotations

from typing import Mapping, Optional

from koordinator_tpu.koordlet.metrics import DEFAULT_BUCKETS_MS, MetricsRegistry

CYCLE_LATENCY = "koord_scorer_cycle_latency_ms"
CYCLE_ROUNDS = "koord_scorer_cycle_rounds"
ROUNDS_TOTAL = "koord_scorer_rounds_total"
CYCLES_TOTAL = "koord_scorer_cycles_total"
CYCLE_ERRORS = "koord_scorer_cycle_errors_total"
SYNC_TOTAL = "koord_scorer_sync_total"
SYNC_TENSORS = "koord_scorer_sync_tensors_total"
JIT_CACHE_MISS = "koord_scorer_jit_cache_miss_total"
SNAPSHOT_GENERATION = "koord_scorer_snapshot_generation"
RESIDENT_EPOCH = "koord_scorer_resident_epoch"
RESIDENT_WARM = "koord_scorer_resident_warm"
DEMOTIONS_TOTAL = "koord_scorer_kernel_demotions_total"
UDS_FRAMES = "koord_scorer_uds_frames_total"
UDS_MALFORMED = "koord_scorer_uds_malformed_total"
UDS_ERRORS = "koord_scorer_uds_errors_total"

_FAMILIES = (
    (CYCLE_LATENCY, "histogram",
     "end-to-end Assign/Score cycle latency on the bridge, by device "
     "path and wave width"),
    (CYCLE_ROUNDS, "gauge",
     "sequential device rounds of the last wave-batched cycle (~P/wave "
     "certified-prefix rounds vs P per-pod steps)"),
    (ROUNDS_TOTAL, "counter", "cumulative wave-cycle rounds, by path"),
    (CYCLES_TOTAL, "counter", "completed scoring cycles, by device path"),
    (CYCLE_ERRORS, "counter", "cycles that raised, by pipeline stage"),
    (SYNC_TOTAL, "counter",
     "Sync frames by how their tensors rode the wire (delta/full/mixed)"),
    (SYNC_TENSORS, "counter", "synced tensors by encoding (delta/full)"),
    (JIT_CACHE_MISS, "counter",
     "jit cache misses observed process-wide (trace) and those that "
     "reached XLA (compile); a warm stream must not grow this"),
    (SNAPSHOT_GENERATION, "gauge",
     "generation of the resident snapshot (the <gen> of s<epoch>-<gen>)"),
    (RESIDENT_EPOCH, "gauge",
     "per-boot epoch of the resident snapshot as a label; value is "
     "always 1"),
    (RESIDENT_WARM, "gauge",
     "1 when the last Sync landed on the resident device tensors "
     "(warm), 0 when it dropped residency (cold)"),
    (DEMOTIONS_TOTAL, "counter",
     "Pallas kernel shape-bucket demotions to a fallback path"),
    (UDS_FRAMES, "counter", "raw-UDS request frames served, by method"),
    (UDS_MALFORMED, "counter",
     "malformed raw-UDS frames (oversized, unknown method, truncated "
     "mid-frame), by reason"),
    (UDS_ERRORS, "counter", "raw-UDS requests answered with an error frame"),
)


class ScorerMetrics:
    """Typed facade over the registry for the scorer families.  All
    methods take host-side Python scalars only — values must be
    materialized BEFORE they reach here (never call from jitted code;
    koordlint's host-sync rule enforces that statically)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()
        for name, kind, help_text in _FAMILIES:
            self.registry.register(
                name, kind, help_text,
                buckets=DEFAULT_BUCKETS_MS if kind == "histogram" else None,
            )

    # -- cycle completion --
    def observe_cycle(
        self,
        latency_ms: float,
        path: str,
        wave: int,
        rounds: Optional[int] = None,
    ) -> None:
        labels = {"path": path or "unknown", "wave": str(int(wave))}
        self.registry.histogram_observe(CYCLE_LATENCY, latency_ms, labels)
        self.registry.counter_add(
            CYCLES_TOTAL, 1, {"path": path or "unknown"}
        )
        if rounds is not None:
            self.registry.gauge_set(
                CYCLE_ROUNDS, rounds, {"path": path or "unknown"}
            )
            self.registry.counter_add(
                ROUNDS_TOTAL, rounds, {"path": path or "unknown"}
            )

    def count_cycle_error(self, stage: str) -> None:
        self.registry.counter_add(CYCLE_ERRORS, 1, {"stage": stage})

    # -- sync --
    def record_sync(self, info: Mapping[str, object]) -> None:
        """``info`` is bridge/state.py apply_sync's summary dict."""
        delta = int(info.get("delta_tensors", 0))
        full = int(info.get("full_tensors", 0))
        if delta and full:
            kind = "mixed"
        elif delta:
            kind = "delta"
        elif full:
            kind = "full"
        else:
            # scalar-columns-only frame (freshness/priority churn): no
            # tensors rode the wire at all — don't claim a delta
            kind = "scalar"
        self.registry.counter_add(SYNC_TOTAL, 1, {"kind": kind})
        if delta:
            self.registry.counter_add(SYNC_TENSORS, delta, {"kind": "delta"})
        if full:
            self.registry.counter_add(SYNC_TENSORS, full, {"kind": "full"})
        self.registry.gauge_set(
            RESIDENT_WARM, 1 if info.get("path") == "warm" else 0
        )

    def set_snapshot(self, epoch: str, generation: int) -> None:
        self.registry.gauge_set(SNAPSHOT_GENERATION, generation)
        self.registry.gauge_set(RESIDENT_EPOCH, 1, {"epoch": epoch})

    # -- feeds --
    def count_jit_miss(self, kind: str) -> None:
        self.registry.counter_add(JIT_CACHE_MISS, 1, {"kind": kind})

    def count_demotion(self) -> None:
        self.registry.counter_add(DEMOTIONS_TOTAL, 1)

    def count_uds_frame(self, method: str) -> None:
        self.registry.counter_add(UDS_FRAMES, 1, {"method": method})

    def count_uds_malformed(self, reason: str) -> None:
        self.registry.counter_add(UDS_MALFORMED, 1, {"reason": reason})

    def count_uds_error(self) -> None:
        self.registry.counter_add(UDS_ERRORS, 1)
