"""Runtime lock witness (ISSUE 17): FreeBSD WITNESS / Go lockrank for
the serving tier.

The static half (``analysis/lockgraph.py``) derives the repo's lock
partial order from the AST; this module validates it against REAL
interleavings.  Under ``KOORD_LOCK_WITNESS=1`` (or an explicit
:func:`install`), the ``witness_lock``/``witness_rlock``/
``witness_condition`` factories — which every threaded-tier creation
site routes through — return instrumented wrappers instead of plain
``threading`` primitives.  Each wrapper

* tracks the per-thread HELD-SET (a ``threading.local`` stack, so the
  bookkeeping itself takes no lock on the hot path);
* records every first-seen acquisition edge ``held -> acquired``;
* raises :class:`LockOrderInversion` the moment a new edge closes a
  cycle against the statically derived order *or* against the edges
  already observed this run — the two-sided check: a static A->B plus
  an observed B->A is a deadlock two threads can schedule, whether or
  not lint saw the B->A path.

``Condition.wait`` is modelled faithfully: the identity leaves the
held-set for the duration of the wait (other threads acquire freely)
and the re-acquire re-records edges against whatever the thread still
holds — exactly the release/re-acquire semantics the static pass
models.

Same-identity nesting (two ``_Subscriber._cond`` instances, an RLock
re-entry) is "dup ok", matching the static pass: identities collapse
instances, so a self-edge carries no order information.

With the env var unset and no install, the factories return plain
``threading`` objects — zero overhead, byte-identical behavior.  The
factory NAME STRINGS are drift-checked by ``lockorder-doc-drift``
against the derived identities, so the witness and the graph can never
disagree about what a lock is called.

Distinct observed edges feed the
``koord_scorer_lock_witness_edges_total`` counter (label ``result``:
``observed`` | ``inversion``) once a registry is attached — the
servicer attaches its own when witness mode is on.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, List, Optional, Set, Tuple

ENV = "KOORD_LOCK_WITNESS"

_TRUTHY = ("1", "true", "yes", "on")

_INSTALL_LOCK = threading.Lock()
_STATE: Optional["_WitnessState"] = None


class LockOrderInversion(RuntimeError):
    """A thread acquired locks in an order that closes a cycle against
    the derived partial order — a schedulable deadlock."""


class _Held:
    __slots__ = ("name", "count")

    def __init__(self, name: str):
        self.name = name
        self.count = 1


class _WitnessState:
    def __init__(self, order_edges: Iterable[Tuple[str, str]]):
        self.static_order: Dict[str, Set[str]] = {}
        for a, b in order_edges:
            self.static_order.setdefault(a, set()).add(b)
        # guards observed/inversions/metrics (NOT the held-set, which is
        # thread-local); deliberately a plain lock outside its own
        # bookkeeping — the witness must not witness itself
        self._lock = threading.Lock()
        self.observed: Dict[Tuple[str, str], int] = {}
        self.inversions: List[dict] = []
        # edges flagged as inversions: reported (once) but EXCLUDED
        # from the order _reaches_locked walks — admitting them would
        # poison the legal direction into "inverting" right back
        self._inverted: Set[Tuple[str, str]] = set()
        self.metrics = None
        self._tls = threading.local()

    # -- held-set -----------------------------------------------------
    def held(self) -> List[_Held]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    # -- order check --------------------------------------------------
    def _reaches_locked(self, src: str, dst: str) -> bool:
        """Path src => dst over static order + observed edges; caller
        holds ``self._lock`` (the ``observed`` iteration needs it)."""
        seen: Set[str] = set()
        frontier = [src]
        while frontier:
            node = frontier.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            frontier.extend(self.static_order.get(node, ()))
            frontier.extend(
                b for (a, b) in self.observed
                if a == node and (a, b) not in self._inverted
            )
        return False

    def note_acquire(self, name: str) -> None:
        stack = self.held()
        for entry in stack:
            if entry.name == name:
                entry.count += 1  # reentrant / same-identity: dup ok
                return
        stack.append(_Held(name))
        if len(stack) > 1:
            try:
                self._record_edges([e.name for e in stack[:-1]], name)
            except LockOrderInversion:
                stack.pop()  # wrapper releases the inner lock and re-raises
                raise

    def note_release(self, name: str) -> None:
        stack = self.held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].name == name:
                stack[i].count -= 1
                if stack[i].count == 0:
                    del stack[i]
                return

    def note_wait_release(self, name: str) -> int:
        """Condition.wait: the identity fully leaves the held-set (the
        stdlib releases every recursion level); returns the saved
        depth for the re-acquire."""
        stack = self.held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].name == name:
                count = stack[i].count
                del stack[i]
                return count
        return 0

    def note_wait_reacquire(self, name: str, count: int) -> None:
        stack = self.held()
        entry = _Held(name)
        entry.count = max(1, count)
        stack.append(entry)
        if len(stack) > 1:
            try:
                self._record_edges([e.name for e in stack[:-1]], name)
            except LockOrderInversion:
                stack.pop()
                raise

    def _record_edges(self, held_names: List[str], dst: str) -> None:
        fresh_inversion = None
        with self._lock:
            for src in held_names:
                key = (src, dst)
                if key in self.observed:
                    self.observed[key] += 1
                    continue
                # first sighting: the two-sided check BEFORE admitting
                # the edge — a path dst => src makes (src, dst) close a
                # cycle
                inverted = self._reaches_locked(dst, src)
                self.observed[key] = 1
                if inverted:
                    self._inverted.add(key)
                    detail = {
                        "edge": key,
                        "held": list(held_names),
                        "thread": threading.current_thread().name,
                    }
                    self.inversions.append(detail)
                    if self.metrics is not None:
                        self.metrics.count_lock_witness_edge("inversion")
                    fresh_inversion = detail
                elif self.metrics is not None:
                    self.metrics.count_lock_witness_edge("observed")
        if fresh_inversion is not None:
            raise LockOrderInversion(
                f"lock-order inversion: thread "
                f"{fresh_inversion['thread']!r} acquired {dst!r} while "
                f"holding {fresh_inversion['held']} but the derived "
                f"order (static graph + observed edges) already orders "
                f"{dst!r} before {fresh_inversion['edge'][0]!r} — two "
                "threads can deadlock on this; see docs/LOCKORDER.md"
            )

    def attach_metrics(self, metrics) -> None:
        """Late attach replays the distinct edges recorded so far, so
        the counter is exact regardless of attach order."""
        with self._lock:
            self.metrics = metrics
            for key in self.observed:
                result = (
                    "inversion"
                    if any(i["edge"] == key for i in self.inversions)
                    else "observed"
                )
                metrics.count_lock_witness_edge(result)


# ---------------------------------------------------------------------------
# lifecycle


def env_enabled() -> bool:
    return os.environ.get(ENV, "").strip().lower() in _TRUTHY


def installed() -> bool:
    return _STATE is not None


def enabled() -> bool:
    """Witness mode on?  Either installed programmatically (tests) or
    requested via KOORD_LOCK_WITNESS=1 (daemons)."""
    return installed() or env_enabled()


def install(order_edges: Optional[Iterable[Tuple[str, str]]] = None,
            metrics=None) -> None:
    """Arm the witness.  ``order_edges`` defaults to the statically
    derived repo order (one AST pass — debug-mode startup cost)."""
    global _STATE
    with _INSTALL_LOCK:
        if order_edges is None:
            order_edges = _repo_order()
        state = _WitnessState(order_edges)
        if metrics is not None:
            state.metrics = metrics
        _STATE = state


def uninstall() -> None:
    global _STATE
    with _INSTALL_LOCK:
        _STATE = None


def _repo_order() -> Set[Tuple[str, str]]:
    from koordinator_tpu.analysis import lockgraph
    from koordinator_tpu.analysis.core import find_repo_root

    return lockgraph.static_order(find_repo_root(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def _active_state() -> Optional[_WitnessState]:
    if _STATE is not None:
        return _STATE
    if env_enabled():
        install()
        return _STATE
    return None


def attach_metrics(metrics) -> None:
    state = _STATE
    if state is not None:
        state.attach_metrics(metrics)


def observed_edges() -> Dict[Tuple[str, str], int]:
    state = _STATE
    if state is None:
        return {}
    with state._lock:
        return dict(state.observed)


def inversions() -> List[dict]:
    state = _STATE
    if state is None:
        return []
    with state._lock:
        return list(state.inversions)


# ---------------------------------------------------------------------------
# the instrumented primitives


class _WitnessMixin:
    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class WitnessLock(_WitnessMixin):
    def __init__(self, name: str, state: _WitnessState):
        self.name = name
        self._state = state
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._state.note_acquire(self.name)
            except LockOrderInversion:
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        self._state.note_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()


class WitnessRLock(_WitnessMixin):
    def __init__(self, name: str, state: _WitnessState):
        self.name = name
        self._state = state
        self._inner = threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._state.note_acquire(self.name)
            except LockOrderInversion:
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        self._state.note_release(self.name)
        self._inner.release()


class WitnessCondition(_WitnessMixin):
    """Wraps a ``threading.Condition`` (its default RLock); ``wait``
    leaves the held-set for the park and re-records edges on wakeup."""

    def __init__(self, name: str, state: _WitnessState):
        self.name = name
        self._state = state
        self._inner = threading.Condition()

    def acquire(self, *args) -> bool:
        got = self._inner.acquire(*args)
        if got:
            try:
                self._state.note_acquire(self.name)
            except LockOrderInversion:
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        self._state.note_release(self.name)
        self._inner.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        depth = self._state.note_wait_release(self.name)
        try:
            return self._inner.wait(timeout)
        finally:
            self._state.note_wait_reacquire(self.name, depth)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # re-implemented over wait() so the held-set bookkeeping holds
        # for every park, matching the stdlib's loop
        import time

        result = predicate()
        if result:
            return result
        endtime = None if timeout is None else time.monotonic() + timeout
        while not result:
            remaining = None
            if endtime is not None:
                remaining = endtime - time.monotonic()
                if remaining <= 0:
                    break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


# ---------------------------------------------------------------------------
# the factories (the only API creation sites use)


def witness_lock(name: str):
    """``threading.Lock()`` unless witness mode is armed.  ``name`` must
    equal the statically derived identity — lint drift-checks it."""
    state = _active_state()
    if state is None:
        return threading.Lock()
    return WitnessLock(name, state)


def witness_rlock(name: str):
    state = _active_state()
    if state is None:
        return threading.RLock()
    return WitnessRLock(name, state)


def witness_condition(name: str):
    state = _active_state()
    if state is None:
        return threading.Condition()
    return WitnessCondition(name, state)
