"""Offline trace assembly: one request, one tree (ISSUE 14).

``obs/export.py`` leaves each process's completed spans as OTLP-shaped
JSON lines under an export directory.  This module merges those
per-process files back into whole-request trees:

* spans group by ``traceId``; within a trace, ``parentSpanId`` builds
  the tree (client op span -> attempt spans -> server RPC spans ->
  replica-apply / journal-replay spans — the parent ids cross process
  boundaries because the wire carries them);
* **fan-in links** resolve against the WHOLE assembly, not just the
  owning trace: the one launch span of a coalesced batch is parented
  under its leader's trace, and every other rider references it by
  ``(traceId, spanId)`` link;
* a span whose parent id names a span nobody exported is an ORPHAN;
  a link (or a client attempt's recorded ``server_span`` attribute)
  naming a missing span is an UNRESOLVED REF; a trace carrying either
  is INCOMPLETE.  The chaos-trace gate asserts zero client orphans and
  fully complete trees across a leader kill (tests/test_chaos_trace.py).

CLI::

    python -m koordinator_tpu.obs.assemble <dir-or-file>... [--trace ID]
        [--check] [--waterfall N]

``--check`` exits non-zero on any orphan/incomplete trace (the CI
shape); ``--trace`` renders one trace's text waterfall.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Tuple

_REQUIRED_KEYS = ("traceId", "spanId", "name")


def iter_span_files(paths: Iterable[str]) -> List[str]:
    """Expand directories into their ``*.jsonl`` span files (sorted for
    deterministic assembly), pass files through, skip what is absent —
    an empty tier is a report, not a crash."""
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(
                os.path.join(path, name)
                for name in sorted(os.listdir(path))
                if name.endswith(".jsonl")
            )
        elif os.path.isfile(path):
            out.append(path)
    return out


def load_spans(paths: Iterable[str]) -> Tuple[List[dict], int]:
    """All span records from ``paths`` (files or directories), plus a
    count of malformed lines (torn writes from a killed process are
    expected on exactly the runs this tool exists for — counted,
    skipped, never fatal)."""
    spans: List[dict] = []
    malformed = 0
    for path in iter_span_files(paths):
        try:
            fh = open(path, "r", encoding="utf-8")
        except OSError:
            malformed += 1
            continue
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    malformed += 1
                    continue
                if not isinstance(doc, dict) or any(
                    not doc.get(k) for k in _REQUIRED_KEYS
                ):
                    malformed += 1
                    continue
                spans.append(doc)
    return spans, malformed


@dataclasses.dataclass
class AssembledTrace:
    """One trace's tree: spans by id, roots (no parent), and its
    completeness defects."""

    trace_id: str
    spans: Dict[str, dict]
    roots: List[dict]
    orphans: List[dict]          # parentSpanId set but parent missing
    unresolved: List[dict]       # links / server_span refs nobody exported

    @property
    def complete(self) -> bool:
        return not self.orphans and not self.unresolved

    def children(self, span_id: Optional[str]) -> List[dict]:
        kids = [
            s for s in self.spans.values()
            if s.get("parentSpanId") == span_id
        ]
        kids.sort(key=lambda s: s.get("startTimeUnixNano") or 0)
        return kids


@dataclasses.dataclass
class Assembly:
    """The merged view over every export file handed in."""

    traces: Dict[str, AssembledTrace]
    spans_by_id: Dict[str, dict]
    malformed_lines: int

    @property
    def orphan_spans(self) -> List[dict]:
        return [s for t in self.traces.values() for s in t.orphans]

    @property
    def client_orphans(self) -> List[dict]:
        """Client-kind spans that fail to assemble — the gate's 'zero
        orphan client spans' quantity: a client span with a missing
        parent, or a client attempt whose recorded server span nobody
        exported."""
        out = []
        for trace in self.traces.values():
            for s in trace.orphans:
                if s.get("kind") == "client":
                    out.append(s)
            for s in trace.unresolved:
                if s.get("kind") == "client":
                    out.append(s)
        return out

    @property
    def incomplete(self) -> List[AssembledTrace]:
        return [t for t in self.traces.values() if not t.complete]


def _span_refs(span: dict) -> List[Tuple[str, str]]:
    """Every cross-span reference this span claims must exist: its
    fan-in links, plus a client attempt's recorded ``server_span``
    attribute (the reply echo — if the client saw a reply, the server
    span was minted, so its absence from the assembly is a hole)."""
    refs: List[Tuple[str, str]] = []
    for link in span.get("links") or ():
        if isinstance(link, dict) and link.get("spanId"):
            refs.append((str(link.get("traceId") or ""),
                         str(link["spanId"])))
    attrs = span.get("attributes") or {}
    server_span = attrs.get("server_span")
    if server_span:
        refs.append((str(span.get("traceId") or ""), str(server_span)))
    return refs


def assemble(paths: Iterable[str]) -> Assembly:
    spans, malformed = load_spans(paths)
    spans_by_id: Dict[str, dict] = {}
    by_trace: Dict[str, List[dict]] = {}
    for span in spans:
        spans_by_id[str(span["spanId"])] = span
        by_trace.setdefault(str(span["traceId"]), []).append(span)
    traces: Dict[str, AssembledTrace] = {}
    for trace_id, members in by_trace.items():
        ids = {str(s["spanId"]): s for s in members}
        roots, orphans, unresolved = [], [], []
        for span in members:
            parent = span.get("parentSpanId")
            if not parent:
                roots.append(span)
            elif parent not in ids:
                # a parent in ANOTHER trace would be a codec bug, not a
                # tree: parents are intra-trace by construction
                orphans.append(span)
            for _tid, sid in _span_refs(span):
                # links are the cross-trace edges: resolve globally
                if sid not in spans_by_id:
                    unresolved.append(span)
                    break
        roots.sort(key=lambda s: s.get("startTimeUnixNano") or 0)
        traces[trace_id] = AssembledTrace(
            trace_id=trace_id, spans=ids, roots=roots,
            orphans=orphans, unresolved=unresolved,
        )
    return Assembly(
        traces=traces, spans_by_id=spans_by_id, malformed_lines=malformed,
    )


# ---- text waterfall ----

def render_waterfall(trace: AssembledTrace, assembly: Optional[Assembly]
                     = None, width: int = 64) -> str:
    """Plain-text waterfall of one trace: indentation is the tree, the
    bar is wall-clock placement relative to the trace's first span.
    Fan-in links render as ``~> <span-id>`` annotations (the linked
    span may live in another trace — the coalesced-batch shape).

    Device-time truth (ISSUE 19): spans carrying the launch ledger's
    attrs (``device_us``/``compiled``/``flops``, attached by the
    servicer from drained launch notes) split their bar — ``#`` is
    host wall, ``=`` is the sampled device share — and annotate
    ``dev=…us`` (plus ``compile=…ms`` on a first-compile launch), so
    one rendering answers where a slow request's time actually went:
    Python, XLA compile, or the device program itself."""
    if not trace.spans:
        return f"trace {trace.trace_id}: no spans"
    starts = [
        s.get("startTimeUnixNano") or 0 for s in trace.spans.values()
    ]
    ends = [
        s.get("endTimeUnixNano") or 0 for s in trace.spans.values()
    ]
    t0, t1 = min(starts), max(ends)
    total_ns = max(1, t1 - t0)
    dev_spans = [
        s for s in trace.spans.values()
        if (s.get("attributes") or {}).get("device_us") is not None
    ]
    dev_note = ""
    if dev_spans:
        dev_total_us = sum(
            float(s["attributes"]["device_us"]) for s in dev_spans
        )
        dev_note = (
            f", device {dev_total_us / 1e3:.3f} ms sampled across "
            f"{len(dev_spans)} span(s)"
        )
    lines = [
        f"trace {trace.trace_id}"
        f"  ({len(trace.spans)} spans, {total_ns / 1e6:.3f} ms"
        f"{dev_note}"
        f"{', INCOMPLETE' if not trace.complete else ''})"
    ]

    def emit(span: dict, depth: int) -> None:
        start = (span.get("startTimeUnixNano") or 0) - t0
        dur_ms = float(span.get("durMs") or 0.0)
        dur_ns = int(dur_ms * 1e6)
        left = int(width * start / total_ns)
        bar_w = max(1, int(width * dur_ns / total_ns))
        attrs = span.get("attributes") or {}
        dev_us = attrs.get("device_us")
        body = "#" * bar_w
        dev = ""
        if dev_us is not None:
            dev_ms = float(dev_us) / 1e3
            if dur_ms > 0:
                # right-align the device share inside the span's own
                # bar: sampled device time is a total, not an interval,
                # so the split is proportional, not positional
                dev_w = min(
                    bar_w, max(1, round(bar_w * dev_ms / dur_ms))
                )
                body = "#" * (bar_w - dev_w) + "=" * dev_w
            dev = f" dev={float(dev_us):.1f}us"
        if attrs.get("compiled"):
            dev += f" compile={float(attrs.get('compile_ms') or 0.0):.2f}ms"
        bar = " " * left + body[: max(0, width - left)]
        status = span.get("status") or {}
        err = " !" if status.get("code") == "ERROR" else ""
        links = "".join(
            f" ~> {link.get('spanId')}"
            for link in span.get("links") or ()
        )
        label = f"{'  ' * depth}{span.get('name')} [{span.get('kind')}]"
        lines.append(
            f"  {bar:<{width}} {dur_ms:9.3f} ms  {label}{err}{dev}{links}"
        )
        for child in trace.children(str(span["spanId"])):
            emit(child, depth + 1)

    for root in trace.roots:
        emit(root, 0)
    for orphan in trace.orphans:
        lines.append(
            f"  ORPHAN: {orphan.get('name')} "
            f"span={orphan.get('spanId')} "
            f"parent={orphan.get('parentSpanId')} (parent never exported)"
        )
    for span in trace.unresolved:
        lines.append(
            f"  UNRESOLVED REF from {orphan_name(span)}: a linked/"
            "replied span was never exported"
        )
    return "\n".join(lines)


def orphan_name(span: dict) -> str:
    return f"{span.get('name')}[{span.get('spanId')}]"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.obs.assemble",
        description="merge per-process span exports into request trees",
    )
    ap.add_argument("paths", nargs="+",
                    help="export directories (or .jsonl files)")
    ap.add_argument("--trace", default=None,
                    help="render this trace id's waterfall")
    ap.add_argument("--waterfall", type=int, default=0, metavar="N",
                    help="render the N slowest traces' waterfalls")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any orphan span or incomplete trace")
    args = ap.parse_args(argv)

    assembly = assemble(args.paths)
    traces = assembly.traces
    n_spans = len(assembly.spans_by_id)
    n_orphans = len(assembly.orphan_spans)
    incomplete = assembly.incomplete
    print(
        f"{len(traces)} trace(s), {n_spans} span(s); "
        f"{n_orphans} orphan(s), {len(incomplete)} incomplete trace(s), "
        f"{assembly.malformed_lines} malformed line(s)"
    )
    for trace in incomplete:
        print(
            f"  incomplete: {trace.trace_id} "
            f"({len(trace.orphans)} orphan(s), "
            f"{len(trace.unresolved)} unresolved ref(s))"
        )
    if args.trace:
        trace = traces.get(args.trace)
        if trace is None:
            print(f"trace {args.trace} not found", file=sys.stderr)
            return 2
        print(render_waterfall(trace, assembly))
    elif args.waterfall:
        def span_ns(t: AssembledTrace) -> int:
            stamps = [
                s.get("endTimeUnixNano") or 0 for s in t.spans.values()
            ]
            starts = [
                s.get("startTimeUnixNano") or 0 for s in t.spans.values()
            ]
            return (max(stamps) - min(starts)) if t.spans else 0

        slowest = sorted(traces.values(), key=span_ns, reverse=True)
        for trace in slowest[: args.waterfall]:
            print(render_waterfall(trace, assembly))
    if args.check and (n_orphans or incomplete):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
