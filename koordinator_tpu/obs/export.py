"""Span export: completed distributed-trace spans as OTLP-shaped JSON
lines (ISSUE 14).

Every process that participates in a request — the bridge daemon, each
follower, each pooled client — appends its completed spans
(``obs/spans.py TraceSpan.to_record`` dicts) to its OWN file under one
export directory (``--state-dir/traces`` on the daemon;
``--trace-export`` / ``KOORD_TRACE_EXPORT`` names the directory
everywhere else).  ``python -m koordinator_tpu.obs.assemble`` then
merges the per-process files into whole-request trees offline — no
collector service, no network hop on the serving path.

Contract (the flight-recorder discipline applied to spans):

* **Off the serving path.**  ``export()`` is an ENQUEUE (~µs): one
  background writer thread per exporter does the JSON encode, the
  append and the flush — measured at tens of µs per span, which a
  10-span request cycle must not pay inline.  Span ends already run
  only on RPC bodies and readback closures, never inside a launch
  section.
* **Bounded.**  A file past ``max_bytes`` stops growing and a queue
  past ``max_queue`` stops accepting: further spans DROP with a
  counter (``koord_scorer_trace_export_dropped_total``), never an
  error on the serving path.
* **Rate-limited.**  A span storm past ``max_per_s`` (a misbehaving
  client looping traced requests) drops at enqueue with the same
  counter instead of turning the export file into the bottleneck.
* **Crash-visible.**  The writer flushes each drained batch to the OS,
  and it drains EAGERLY (woken per enqueue), so an in-process leader
  kill loses at most the µs-old tail; ``close()`` joins the writer
  after draining everything queued.

A handle must be ``close()``d (koordlint's ``span-leak`` rule checks
exporter construction sites statically); ``export()`` after close
drops, it never raises.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
import uuid
from typing import Dict, Optional

from koordinator_tpu.obs.lockwitness import witness_condition

logger = logging.getLogger(__name__)

EXPORT_VERSION = 1

# a span line is ~300-600 bytes; 64 MiB holds ~10^5 spans — a bound on
# disk, not on any realistic replay
DEFAULT_MAX_BYTES = 64 << 20
# spans per second before the limiter sheds (per-process; the serving
# path emits a handful per RPC, so this only fires on a runaway loop)
DEFAULT_MAX_PER_S = 2000.0
# spans queued for the writer before new ones drop (a wedged disk must
# cost spans, not memory)
DEFAULT_MAX_QUEUE = 4096


def export_dir(state_dir: Optional[str]) -> Optional[str]:
    """The daemon's default export location: ``<state-dir>/traces``
    (the flight-dump convention)."""
    if not state_dir:
        return None
    return os.path.join(state_dir, "traces")


def resolve_export_dir(
    trace_export, state_dir: Optional[str] = None
) -> Optional[str]:
    """One resolution rule for every surface (servicer, clients, the
    daemon flag): an explicit directory wins; the boolean-ish values
    "1"/"true"/"yes" (and the bare-flag empty string) mean "the default
    location under state_dir"; ``False`` (or "0"/"off"/"false"/"none")
    forces tracing OFF even when the env is set — the oracle/baseline
    sides of a measured replay need that; unset (None) falls back to
    the ``KOORD_TRACE_EXPORT`` env (same parse).  Returns the export
    directory or None (off)."""
    if trace_export is None:
        trace_export = os.environ.get("KOORD_TRACE_EXPORT") or None
    if trace_export is None or trace_export is False:
        return None
    text = str(trace_export).strip().lower()
    if text in ("0", "off", "false", "none"):
        return None
    if text in ("", "1", "true", "yes"):
        resolved = export_dir(state_dir)
        if resolved is None:
            # tracing was ASKED for but there is no state dir to
            # default under (the client shims have none): exporting
            # nothing silently would leave every assembled trace
            # incomplete and the operator debugging the assembler —
            # say so, once per construction site
            logger.warning(
                "trace export requested (%r) but this process has no "
                "state dir to default under; span export DISABLED — "
                "pass an explicit directory (KOORD_TRACE_EXPORT=/path "
                "or trace_export=/path)",
                trace_export,
            )
        return resolved
    return str(trace_export)


class SpanExporter:
    """Append-only JSON-lines span sink for ONE process, drained by a
    background writer thread.

    The file name carries the pid and a nonce so concurrent processes
    sharing an export directory (leader + followers + client shims —
    the assembly's whole point) never interleave writes.  Thread-safe;
    failures degrade to the drop counter, never to a serving error.
    ``exported`` counts spans ACCEPTED for write; enqueue-time drops
    (closed/rate/queue) return False, writer-side drops
    (bytes/encode/io) are visible in ``dropped`` after ``close()``
    drains."""

    def __init__(
        self,
        directory: str,
        service: str = "koord-scorer",
        max_bytes: int = DEFAULT_MAX_BYTES,
        max_per_s: float = DEFAULT_MAX_PER_S,
        max_queue: int = DEFAULT_MAX_QUEUE,
        on_export=None,
        on_drop=None,
        clock=time.monotonic,
    ):
        self.directory = directory
        self.service = service
        self.max_bytes = int(max_bytes)
        self.max_per_s = float(max_per_s)
        self.max_queue = int(max_queue)
        # observability seams (CycleTelemetry wires the
        # koord_scorer_trace_spans_total / _export_dropped_total
        # families); on_export fires at enqueue (cheap counter bump),
        # on_drop from whichever side dropped
        self.on_export = on_export
        self.on_drop = on_drop
        self._clock = clock
        self._cond = witness_condition("obs.export.SpanExporter._cond")
        self._queue: collections.deque = collections.deque()
        self._writer: Optional[threading.Thread] = None
        self._closed = False
        # writer-thread-only I/O state (single consumer)
        self._fh = None
        self._bytes = 0
        # token bucket for the rate limit: refills at max_per_s, burst
        # of one second's worth
        self._tokens = self.max_per_s
        self._last_refill = clock()
        self.path = os.path.join(
            directory,
            f"spans-{os.getpid()}-{uuid.uuid4().hex[:8]}.jsonl",
        )
        self.exported = 0
        self.dropped = 0

    def _drop(self, reason: str) -> bool:
        # under _cond from export(), lock-free from the writer — the
        # counter is advisory, the hook (a locked registry) is not
        self.dropped += 1
        if self.on_drop is not None:
            try:
                self.on_drop(reason)
            except Exception:  # a metrics hook must never fail the span path
                logger.warning("span-export drop hook failed", exc_info=True)
        return False

    def export(self, record: Dict[str, object]) -> bool:
        """Enqueue one completed span record for the writer (~µs on
        the serving path); returns False when it was dropped at
        enqueue (closed handle, rate limit, full queue).  Writer-side
        failures (byte bound, unencodable record, I/O) drop with a
        counter instead of surfacing here."""
        with self._cond:
            if self._closed:
                return self._drop("closed")
            now = self._clock()
            self._tokens = min(
                self.max_per_s,
                self._tokens + (now - self._last_refill) * self.max_per_s,
            )
            self._last_refill = now
            if self._tokens < 1.0:
                return self._drop("rate")
            if len(self._queue) >= self.max_queue:
                return self._drop("queue")
            self._tokens -= 1.0
            self._queue.append(record)
            self.exported += 1
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._drain_loop,
                    name="koord-span-export",
                    daemon=True,
                )
                self._writer.start()
            self._cond.notify_all()
            if self.on_export is not None:
                try:
                    self.on_export(str(record.get("kind") or "unknown"))
                except Exception:  # a metrics hook must never fail the span path
                    logger.warning(
                        "span-export count hook failed", exc_info=True
                    )
            return True

    # -- writer thread --
    def _drain_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    # not a poll: every enqueue and close() notifies;
                    # the timeout is a deadlock backstop only
                    self._cond.wait(timeout=1.0)
                batch = list(self._queue)
                self._queue.clear()
                closed = self._closed
            if batch:
                self._write_batch(batch)
            if closed and not batch:
                return

    def _write_batch(self, batch) -> None:
        lines = []
        for record in batch:
            if self._bytes >= self.max_bytes:
                self._drop("bytes")
                continue
            try:
                line = json.dumps(
                    dict(record, resource={
                        "service": self.service,
                        "pid": os.getpid(),
                        "version": EXPORT_VERSION,
                    }),
                    sort_keys=True,
                ) + "\n"
            except (TypeError, ValueError):
                self._drop("encode")
                continue
            self._bytes += len(line)
            lines.append(line)
        if not lines:
            return
        try:
            if self._fh is None:
                os.makedirs(self.directory, exist_ok=True)
                self._fh = open(self.path, "a", encoding="utf-8")
            self._fh.write("".join(lines))
            # per-batch flush to the OS: an in-process leader kill must
            # not eat the spans the post-mortem assembly needs
            self._fh.flush()
        except OSError:
            for _ in lines:
                self._drop("io")

    def close(self) -> None:
        """Drain the queue, stop the writer and close the file.
        Idempotent; an export after close drops with reason "closed"
        instead of raising on a dead file handle."""
        with self._cond:
            self._closed = True
            writer = self._writer
            self._cond.notify_all()
        if writer is not None:
            writer.join(timeout=10.0)
        fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                logger.warning("span exporter close failed", exc_info=True)

    def __enter__(self) -> "SpanExporter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
