"""AOT signature prewarm: kill the cold path's compile ladder (ISSUE 20).

The devprof launch ledger (obs/devprof.py) already labels every
serving-path jit boundary and the static signatures it minted; with
``--prewarm`` the ledger additionally captures, per signature, an
abstract replay spec (array leaves as ``jax.ShapeDtypeStruct``, statics
pickled as-is) persisted as ``<state-dir>/prewarm.pkl``.  On the next
boot — and again on follower promotion and on autoscaler spawn — a
:class:`PrewarmRunner` background thread replays that set through
``fn.lower(*spec).compile()`` in ledger-hot order (most-launched
signatures first) *while the server is already accepting RPCs*:

* a request whose signature the runner has not reached yet just
  compiles inline, exactly as today — prewarm is an accelerant, never
  a gate, and the breaker/brownout ladder is untouched;
* each replayed compile lands in the persistent XLA cache under
  ``--state-dir/xla-cache``, so even the inline-compile fallback pays
  trace time only, not backend compile time;
* replayed signatures land in the compile ledger via
  ``devprof.record_prewarm_compile`` — warm, but NOT attributed
  retraces (replaying yesterday's shapes is the expected boot path).

Progress is observable three ways: the ``koord_scorer_prewarm_*``
metric families, the /healthz ``prewarm`` block
(:meth:`PrewarmRunner.stats`), and the coldstart bench artifact's
``prewarm_ms``.

The two module tables below are the lint-checked contract
(``koordlint prewarm-drift``, analysis/prewarmdrift.py): every
``@devprof.boundary``-registered name must appear in exactly one of
them, so the replay set can never silently rot as boundaries are
added.  ``PREWARM_EXCLUDED`` names boundaries whose signatures carry a
process-local static (a ``jax.sharding.Mesh``) that cannot ride a
pickle — their capture marks them non-replayable and the runner skips
them; everything else is replayable and listed in
``PREWARM_BOUNDARIES``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from koordinator_tpu.obs import devprof

__all__ = [
    "PREWARM_BOUNDARIES",
    "PREWARM_EXCLUDED",
    "PrewarmRunner",
]

# Replayable jit boundaries: statics pickle (frozen CycleConfig, ints,
# bools), so a prior incarnation's signatures replay through the AOT
# seam.  Keep sorted; koordlint prewarm-drift diffs this table against
# every @devprof.boundary registration in the repo, both directions.
PREWARM_BOUNDARIES = (
    "solver.candidates._build",
    "solver.candidates._count_blocks",
    "solver.candidates._extract_block",
    "solver.candidates._refresh",
    "solver.candidates._score",
    "solver.candidates.sparse_top_k",
    "solver.greedy.greedy_assign",
    "solver.greedy.score_cycle",
    "solver.incremental._rescore",
    "solver.pallas_cycle._greedy_assign_pallas",
    "solver.pallas_cycle._run_cycle",
    "solver.pallas_dense._greedy_assign_dense",
    "solver.pallas_dense._run_cycle_dense",
    "solver.resident._scatter_flat",
    "solver.terms._term_extras_jit",
    "solver.topk.masked_top_k",
    "solver.wave._wave_assign",
)

# Boundaries prewarm can never replay, with the reason on record: their
# jit signature includes a process-local static no pickle can carry.
# Capture marks their specs non-replayable (spec=None) at record time;
# the runner counts them skipped.  A fresh mesh process re-compiles
# them inline once — and still hits the persistent XLA cache when the
# mesh geometry matches a prior incarnation's.
PREWARM_EXCLUDED: Dict[str, str] = {
    "parallel.shard_assign._assign_sharded": "mesh static is process-local",
    "parallel.shard_assign._assign_waves": "mesh static is process-local",
    "solver.candidates._build_sharded": "mesh static is process-local",
    "solver.candidates._count_blocks_sharded": "mesh static is process-local",
    "solver.candidates._refresh_sharded": "mesh static is process-local",
    "solver.candidates._score_sharded": "mesh static is process-local",
    "solver.incremental._rescore_sharded": "mesh static is process-local",
    "solver.resident._scatter_flat_sharded": "mesh static is process-local",
}

# modules whose import registers the serving boundaries; the runner
# imports them up front so name->fn resolution does not depend on the
# server having touched every engine before prewarm starts
_BOUNDARY_MODULES = (
    "koordinator_tpu.solver.candidates",
    "koordinator_tpu.solver.greedy",
    "koordinator_tpu.solver.incremental",
    "koordinator_tpu.solver.pallas_cycle",
    "koordinator_tpu.solver.pallas_dense",
    "koordinator_tpu.solver.resident",
    "koordinator_tpu.solver.terms",
    "koordinator_tpu.solver.topk",
    "koordinator_tpu.solver.wave",
    "koordinator_tpu.parallel.shard_assign",
)


def _import_boundary_modules() -> None:
    import importlib

    for mod in _BOUNDARY_MODULES:
        try:
            importlib.import_module(mod)
        except Exception:  # koordlint: disable=broad-except(reason: a backend-gated engine module (pallas on a cpu-only build) failing to import just leaves its boundaries unresolvable — those records are counted skipped, the rest prewarm)
            pass


class PrewarmRunner:
    """Replay a persisted signature set on a background thread.

    One-shot: :meth:`start` spawns the daemon thread, :meth:`stats`
    is the /healthz ``prewarm`` block, :meth:`wait` is the test/bench
    barrier.  Re-triggering (promotion, a fresh autoscaler replica)
    constructs a NEW runner — replays of already-compiled signatures
    cost one trace each and hit both caches.
    """

    def __init__(self, state_dir: str, metrics=None):
        self.state_dir = str(state_dir)
        self._metrics = metrics
        self._lock = threading.Lock()
        self._state = "idle"  # idle -> running -> done
        self._total = 0
        self._replayable = 0
        self._compiled = 0
        self._skipped = 0
        self._failed = 0
        self._compile_ms_total = 0.0
        self._elapsed_ms: Optional[float] = None
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------
    def start(self) -> "PrewarmRunner":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="koord-prewarm"
        )
        with self._lock:
            self._state = "running"
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout=timeout if timeout is not None
                               else 1.0)

    # -- the replay loop ---------------------------------------------
    def _run(self) -> None:
        import pickle

        t0 = time.perf_counter()
        records = devprof.load_prewarm(self.state_dir)
        _import_boundary_modules()
        # future dumps from THIS process must keep yesterday's
        # signatures even if today's traffic never replays them all
        devprof.load_replays(records)
        with self._lock:
            self._total = len(records)
            self._replayable = sum(1 for r in records if r.get("spec"))
        self._gauge(self._replayable)
        pending = self._replayable
        for rec in records:
            if self._stop.is_set():
                break
            spec = rec.get("spec")
            if not spec:
                self._count("skipped")
                continue
            fn = devprof.boundary_fn(rec["boundary"])
            if fn is None or not hasattr(fn, "lower"):
                self._count("skipped")
                pending -= 1
                self._gauge(pending)
                continue
            try:
                args, kwargs = pickle.loads(spec)
                c0 = time.perf_counter()
                compiled = fn.lower(*args, **kwargs).compile()
                compile_ms = (time.perf_counter() - c0) * 1e3
            except Exception:  # koordlint: disable=broad-except(reason: a stale spec (code drift since capture, backend drift) must cost one replay slot, never the serving process — the live path compiles inline as before)
                self._count("failed")
                pending -= 1
                self._gauge(pending)
                continue
            devprof.record_prewarm_compile(
                rec["boundary"], rec["sig"],
                _backend() or "unknown", compile_ms,
                devprof._cost_dict(compiled), devprof._mem_dict(compiled),
            )
            with self._lock:
                self._compiled += 1
                self._compile_ms_total += compile_ms
            m = self._metrics
            if m is not None:
                try:
                    m.count_prewarm("compiled")
                    m.add_prewarm_compile_ms(compile_ms)
                except Exception:  # koordlint: disable=broad-except(reason: telemetry sink drift must not break the prewarm loop; the runner's own counters already recorded the replay)
                    pass
            pending -= 1
            self._gauge(pending)
        with self._lock:
            self._state = "done"
            self._elapsed_ms = (time.perf_counter() - t0) * 1e3
        self._gauge(0)
        self._done.set()

    def _count(self, result: str) -> None:
        with self._lock:
            if result == "skipped":
                self._skipped += 1
            elif result == "failed":
                self._failed += 1
        m = self._metrics
        if m is not None:
            try:
                m.count_prewarm(result)
            except Exception:  # koordlint: disable=broad-except(reason: telemetry sink drift must not break the prewarm loop; the runner's own counters already recorded the outcome)
                pass

    def _gauge(self, pending: int) -> None:
        m = self._metrics
        if m is not None:
            try:
                m.set_prewarm_pending(max(0, int(pending)))
            except Exception:  # koordlint: disable=broad-except(reason: telemetry sink drift must not break the prewarm loop)
                pass

    # -- views -------------------------------------------------------
    def stats(self) -> dict:
        """The /healthz ``prewarm`` block."""
        with self._lock:
            return {
                "state": self._state,
                "total": self._total,
                "replayable": self._replayable,
                "compiled": self._compiled,
                "skipped": self._skipped,
                "failed": self._failed,
                "compile_ms_total": round(self._compile_ms_total, 3),
                "elapsed_ms": (
                    round(self._elapsed_ms, 3)
                    if self._elapsed_ms is not None else None
                ),
            }


def _backend() -> Optional[str]:
    return devprof._backend_platform()
