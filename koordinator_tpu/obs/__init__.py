"""Cycle telemetry for the TPU scoring pipeline (ISSUE 4).

Three layers, composed by :class:`CycleTelemetry` (one per
ScorerServicer, one per bridge daemon):

* **spans** (obs/spans.py) — a monotonic span recorder with explicit
  cycle ids ("c<epoch>-<seq>", correlating with "s<epoch>-<gen>"
  snapshot ids and echoed to clients in AssignReply.cycle_id).  Records
  host-side stages (Sync decode, delta scatter, dispatch, readback) and
  device-derived stats the solver already returns (rounds, path,
  wave_ms) — never from inside jitted code (koordlint's host-sync and
  span-leak rules gate the API statically).
* **metrics** (obs/scorer_metrics.py) — the koord_scorer_* Prometheus
  families over koordlet/metrics.py, served on the bridge daemon's
  /metrics (scheduler/server.py; MetricsRegistry.wsgi_app is the WSGI
  form).
* **flight** (obs/flight.py) — a ring buffer of the last K cycles'
  records + config knobs + snapshot ids, dumped as schema-validated
  JSON under --state-dir on cycle error, kernel demotion, or SIGUSR1.

The overhead contract is locked in by tests/test_resident_warm.py: a
warm delta-Sync/Assign stream with telemetry enabled (it always is on
the bridge) holds ZERO jit cache misses — instrumentation lives
entirely outside the traced programs.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

from koordinator_tpu.obs.export import (  # noqa: F401
    SpanExporter,
    resolve_export_dir,
)
from koordinator_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    validate_flight_dump,
)
from koordinator_tpu.obs.scorer_metrics import ScorerMetrics
from koordinator_tpu.obs.spans import (  # noqa: F401
    ClientTraceOp,
    CycleScope,
    SpanRecorder,
    TraceSpan,
)

logger = logging.getLogger(__name__)


def _config_knobs(cfg) -> Dict[str, object]:
    """The CycleConfig knobs worth reconstructing a bad cycle from."""
    if cfg is None:
        return {}
    return {
        "wave": int(getattr(cfg, "wave", 1)),
        "top_m": int(getattr(cfg, "top_m", 0)),
        "fit_scoring_strategy": getattr(cfg, "fit_scoring_strategy", ""),
        "enable_loadaware": bool(getattr(cfg, "enable_loadaware", False)),
        "enable_fit_score": bool(getattr(cfg, "enable_fit_score", False)),
    }


class CycleTelemetry:
    """Spans + scorer metrics + flight recorder, wired to the process
    feeds (jit cache misses via analysis.retrace_guard, kernel
    demotions via solver.register_demotion_listener)."""

    def __init__(
        self,
        epoch: str = "",
        cfg=None,
        state_dir: Optional[str] = None,
        capacity: int = 64,
        registry=None,
        trace_export: Optional[str] = None,
    ):
        self.spans = SpanRecorder(epoch=epoch)
        self.metrics = ScorerMetrics(registry=registry)
        self.registry = self.metrics.registry
        self.flight = FlightRecorder(
            capacity=capacity, state_dir=state_dir,
            config={"epoch": epoch, **_config_knobs(cfg)},
        )
        # distributed-trace export (ISSUE 14): completed TraceSpans
        # flow recorder -> trace_sink -> exporter as OTLP-shaped JSON
        # lines under the export dir ("<state-dir>/traces" by default
        # when --trace-export / KOORD_TRACE_EXPORT turns it on).  With
        # no exporter the sink still feeds the span-count family —
        # spans only exist when a client stamped a trace_id, so the
        # counter is exact either way.
        self.exporter: Optional[SpanExporter] = None
        directory = resolve_export_dir(trace_export, state_dir)
        if directory is not None:
            self.exporter = SpanExporter(
                directory,
                on_drop=self.metrics.count_trace_export_dropped,
            )
        self.spans.trace_sink = self._sink_trace_span
        self._unhooks = []
        self._install_feeds()

    def _sink_trace_span(self, record) -> None:
        self.metrics.count_trace_span(str(record.get("kind") or "unknown"))
        if self.exporter is not None:
            self.exporter.export(record)

    # -- process-wide feeds --
    def _install_feeds(self) -> None:
        # the listener closure must NOT hold self (or metrics) strongly:
        # watch_cache_misses keeps its callback for the life of the
        # process, and a strong cycle would pin every telemetry — and
        # its servicer — created by every test ever.  A weakref shim
        # no-ops and self-unhooks once the telemetry is collected.
        import weakref

        metrics_ref = weakref.ref(self.metrics)
        cell: Dict[str, object] = {}

        def _on_miss(kind: str) -> None:
            metrics = metrics_ref()
            if metrics is None:
                unhook = cell.pop("unhook", None)
                if unhook is not None:
                    unhook()
                return
            metrics.count_jit_miss(kind)

        try:
            from koordinator_tpu.analysis.retrace_guard import (
                watch_cache_misses,
            )

            cell["unhook"] = watch_cache_misses(_on_miss)
            self._unhooks.append(lambda: cell.pop("unhook", lambda: None)())
        except Exception:  # jax private monitoring API may drift; telemetry must degrade, not fail the server
            logger.warning(
                "jit cache-miss feed unavailable; "
                "koord_scorer_jit_cache_miss_total will not populate",
                exc_info=True,
            )
        from koordinator_tpu import solver

        self._unhooks.append(
            solver.register_demotion_listener(self.on_demotion)
        )

    def close(self) -> None:
        """Unhook the process-wide feeds (tests; daemons run for life)
        and close the span exporter handle."""
        for unhook in self._unhooks:
            try:
                unhook()
            except Exception:  # best-effort teardown; one failed unhook must not keep the rest hooked
                logger.warning("telemetry unhook failed", exc_info=True)
        self._unhooks = []
        if self.exporter is not None:
            self.exporter.close()

    # -- event sinks --
    def on_demotion(self, bucket, failures) -> None:
        """Kernel demotions are PROCESS-global (solver module state) and
        this fires on the demoting thread, which may not be this
        telemetry's servicer thread — so only thread-safe sinks here:
        the locked registry and the RLock'd flight recorder.  Never the
        span recorder (unlocked by design; owned by the RPC thread).
        The demoted bucket rides the dump itself."""
        self.metrics.count_demotion()
        self.flight.dump(
            "demotion",
            extra={
                "bucket": "/".join(map(str, bucket)),
                "failures": int(failures),
            },
        )

    def record_sync(self, info, snapshot_id: str, epoch: str,
                    generation: int) -> None:
        self.metrics.record_sync(info)
        self.metrics.set_snapshot(epoch, generation)
        spans = self.spans
        spans.current(snapshot_id=snapshot_id)
        spans.note("sync_path", info.get("path"))

    def commit_cycle(
        self,
        latency_ms: float,
        path: str,
        wave: int = 1,
        rounds: Optional[int] = None,
    ) -> Dict[str, object]:
        """Close the current cycle: metrics + flight ring."""
        self.metrics.observe_cycle(latency_ms, path, wave, rounds=rounds)
        spans = self.spans
        spans.note("path", path)
        spans.note("latency_ms", round(float(latency_ms), 3))
        if rounds is not None:
            spans.note("rounds", int(rounds))
        record = spans.commit()
        self.flight.record(record)
        return record

    # -- per-RPC scopes (ISSUE 6: exact records under concurrency) --
    def begin_rpc_scope(
        self,
        snapshot_id: Optional[str] = None,
        cycle_id: Optional[str] = None,
        adopt_pending: bool = True,
        trace_id: Optional[str] = None,
    ):
        """A private cycle for one RPC (see obs/spans.py CycleScope).
        The correlating RPC of a Sync→Score→Assign flow adopts the
        pending cycle atomically; concurrent siblings mint fresh ones
        and can no longer relabel or stamp it.  ``trace_id`` stamps
        the distributed-trace correlation onto the cycle record
        (ISSUE 14) so flight dumps and assembled trees cross-reference."""
        return self.spans.open_scope(
            snapshot_id=snapshot_id, cycle_id=cycle_id,
            adopt_pending=adopt_pending, trace_id=trace_id,
        )

    def commit_scope(
        self,
        scope,
        latency_ms: float,
        path: str,
        wave: int = 1,
        rounds: Optional[int] = None,
    ) -> Dict[str, object]:
        """`commit_cycle`, scoped: metrics + the scope's own record into
        the flight ring.  The recorder's pending cycle is untouched."""
        self.metrics.observe_cycle(latency_ms, path, wave, rounds=rounds)
        scope.note("path", path)
        scope.note("latency_ms", round(float(latency_ms), 3))
        if rounds is not None:
            scope.note("rounds", int(rounds))
        record = scope.commit()
        self.flight.record(record)
        return record

    def abort_scope(
        self, scope, stage: str, exc: BaseException, dump: bool = True
    ) -> None:
        """`abort_cycle`, scoped.  ``dump=False`` records the failed
        cycle in the ring without a disk dump — the client-protocol
        conditions (a displaced Assign) that must stay visible in the
        records but must not churn the dump directory."""
        if dump:
            self.metrics.count_cycle_error(stage)
        record = scope.commit(error=f"{stage}: {exc!r:.300}")
        self.flight.record(record)
        if dump:
            self.flight.dump("cycle-error")

    def abort_cycle(self, stage: str, exc: BaseException) -> None:
        """An UNEXPECTED failure on the cycle pipeline: count it, commit
        the partial record with the error attached, and dump the ring
        for the post-mortem.  Client-rejectable errors (a malformed
        frame bounced by validation) must NOT come here — they are
        counted via ``metrics.count_cycle_error`` alone, so a looping
        bad client can neither churn the ring/dump directory nor commit
        a pending cycle out from under another client's correlation."""
        self.metrics.count_cycle_error(stage)
        record = self.spans.commit(error=f"{stage}: {exc!r:.300}")
        self.flight.record(record)
        self.flight.dump("cycle-error")

    # Sync/Score-only streams (e.g. a non-leader replica whose Assign
    # is refused) never reach commit_cycle; without a backstop their
    # spans pile onto one immortal pending cycle and the flight ring
    # stays empty forever.  Past this many buffered spans the pending
    # cycle is committed as a backlog record at the next frame boundary.
    PENDING_COMMIT_SPANS = 64

    def flush_backlog(self) -> None:
        spans = self.spans
        # pending_spans() is atomic on the recorder (the coalescer's
        # batch leaders call this concurrently with Sync commits)
        if spans.pending_spans() >= self.PENDING_COMMIT_SPANS:
            spans.note("backlog", True)
            self.flight.record(spans.commit())
