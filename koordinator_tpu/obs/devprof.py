"""Device-time truth: the XLA launch ledger (ISSUE 19).

Every latency the system publishes elsewhere is host wall-clock —
``time.perf_counter`` around dispatch/readback in ``bridge/server.py``.
This module makes the DEVICE side first-class: every jit boundary in
the serving path registers here with :func:`boundary`, and the ledger
captures, per (boundary, static shape signature):

* **compile truth** — at first-compile time via the AOT path
  (``fn.lower(*args).compile()``): compile wall-time, XLA
  ``cost_analysis()`` (flops, bytes accessed) and
  ``memory_analysis()`` (temp/argument/output bytes), labeled by
  backend platform.  A retrace is therefore no longer just a counter
  bump (``koord_scorer_jit_cache_miss_total``) but an **attributed
  event** naming the boundary and the shape signature that minted it.
* **execution truth** — per-launch device time, sampled at a bounded
  rate (``--devprof-sample N`` = time 1 launch in N;  0 = off) by
  blocking on the launch's own outputs, so the sample is the real
  dispatch→ready wall for exactly that program.

The ledger feeds four consumers: new ``koord_scorer_devprof_*``
metric families on /metrics, ``device_us``/``compiled``/``flops``
attributes on the ``score_launch``/assign spans (the
``obs/assemble.py`` waterfall renders the host/device split), the
/healthz ``device`` block, and the report CLI::

    python -m koordinator_tpu.obs.devprof <state-dir>

which prints the compile ledger and a top-N-by-device-time table with
flops/bytes — the roofline-style per-backend constant factors ROADMAP
item 4's flag-sweep campaign consumes.

The hard contract, inherited from the warm path's compile economics
(docs/ANALYSIS.md "instrumentation never enters jitted code"):

* ``sample == 0`` (the default; oracles pin it) is **bit-inert**: the
  wrapper short-circuits to ``fn(*args, **kwargs)`` before touching
  anything — no signature hashing, no notes, no AOT, zero retraces.
* A boundary invoked while a jax trace is live (nested jits: the
  Pallas cycle calling ``score_cycle``, term extras fused inside
  ``score_all``) bypasses ALL instrumentation — only outermost,
  host-invoked launches are measured.
* Capture is exception-gated everywhere: ``cost_analysis`` /
  ``memory_analysis`` availability drifts across jax versions and
  backends, and a telemetry failure must degrade, never break a
  launch.

Costs, stated honestly: with sampling ON, a cold signature compiles
twice (once for the AOT capture, once through jit's own cache) — the
warm path never pays this; a sampled warm launch pays one
``block_until_ready`` (it serializes that one launch against the
pipeline, which is exactly why sampling is bounded-rate).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "boundary",
    "boundaries",
    "boundary_fn",
    "configure",
    "reset",
    "enabled",
    "capture_enabled",
    "drain_notes",
    "summary",
    "health_block",
    "dump",
    "dump_prewarm",
    "load_prewarm",
    "replay_records",
    "record_prewarm_compile",
    "capture_profile",
    "DEFAULT_SAMPLE",
    "LEDGER_FILENAME",
    "PREWARM_FILENAME",
]

# the recommended sampling rate when the operator turns devprof on
# without choosing one: time 1 launch in 16
DEFAULT_SAMPLE = 16

LEDGER_FILENAME = "devprof.json"

# the prewarm replay set: every (boundary, signature) the process ever
# launched, with an abstract (ShapeDtypeStruct) argument spec a future
# incarnation can replay through fn.lower(...).compile() — pickled
# because the specs carry real static objects (frozen CycleConfig);
# same trust domain as the xla-cache executables beside it
PREWARM_FILENAME = "prewarm.pkl"

# flush the on-disk ledger every this many sampled launches (compile
# events always flush immediately — they are rare and load-bearing)
_FLUSH_EVERY = 32

# flush prewarm launch-count hotness every this many captured launches
# (a NEW signature always flushes immediately — losing one would leave
# a cold hole in the next incarnation's replay set)
_REPLAY_FLUSH_EVERY = 256

# signature strings are labels on events and ledger rows; a pathological
# static repr must not bloat them
_SIG_MAX = 160


def _now() -> float:
    return time.perf_counter()


class _Entry:
    """One (boundary, signature) row of the compile ledger."""

    __slots__ = (
        "boundary", "sig", "backend", "compile_ms", "flops",
        "bytes_accessed", "temp_bytes", "argument_bytes", "output_bytes",
        "first_seen_s",
    )

    def __init__(self, boundary: str, sig: str):
        self.boundary = boundary
        self.sig = sig
        self.backend: Optional[str] = None
        self.compile_ms: Optional[float] = None
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.temp_bytes: Optional[int] = None
        self.argument_bytes: Optional[int] = None
        self.output_bytes: Optional[int] = None
        self.first_seen_s = time.time()

    def to_dict(self) -> dict:
        return {
            "boundary": self.boundary,
            "sig": self.sig,
            "backend": self.backend,
            "compile_ms": self.compile_ms,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "temp_bytes": self.temp_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "first_seen_s": self.first_seen_s,
        }


class _BoundaryStats:
    """Cumulative per-boundary launch/device-time accounting."""

    __slots__ = ("launches", "sampled", "device_us_total", "compiles")

    def __init__(self):
        self.launches = 0
        self.sampled = 0
        self.device_us_total = 0.0
        self.compiles = 0

    def to_dict(self) -> dict:
        return {
            "launches": self.launches,
            "sampled": self.sampled,
            "device_us_total": self.device_us_total,
            "compiles": self.compiles,
        }


class LaunchLedger:
    """Process-global registry of jit boundaries + their capture state.

    One instance lives at module scope (like the retrace-guard hook and
    the kernel demotion listeners); tests get a fresh one via
    :func:`reset`.  All mutation happens under one lock — boundaries
    fire from the bridge worker threads AND the pipelined readback
    threads concurrently.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._boundaries: Dict[str, _BoundaryStats] = {}
        self._entries: Dict[tuple, _Entry] = {}  # (boundary, sig) -> row
        self._retraces: List[dict] = []  # attributed retrace events
        self.sample = 0
        self.capture = False  # prewarm replay-spec capture (ISSUE 20)
        self._metrics_ref: Optional[Callable[[], Any]] = None
        self.state_dir: Optional[str] = None
        self._counter = 0  # global launch counter driving 1-in-N
        self._unflushed = 0
        # (boundary, sig) -> replay record: per-sig launch hotness plus
        # the pickled abstract argument spec (None = non-replayable)
        self._replays: Dict[tuple, dict] = {}
        self._replay_unflushed = 0
        self._tls = threading.local()

    # -- registration ------------------------------------------------
    def register(self, name: str) -> None:
        with self._lock:
            self._boundaries.setdefault(name, _BoundaryStats())

    def boundaries(self) -> List[str]:
        with self._lock:
            return sorted(self._boundaries)

    # -- configuration -----------------------------------------------
    def configure(self, sample: Optional[int] = None, metrics=None,
                  state_dir: Optional[str] = None,
                  capture: Optional[bool] = None) -> None:
        import weakref

        with self._lock:
            if sample is not None:
                self.sample = max(0, int(sample))
            if capture is not None:
                self.capture = bool(capture)
            if metrics is not None:
                # weakref, CycleTelemetry-feed style: the ledger is
                # process-global and must never pin a servicer's
                # metrics object past its lifetime
                self._metrics_ref = weakref.ref(metrics)
            if state_dir is not None:
                self.state_dir = str(state_dir)

    def _metrics(self):
        ref = self._metrics_ref
        if ref is None:
            return None
        return ref()

    # -- the wrapper's accounting primitives -------------------------
    def should_sample(self) -> bool:
        """1-in-N gate over the global launch counter (all boundaries
        share one counter so a quiet boundary still gets samples)."""
        with self._lock:
            self._counter += 1
            return self.sample > 0 and self._counter % self.sample == 0

    def note_launch(self, name: str) -> None:
        with self._lock:
            st = self._boundaries.setdefault(name, _BoundaryStats())
            st.launches += 1

    def seen_sig(self, name: str, sig: str) -> bool:
        with self._lock:
            return (name, sig) in self._entries

    def record_compile(self, name: str, sig: str, backend: str,
                       compile_ms: float, cost: Optional[dict],
                       mem: Optional[dict]) -> None:
        with self._lock:
            st = self._boundaries.setdefault(name, _BoundaryStats())
            prior_sigs = st.compiles
            st.compiles += 1
            e = self._entries.setdefault((name, sig), _Entry(name, sig))
            e.backend = backend
            e.compile_ms = compile_ms
            if cost:
                e.flops = cost.get("flops")
                e.bytes_accessed = cost.get("bytes accessed")
            if mem:
                e.temp_bytes = mem.get("temp")
                e.argument_bytes = mem.get("argument")
                e.output_bytes = mem.get("output")
            retrace = prior_sigs > 0
            if retrace:
                # the attributed event the ISSUE asks for: not "a
                # cache miss happened" but "THIS boundary minted a new
                # program for THIS shape"
                self._retraces.append({
                    "boundary": name,
                    "sig": sig,
                    "backend": backend,
                    "compile_ms": compile_ms,
                    "at_s": time.time(),
                })
        m = self._metrics()
        if m is not None:
            try:
                m.devprof_compile(name, backend, compile_ms)
                if retrace:
                    m.devprof_retrace(name)
            except Exception:  # koordlint: disable=broad-except(reason: telemetry sink drift must not break a launch; the ledger itself already recorded the compile)
                pass
        self._flush(force=True)

    def record_device_time(self, name: str, device_us: float) -> None:
        with self._lock:
            st = self._boundaries.setdefault(name, _BoundaryStats())
            st.sampled += 1
            st.device_us_total += device_us
            self._unflushed += 1
            flush = self._unflushed >= _FLUSH_EVERY
        m = self._metrics()
        if m is not None:
            try:
                m.devprof_device_us(name, device_us)
            except Exception:  # koordlint: disable=broad-except(reason: telemetry sink drift must not break a launch; the ledger itself already recorded the sample)
                pass
        if flush:
            self._flush(force=True)

    # -- prewarm replay capture (ISSUE 20) ---------------------------
    def note_replay(self, name: str, sig: str, args: tuple,
                    kwargs: dict) -> None:
        """Capture-mode accounting: bump the (boundary, sig) launch
        hotness; on first sight, record the abstract argument spec a
        future incarnation replays.  Spec pickling happens OUTSIDE the
        lock (statics can be arbitrarily slow to serialize); the
        double-checked insert keeps concurrent first-sights exact."""
        with self._lock:
            rec = self._replays.get((name, sig))
            if rec is not None:
                rec["launches"] += 1
                self._replay_unflushed += 1
                flush = self._replay_unflushed >= _REPLAY_FLUSH_EVERY
                if flush:
                    self._replay_unflushed = 0
            else:
                flush = False
        if rec is None:
            spec = _replay_spec_bytes(args, kwargs)
            with self._lock:
                rec = self._replays.setdefault((name, sig), {
                    "boundary": name,
                    "sig": sig,
                    "launches": 0,
                    "spec": spec,
                    "first_seen_s": time.time(),
                })
                rec["launches"] += 1
            flush = True  # a new signature flushes immediately
        if flush:
            self.dump_prewarm()

    def record_prewarm_compile(self, name: str, sig: str, backend: str,
                               compile_ms: float, cost: Optional[dict],
                               mem: Optional[dict]) -> None:
        """A compile the PREWARM thread performed: lands in the compile
        ledger like any other (so a later live launch of the same
        signature sees it warm and skips its own AOT capture), but is
        NOT an attributed retrace and feeds the ``prewarm_*`` metric
        families, not ``devprof_*`` — replaying yesterday's signatures
        is the expected boot path, not a shape-stability regression."""
        with self._lock:
            st = self._boundaries.setdefault(name, _BoundaryStats())
            st.compiles += 1
            e = self._entries.setdefault((name, sig), _Entry(name, sig))
            e.backend = backend
            e.compile_ms = compile_ms
            if cost:
                e.flops = cost.get("flops")
                e.bytes_accessed = cost.get("bytes accessed")
            if mem:
                e.temp_bytes = mem.get("temp")
                e.argument_bytes = mem.get("argument")
                e.output_bytes = mem.get("output")
        self._flush(force=True)

    def replay_records(self) -> List[dict]:
        """The captured replay set, ledger-hot order (most-launched
        first; ties break on name+sig for a deterministic replay)."""
        with self._lock:
            recs = [dict(r) for r in self._replays.values()]
        recs.sort(key=lambda r: (-r["launches"], r["boundary"], r["sig"]))
        return recs

    def load_replays(self, records: List[dict]) -> None:
        """Seed the capture set from a prior incarnation's prewarm file
        so re-dumps don't forget signatures this process never
        launched (counts merge additively on re-sight)."""
        with self._lock:
            for r in records:
                key = (r.get("boundary"), r.get("sig"))
                if key not in self._replays:
                    self._replays[key] = dict(r)

    def dump_prewarm(self, state_dir: Optional[str] = None) -> Optional[str]:
        """Write the replay set as ``<state-dir>/prewarm.pkl``.
        Returns the path, or None without a state dir."""
        import pickle

        target = state_dir or self.state_dir
        if not target:
            return None
        path = os.path.join(target, PREWARM_FILENAME)
        doc = {"version": 1, "records": self.replay_records()}
        tmp = path + ".tmp"
        try:
            os.makedirs(target, exist_ok=True)
            with open(tmp, "wb") as fh:
                pickle.dump(doc, fh)
            os.replace(tmp, path)
        except OSError:
            return None
        return path

    # -- per-thread launch notes (span attribution seam) -------------
    def push_note(self, note: dict) -> None:
        notes = getattr(self._tls, "notes", None)
        if notes is None:
            notes = self._tls.notes = []
        notes.append(note)

    def drain_notes(self) -> List[dict]:
        notes = getattr(self._tls, "notes", None)
        if not notes:
            return []
        out = list(notes)
        notes.clear()
        return out

    # -- views -------------------------------------------------------
    def summary(self) -> dict:
        """The bench/report view: compile ledger + per-boundary
        cumulative device time + attributed retraces."""
        with self._lock:
            entries = [e.to_dict() for e in self._entries.values()]
            bounds = {
                n: st.to_dict() for n, st in self._boundaries.items()
            }
            retraces = list(self._retraces)
            sample = self.sample
        entries.sort(key=lambda d: (d["boundary"], d["sig"]))
        return {
            "sample": sample,
            "backend": _backend_platform(),
            "boundaries": bounds,
            "entries": entries,
            "retraces": retraces,
        }

    def health_block(self, top: int = 3) -> dict:
        """The /healthz ``device`` block: platform, device count, the
        compile ledger summary, and the top boundaries by cumulative
        device time."""
        with self._lock:
            compiles = sum(st.compiles for st in self._boundaries.values())
            compile_ms = sum(
                e.compile_ms or 0.0 for e in self._entries.values()
            )
            ranked = sorted(
                (
                    (n, st) for n, st in self._boundaries.items()
                    if st.device_us_total > 0
                ),
                key=lambda kv: kv[1].device_us_total,
                reverse=True,
            )[:top]
            retraces = len(self._retraces)
            sample = self.sample
            registered = len(self._boundaries)
        return {
            "platform": _backend_platform(),
            "device_count": _device_count(),
            "sample": sample,
            "registered_boundaries": registered,
            "compiles": compiles,
            "compile_ms_total": round(compile_ms, 3),
            "retraces": retraces,
            "top": [
                {
                    "boundary": n,
                    "device_us_total": round(st.device_us_total, 1),
                    "sampled": st.sampled,
                    "launches": st.launches,
                }
                for n, st in ranked
            ],
        }

    # -- persistence -------------------------------------------------
    def dump(self, state_dir: Optional[str] = None) -> Optional[str]:
        """Write the ledger as ``<state-dir>/devprof.json`` (the report
        CLI's input).  Returns the path, or None without a state dir."""
        target = state_dir or self.state_dir
        if not target:
            return None
        path = os.path.join(target, LEDGER_FILENAME)
        doc = self.summary()
        tmp = path + ".tmp"
        try:
            os.makedirs(target, exist_ok=True)
            with open(tmp, "w") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            return None
        with self._lock:
            self._unflushed = 0
        return path

    def _flush(self, force: bool = False) -> None:
        if self.state_dir:
            self.dump()


# -- module-level singleton ------------------------------------------

_LEDGER = LaunchLedger()

# boundary name -> the jitted callable the decorator wrapped.  Module
# scope (NOT ledger state): decoration happens once per import, and a
# test's reset() must not orphan the prewarm runner's name->fn
# resolution.  Latest registration wins (module reloads).
_BOUNDARY_FNS: Dict[str, Any] = {}


def _ledger() -> LaunchLedger:
    return _LEDGER


def reset() -> None:
    """Fresh ledger (tests).  Boundaries re-register lazily on their
    next launch; already-wrapped callables keep working because the
    wrapper resolves the singleton per call."""
    global _LEDGER
    _LEDGER = LaunchLedger()


def configure(sample: Optional[int] = None, metrics=None,
              state_dir: Optional[str] = None,
              capture: Optional[bool] = None) -> None:
    _LEDGER.configure(sample=sample, metrics=metrics, state_dir=state_dir,
                      capture=capture)


def enabled() -> bool:
    return _LEDGER.sample > 0


def capture_enabled() -> bool:
    return _LEDGER.capture


def boundaries() -> List[str]:
    return _LEDGER.boundaries()


def boundary_fn(name: str) -> Optional[Any]:
    """The jitted callable registered under ``name`` (its ``.lower``
    AOT seam is the prewarm replay target), or None when the defining
    module has not been imported in this process."""
    return _BOUNDARY_FNS.get(name)


def replay_records() -> List[dict]:
    return _LEDGER.replay_records()


def dump_prewarm(state_dir: Optional[str] = None) -> Optional[str]:
    return _LEDGER.dump_prewarm(state_dir)


def load_prewarm(state_dir: str) -> List[dict]:
    """Read ``<state-dir>/prewarm.pkl`` -> replay records, ledger-hot
    order.  Missing/corrupt files are an empty replay set — prewarm is
    an accelerant, never a boot dependency."""
    import pickle

    path = os.path.join(state_dir, PREWARM_FILENAME)
    try:
        with open(path, "rb") as fh:
            doc = pickle.load(fh)
    except Exception:  # koordlint: disable=broad-except(reason: a missing, torn or version-drifted prewarm file must degrade to a cold boot, never block one)
        return []
    records = doc.get("records") if isinstance(doc, dict) else None
    if not isinstance(records, list):
        return []
    out = [
        r for r in records
        if isinstance(r, dict) and r.get("boundary") and r.get("sig")
    ]
    out.sort(key=lambda r: (-int(r.get("launches") or 0),
                            r["boundary"], r["sig"]))
    return out


def record_prewarm_compile(name: str, sig: str, backend: str,
                           compile_ms: float, cost: Optional[dict],
                           mem: Optional[dict]) -> None:
    _LEDGER.record_prewarm_compile(name, sig, backend, compile_ms,
                                   cost, mem)


def load_replays(records: List[dict]) -> None:
    _LEDGER.load_replays(records)


def drain_notes() -> List[dict]:
    """Pop this thread's launch notes (bridge span attribution).  Cheap
    no-op when devprof is off — the wrapper never pushes then."""
    return _LEDGER.drain_notes()


def summary() -> dict:
    return _LEDGER.summary()


def health_block(top: int = 3) -> dict:
    return _LEDGER.health_block(top=top)


def dump(state_dir: Optional[str] = None) -> Optional[str]:
    return _LEDGER.dump(state_dir)


# -- environment probes (exception-gated; jax import stays lazy) -----

def _backend_platform() -> Optional[str]:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # koordlint: disable=broad-except(reason: environment probe — no jax / no backend means no platform to report, never an error)
        return None


def _device_count() -> Optional[int]:
    try:
        import jax

        return jax.device_count()
    except Exception:  # koordlint: disable=broad-except(reason: environment probe — no jax / no backend means no device count to report, never an error)
        return None


def _trace_state_clean() -> bool:
    """True when no jax trace is live on this thread.  Drift-tolerant:
    when the probe is unavailable we claim clean and rely on the
    exception gates (a tracer poisons perf_counter math, not
    correctness — the wrapper still returns fn's result)."""
    try:
        import jax

        return bool(jax.core.trace_state_clean())
    except Exception:  # koordlint: disable=broad-except(reason: version-drift probe; claiming clean only risks a harmless timing sample, never correctness)
        return True


# -- signatures ------------------------------------------------------

def _leaf_sig(leaf: Any) -> str:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    r = repr(leaf)
    if len(r) > 40:
        r = r[:37] + "..."
    return r


def shape_signature(args: tuple, kwargs: dict) -> str:
    """The static shape signature keying the compile ledger: dtype[shape]
    per array leaf (pytrees flattened), short reprs for statics — the
    same partition jit's own cache keys on, rendered human-readable so a
    retrace event names the shape that minted it."""
    from jax.tree_util import tree_leaves

    parts = [_leaf_sig(leaf) for leaf in tree_leaves((args, kwargs))]
    sig = ";".join(parts)
    if len(sig) > _SIG_MAX:
        import hashlib

        digest = hashlib.sha1(sig.encode()).hexdigest()[:8]
        sig = sig[: _SIG_MAX - 12] + "...#" + digest
    return sig


# -- prewarm replay specs --------------------------------------------

def _replay_spec_bytes(args: tuple, kwargs: dict) -> Optional[bytes]:
    """Pickle an ABSTRACT copy of a launch's arguments: array leaves
    become ``jax.ShapeDtypeStruct`` (shape/dtype/weak_type — exactly
    what ``fn.lower`` needs to mint the same program), statics ride
    as-is.  None = non-replayable (a process-local static like a Mesh
    refuses pickling); the launch itself is never at risk."""
    try:
        import pickle

        import jax
        from jax.tree_util import tree_map

        def leaf(x):
            shape = getattr(x, "shape", None)
            dtype = getattr(x, "dtype", None)
            if shape is not None and dtype is not None:
                return jax.ShapeDtypeStruct(
                    shape, dtype,
                    weak_type=bool(getattr(x, "weak_type", False)),
                )
            return x

        return pickle.dumps(tree_map(leaf, (args, dict(kwargs))))
    except Exception:  # koordlint: disable=broad-except(reason: an unpicklable static (Mesh, callables) marks the signature non-replayable; capture degrades, the launch is unaffected)
        return None


# -- AOT capture -----------------------------------------------------

def _cost_dict(compiled) -> Optional[dict]:
    try:
        ca = compiled.cost_analysis()
    except Exception:  # koordlint: disable=broad-except(reason: cost_analysis availability drifts across jax versions/backends; attribution degrades to None, the launch is unaffected)
        return None
    if isinstance(ca, (list, tuple)):  # per-device list on some versions
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for key in ("flops", "bytes accessed"):
        v = ca.get(key)
        if isinstance(v, (int, float)):
            out[key] = float(v)
    return out


def _mem_dict(compiled) -> Optional[dict]:
    try:
        ma = compiled.memory_analysis()
    except Exception:  # koordlint: disable=broad-except(reason: memory_analysis availability drifts across jax versions/backends; attribution degrades to None, the launch is unaffected)
        return None
    out = {}
    for key, attr in (
        ("temp", "temp_size_in_bytes"),
        ("argument", "argument_size_in_bytes"),
        ("output", "output_size_in_bytes"),
    ):
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)):
            out[key] = int(v)
    return out or None


def _aot_capture(led: LaunchLedger, name: str, sig: str, fn,
                 args: tuple, kwargs: dict) -> Optional[float]:
    """First-compile capture through the AOT path.  Returns compile
    wall-time ms, or None when the boundary refuses AOT (abstract
    tracing can reject what the concrete call accepts — e.g. a
    non-hashable static); the launch itself is never at risk."""
    try:
        t0 = _now()
        compiled = fn.lower(*args, **kwargs).compile()
        compile_ms = (_now() - t0) * 1e3
    except Exception:  # koordlint: disable=broad-except(reason: AOT lowering can reject what the concrete call accepts (non-hashable statics); the boundary then runs unattributed rather than failing the launch)
        return None
    led.record_compile(
        name, sig, _backend_platform() or "unknown", compile_ms,
        _cost_dict(compiled), _mem_dict(compiled),
    )
    return compile_ms


# -- the decorator ---------------------------------------------------

def boundary(name: str):
    """Register a jit boundary with the launch ledger.

    Stacks ABOVE the jit application (decorators apply bottom-up), so
    the wrapper holds the jitted callable and its ``.lower`` AOT seam::

        @devprof.boundary("solver.greedy.score_cycle")
        @partial(jax.jit, static_argnames=("cfg",))
        def score_cycle(snapshot, *, cfg): ...

    Off (``sample == 0`` and prewarm capture off): one comparison then
    tail-call — the warm stream is bit-identical with zero retraces
    (the tier-1 retrace-guard oracles run this path).  Inside a live
    jax trace the wrapper also steps aside: nested boundaries
    (``score_cycle`` under the Pallas cycle, term extras inside
    ``score_all``) measure at their outermost host callsite only.
    With prewarm capture ON (``--prewarm``, ISSUE 20) every outermost
    launch additionally records its (boundary, signature) and an
    abstract replay spec for the next incarnation's prewarm thread.
    """

    def deco(fn):
        _LEDGER.register(name)
        _BOUNDARY_FNS[name] = fn

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            led = _LEDGER
            if led.sample <= 0 and not led.capture:
                return fn(*args, **kwargs)  # bit-inert fast path
            if not _trace_state_clean():
                return fn(*args, **kwargs)  # nested under another jit
            if led.capture:
                try:
                    led.note_replay(
                        name, shape_signature(args, kwargs), args, kwargs
                    )
                except Exception:  # koordlint: disable=broad-except(reason: replay capture is an accelerant — an exotic pytree costs the prewarm record, never the launch)
                    pass
                if led.sample <= 0:
                    return fn(*args, **kwargs)
            led.note_launch(name)
            compile_ms = None
            try:
                sig = shape_signature(args, kwargs)
                cold = not led.seen_sig(name, sig)
            except Exception:  # koordlint: disable=broad-except(reason: an unhashable/exotic pytree must cost attribution, never the launch — fall through to the plain call)
                return fn(*args, **kwargs)
            if cold:
                # AOT capture; this signature's launch is NOT
                # device-sampled — the jit-cache compile it pays next
                # would contaminate the sample
                compile_ms = _aot_capture(led, name, sig, fn, args, kwargs)
                out = fn(*args, **kwargs)
                led.push_note({
                    "boundary": name, "sig": sig, "compiled": True,
                    "compile_ms": compile_ms, "device_us": None,
                    "flops": _entry_flops(led, name, sig),
                })
                return out
            if led.should_sample():
                import jax

                t0 = _now()
                out = fn(*args, **kwargs)
                try:
                    jax.block_until_ready(out)
                except Exception:  # koordlint: disable=broad-except(reason: non-array outputs or backend drift make the barrier best-effort; the sample degrades to dispatch time, the result is returned untouched)
                    pass
                device_us = (_now() - t0) * 1e6
                led.record_device_time(name, device_us)
                led.push_note({
                    "boundary": name, "sig": sig, "compiled": False,
                    "compile_ms": None, "device_us": device_us,
                    "flops": _entry_flops(led, name, sig),
                })
                return out
            return fn(*args, **kwargs)

        wrapper.__wrapped__ = fn
        wrapper.devprof_boundary = name
        return wrapper

    return deco


def _entry_flops(led: LaunchLedger, name: str, sig: str) -> Optional[float]:
    with led._lock:
        e = led._entries.get((name, sig))
        return e.flops if e is not None else None


# -- on-demand profiler capture (admin plane) ------------------------

def capture_profile(state_dir: str, window_ms: int = 1000) -> str:
    """Start a ``jax.profiler`` trace window under ``state_dir`` and
    stop it after ``window_ms`` on a background thread — the admin-RPC
    seam (udsserver METHOD_PROFILE) returns the capture directory
    immediately; XLA-level inspection happens offline."""
    import jax

    out_dir = os.path.join(
        state_dir, "devprof_trace", f"capture-{os.getpid()}-{time.time_ns()}"
    )
    os.makedirs(out_dir, exist_ok=True)
    jax.profiler.start_trace(out_dir)

    def _stop():
        time.sleep(max(0, int(window_ms)) / 1e3)
        try:
            jax.profiler.stop_trace()
        except Exception:  # koordlint: disable=broad-except(reason: double-stop / backend teardown races are admin-plane noise, not daemon faults)
            pass

    threading.Thread(target=_stop, daemon=True, name="devprof-capture").start()
    return out_dir


# -- report CLI ------------------------------------------------------

def _fmt_num(v, scale=1.0, suffix="") -> str:
    if v is None:
        return "-"
    return f"{v / scale:,.1f}{suffix}"


def format_report(doc: dict, top: int = 10) -> str:
    """Render a dumped ledger: the compile ledger (one row per
    boundary+signature with compile ms / flops / bytes) and the
    top-N-by-cumulative-device-time table."""
    lines = []
    backend = doc.get("backend") or "unknown"
    lines.append(
        f"devprof ledger — backend={backend} sample={doc.get('sample')}"
    )
    lines.append("")
    lines.append("compile ledger:")
    header = (
        f"  {'boundary':<44} {'compile_ms':>10} {'flops':>12} "
        f"{'bytes':>12} {'temp_b':>10}  sig"
    )
    lines.append(header)
    for e in doc.get("entries", []):
        lines.append(
            f"  {e['boundary']:<44} "
            f"{_fmt_num(e.get('compile_ms')):>10} "
            f"{_fmt_num(e.get('flops')):>12} "
            f"{_fmt_num(e.get('bytes_accessed')):>12} "
            f"{_fmt_num(e.get('temp_bytes')):>10}  {e.get('sig', '')}"
        )
    if not doc.get("entries"):
        lines.append("  (no compiles captured)")
    lines.append("")
    lines.append(f"top boundaries by cumulative device time (top {top}):")
    lines.append(
        f"  {'boundary':<44} {'device_ms':>10} {'sampled':>8} "
        f"{'launches':>9} {'compiles':>9}"
    )
    ranked = sorted(
        doc.get("boundaries", {}).items(),
        key=lambda kv: kv[1].get("device_us_total", 0.0),
        reverse=True,
    )
    shown = 0
    for name, st in ranked:
        if shown >= top:
            break
        lines.append(
            f"  {name:<44} "
            f"{st.get('device_us_total', 0.0) / 1e3:>10,.2f} "
            f"{st.get('sampled', 0):>8} {st.get('launches', 0):>9} "
            f"{st.get('compiles', 0):>9}"
        )
        shown += 1
    if not ranked:
        lines.append("  (no launches recorded)")
    retraces = doc.get("retraces", [])
    if retraces:
        lines.append("")
        lines.append(f"attributed retraces ({len(retraces)}):")
        for r in retraces:
            lines.append(
                f"  {r['boundary']}  +{_fmt_num(r.get('compile_ms'))} ms"
                f"  sig={r.get('sig', '')}"
            )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m koordinator_tpu.obs.devprof",
        description="Print the XLA launch ledger captured under a "
        "daemon's --state-dir (compile costs + top boundaries by "
        "cumulative device time).",
    )
    ap.add_argument("state_dir", help="daemon --state-dir (or any "
                    f"directory holding {LEDGER_FILENAME})")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the device-time table (default 10)")
    args = ap.parse_args(argv)
    path = args.state_dir
    if os.path.isdir(path):
        path = os.path.join(path, LEDGER_FILENAME)
    if not os.path.exists(path):
        print(f"devprof: no ledger at {path} (run a daemon with "
              "--devprof-sample > 0, or call devprof.dump())",
              file=sys.stderr)
        return 2
    with open(path) as fh:
        doc = json.load(fh)
    print(format_report(doc, top=args.top))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via main() in tests
    raise SystemExit(main())
