"""SLO evaluation over the ``koord_scorer_*`` histogram families (ISSUE 12).

The trace-driven replay harness (harness/trace.py, ``bench.py --config
trace``) turns the observability layer into a perf GATE: a replay does
not just populate histograms, it judges them against declarative SLO
specs and publishes pass/fail verdicts in the BENCH artifact.  This
module is the judging half, and it deliberately has no harness
dependencies — the daemon's ``/healthz`` serves the SAME estimator over
the same registry, so the numbers an operator reads are the numbers
the gate judges.

Three layers:

* :func:`quantile_from_buckets` — Prometheus ``histogram_quantile``
  semantics over one series' cumulative bucket counts: rank
  ``q * count`` located in the first bucket whose cumulative count
  covers it, linearly interpolated from the bucket's lower bound (0
  for the first bucket).  Mass in the ``+Inf`` bucket estimates as the
  last FINITE bound — the estimator never invents a number above what
  the buckets can support (the Prometheus convention; alert thresholds
  should sit below the top finite bound for exactly this reason).
* :func:`histogram_quantile` — the same estimate over a FAMILY in a
  ``koordlet.metrics.MetricsRegistry``, with label-subset aggregation:
  passing ``labels={"rpc": "assign"}`` sums the bucket counts of every
  series whose labels contain that subset (e.g. all bands of the trace
  family), so per-band and per-RPC extractions read one seam.
* :class:`SloSpec` / :func:`evaluate_slos` — a declarative spec names
  a family, a label subset, a quantile and a threshold; a verdict
  carries the observed estimate, the window's sample count and a
  boolean ``ok``.  A spec whose series holds fewer than ``min_count``
  observations FAILS with ``reason="no data"`` — a gate that cannot
  see is a failed gate, never a silently green one.

:class:`SloWindow` adds the operator view: cumulative histograms only
grow, so it snapshots bucket counts per series and quantile-estimates
the DELTA since the previous call — ``/healthz``'s ``slo`` block is one
``advance()`` per scrape (the first call reports the since-boot
window).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

DEFAULT_QUANTILES = (0.5, 0.99)


def quantile_from_buckets(
    bounds: Sequence[float],
    cumulative: Sequence[int],
    q: float,
) -> Optional[float]:
    """Estimate quantile ``q`` from ``cumulative[i]`` = observations
    ``<= bounds[i]`` (ascending bounds, last one ``+Inf``).  Returns
    None for an empty series.  Monotone in ``q`` by construction."""
    if not bounds or not cumulative or len(bounds) != len(cumulative):
        return None
    total = cumulative[-1]
    if total <= 0:
        return None
    q = min(max(float(q), 0.0), 1.0)
    rank = q * total
    for i, bound in enumerate(bounds):
        if cumulative[i] >= rank:
            prev_cum = cumulative[i - 1] if i else 0
            if math.isinf(bound):
                # mass past the top finite bound: report that bound —
                # the estimator cannot support anything higher
                finite = [b for b in bounds if not math.isinf(b)]
                return finite[-1] if finite else None
            lower = bounds[i - 1] if i else 0.0
            in_bucket = cumulative[i] - prev_cum
            if in_bucket <= 0:
                return float(bound)
            return lower + (bound - lower) * (rank - prev_cum) / in_bucket
    return None  # unreachable with a +Inf bucket; defensive


def _matches(series_labels: Mapping[str, str],
             subset: Mapping[str, str]) -> bool:
    return all(series_labels.get(k) == v for k, v in subset.items())


def aggregate_buckets(
    registry,
    family: str,
    labels: Optional[Mapping[str, str]] = None,
) -> Tuple[Tuple[float, ...], List[int], int]:
    """Sum the cumulative bucket counts of every series of ``family``
    whose labels contain the ``labels`` subset.  Returns
    ``(bounds, cumulative, count)`` — empty bounds when no series
    matches (bounds are identical across one family's series, enforced
    at registration)."""
    subset = dict(labels or {})
    bounds: Tuple[float, ...] = ()
    summed: List[int] = []
    count = 0
    for s_labels, s_bounds, s_cum, _s_sum, s_count in registry.histogram_series(
        family
    ):
        if not _matches(s_labels, subset):
            continue
        if not bounds:
            bounds = s_bounds
            summed = [0] * len(bounds)
        for i, c in enumerate(s_cum):
            summed[i] += c
        count += s_count
    return bounds, summed, count


def histogram_quantile(
    registry,
    family: str,
    q: float,
    labels: Optional[Mapping[str, str]] = None,
) -> Optional[float]:
    """Quantile estimate over a registry family, aggregated across
    every series matching the ``labels`` subset (None/{} = the whole
    family)."""
    bounds, cumulative, _count = aggregate_buckets(registry, family, labels)
    return quantile_from_buckets(bounds, cumulative, q)


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One declarative SLO: quantile ``quantile`` of ``family``
    (aggregated over the ``labels`` subset) must sit at or below
    ``threshold_ms``.  ``min_count`` observations must exist in the
    judged window, else the verdict fails with ``no data``."""

    name: str
    family: str
    quantile: float
    threshold_ms: float
    labels: Tuple[Tuple[str, str], ...] = ()
    min_count: int = 1

    def __post_init__(self):
        # accept a plain dict at construction; store the hashable form
        if isinstance(self.labels, Mapping):
            object.__setattr__(
                self, "labels", tuple(sorted(self.labels.items()))
            )

    def labels_dict(self) -> Dict[str, str]:
        return dict(self.labels)


@dataclasses.dataclass
class SloVerdict:
    """One spec's judgement over one window."""

    spec: SloSpec
    observed_ms: Optional[float]
    count: int
    ok: bool
    reason: str = ""

    def to_doc(self) -> Dict[str, object]:
        """The JSON shape bench artifacts publish (``trace_slo``)."""
        return {
            "name": self.spec.name,
            "quantile": self.spec.quantile,
            "threshold_ms": self.spec.threshold_ms,
            "observed_ms": (
                None if self.observed_ms is None
                else round(float(self.observed_ms), 3)
            ),
            "count": int(self.count),
            "ok": bool(self.ok),
        }


def evaluate_slos(registry, specs: Sequence[SloSpec]) -> List[SloVerdict]:
    out: List[SloVerdict] = []
    for spec in specs:
        bounds, cumulative, count = aggregate_buckets(
            registry, spec.family, spec.labels_dict()
        )
        observed = quantile_from_buckets(bounds, cumulative, spec.quantile)
        if observed is None or count < spec.min_count:
            out.append(SloVerdict(
                spec, observed, count, ok=False,
                reason=f"no data ({count} < {spec.min_count} observations)",
            ))
        elif observed <= spec.threshold_ms:
            out.append(SloVerdict(spec, observed, count, ok=True))
        else:
            out.append(SloVerdict(
                spec, observed, count, ok=False,
                reason=(
                    f"p{spec.quantile * 100:g} {observed:.3f} ms > "
                    f"threshold {spec.threshold_ms:g} ms"
                ),
            ))
    return out


def slos_pass(verdicts: Sequence[SloVerdict]) -> bool:
    return bool(verdicts) and all(v.ok for v in verdicts)


class SloWindow:
    """Delta-window quantiles for the operator surface.  Cumulative
    histograms only grow, so this snapshots per-series bucket counts
    and estimates quantiles over the difference since the previous
    ``advance()`` — the ``/healthz`` ``slo`` block calls it once per
    request, making "last window" = "since the last scrape".  Series
    with no new observations in the window report ``count: 0`` with
    null quantiles (visible, not invented)."""

    def __init__(self, families: Sequence[str],
                 quantiles: Sequence[float] = DEFAULT_QUANTILES):
        self.families = tuple(families)
        self.quantiles = tuple(quantiles)
        # (family, labelkey) -> cumulative counts at the last advance
        self._prev: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Tuple[int, ...]] = {}

    @staticmethod
    def _series_key(labels: Mapping[str, str]) -> str:
        return ",".join(f"{k}={v}" for k, v in sorted(labels.items())) or "all"

    def advance(self, registry) -> Dict[str, Dict[str, Dict[str, object]]]:
        """``{family: {"k=v,...": {"p50": ms|null, "p99": ms|null,
        "count": n}}}`` over the window since the previous call (first
        call: since boot)."""
        out: Dict[str, Dict[str, Dict[str, object]]] = {}
        for family in self.families:
            fam_out: Dict[str, Dict[str, object]] = {}
            for labels, bounds, cum, _sum, _count in registry.histogram_series(
                family
            ):
                key = (family, tuple(sorted(labels.items())))
                prev = self._prev.get(key, (0,) * len(cum))
                delta = [c - p for c, p in zip(cum, prev)]
                self._prev[key] = tuple(cum)
                entry: Dict[str, object] = {"count": delta[-1] if delta else 0}
                for q in self.quantiles:
                    est = quantile_from_buckets(bounds, delta, q)
                    entry[f"p{q * 100:g}"] = (
                        None if est is None else round(est, 3)
                    )
                fam_out[self._series_key(labels)] = entry
            if fam_out:
                out[family] = fam_out
        return out
