"""Flight recorder: the last K cycles, reconstructable after the fact.

A ring buffer of committed cycle records (obs/spans.py
``CycleSpans.to_record()`` dicts) plus the config knobs and snapshot
ids that produced them.  On a cycle error, a kernel demotion, or
SIGUSR1, the whole ring is dumped as ONE schema-validated JSON file
under the daemon's ``--state-dir`` (``<state-dir>/flight/``) — so a bad
cycle is diagnosable from the artifact, not from whatever happened to
be in the log buffer (the BENCH_r05 class: a regression that was only
caught because a run timed out).

The dump schema is enforced by :func:`validate_flight_dump` (stdlib
only, mirroring bench.py's ``_validate_artifact`` convention): a
malformed dump is suppressed with a log line rather than archived as a
diagnosis.  Writes are atomic (tmp + rename) so a crash mid-dump never
leaves a torn JSON file.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import signal
import time
from typing import Dict, List, Optional

from koordinator_tpu.obs.lockwitness import witness_rlock

logger = logging.getLogger(__name__)

FLIGHT_DUMP_VERSION = 1
DEFAULT_CAPACITY = 64
# dump-file churn guard: misbehaving triggers (a demotion storm) must
# not fill the state dir; oldest dumps are pruned past this count
MAX_DUMPS_KEPT = 32

_NUMBER = (int, float)


def _finite(v) -> bool:
    return (
        isinstance(v, _NUMBER)
        and not isinstance(v, bool)
        and v == v
        and v not in (float("inf"), float("-inf"))
    )


def _check_span(span, where: str, problems: List[str]) -> None:
    if not isinstance(span, dict):
        problems.append(f"{where} is not an object")
        return
    name = span.get("name")
    if not isinstance(name, str) or not name:
        problems.append(f"{where}.name must be a non-empty string")
    if not _finite(span.get("start_ms")) or span.get("start_ms") < 0:
        problems.append(f"{where}.start_ms must be a finite number >= 0")
    dur = span.get("dur_ms")
    if dur is not None and (not _finite(dur) or dur < 0):
        problems.append(
            f"{where}.dur_ms must be null or a finite number >= 0"
        )


def _check_cycle(cyc, where: str, problems: List[str]) -> None:
    if not isinstance(cyc, dict):
        problems.append(f"{where} is not an object")
        return
    cid = cyc.get("cycle_id")
    if not isinstance(cid, str) or not cid:
        problems.append(f"{where}.cycle_id must be a non-empty string")
    sid = cyc.get("snapshot_id")
    if sid is not None and not isinstance(sid, str):
        problems.append(f"{where}.snapshot_id must be null or a string")
    # distributed-trace correlation (ISSUE 14): the trace id of the
    # request the cycle served, when the client sent one — nullable,
    # never any other type
    tid = cyc.get("trace_id")
    if tid is not None and not isinstance(tid, str):
        problems.append(f"{where}.trace_id must be null or a string")
    if not _finite(cyc.get("started_unix")):
        problems.append(f"{where}.started_unix must be a finite number")
    err = cyc.get("error")
    if err is not None and not isinstance(err, str):
        problems.append(f"{where}.error must be null or a string")
    spans = cyc.get("spans")
    if not isinstance(spans, list):
        problems.append(f"{where}.spans must be a list")
    else:
        for i, span in enumerate(spans):
            _check_span(span, f"{where}.spans[{i}]", problems)
    notes = cyc.get("notes")
    if not isinstance(notes, dict):
        problems.append(f"{where}.notes must be an object")
    else:
        for k, v in notes.items():
            if v is not None and not isinstance(v, (str, int, float, bool)):
                problems.append(
                    f"{where}.notes[{k!r}] must be a JSON scalar or null"
                )


def validate_flight_dump(doc) -> List[str]:
    """Schema over a flight dump document; returns problems (empty =
    valid).  The writer validates before writing; tests validate the
    written file — both through this ONE function."""
    if not isinstance(doc, dict):
        return ["dump is not a JSON object"]
    problems: List[str] = []
    if doc.get("version") != FLIGHT_DUMP_VERSION:
        problems.append(f"version must be {FLIGHT_DUMP_VERSION}")
    reason = doc.get("reason")
    if not isinstance(reason, str) or not reason:
        problems.append("reason must be a non-empty string")
    if not _finite(doc.get("dumped_at_unix")):
        problems.append("dumped_at_unix must be a finite number")
    if not isinstance(doc.get("config"), dict):
        problems.append("config must be an object")
    dropped = doc.get("dropped_cycles")
    if not isinstance(dropped, int) or isinstance(dropped, bool) or dropped < 0:
        problems.append("dropped_cycles must be an int >= 0")
    extra = doc.get("extra")
    if extra is not None:
        if not isinstance(extra, dict):
            problems.append("extra must be an object")
        else:
            for k, v in extra.items():
                if v is not None and not isinstance(v, (str, int, float, bool)):
                    problems.append(
                        f"extra[{k!r}] must be a JSON scalar or null"
                    )
    cycles = doc.get("cycles")
    if not isinstance(cycles, list):
        problems.append("cycles must be a list")
    else:
        for i, cyc in enumerate(cycles):
            _check_cycle(cyc, f"cycles[{i}]", problems)
    return problems


class FlightRecorder:
    """Ring of the last ``capacity`` cycle records; ``dump()`` persists
    them.  Thread-safe: the SIGUSR1 handler and the serve threads race
    on the ring."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        state_dir: Optional[str] = None,
        config: Optional[Dict[str, object]] = None,
        wall_clock=time.time,
    ):
        self.capacity = int(capacity)
        self.state_dir = state_dir
        # config knobs frozen into every dump (CycleConfig wave/top_m,
        # strategy names — whatever the owner deems reconstruction-worthy)
        self.config: Dict[str, object] = dict(config or {})
        self._wall_clock = wall_clock
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        # RLock, not Lock: the SIGUSR1 handler runs on the main thread
        # between bytecodes and may interrupt record() while it holds
        # the lock — a non-reentrant lock would deadlock the dump
        self._lock = witness_rlock("obs.flight.FlightRecorder._lock")
        self._dump_seq = 0
        self.dropped = 0  # cycles that fell off the ring, for the dump
        # per-reason dump rate limit: a flood of one trigger (a client
        # looping bad frames, a demotion storm) must not stall serving
        # on disk I/O per event NOR churn real post-mortem dumps out of
        # the pruned directory.  sigusr1 is exempt — the operator asked.
        self.min_dump_interval_s = 10.0
        self._last_dump: Dict[str, float] = {}
        self.dumps_suppressed = 0

    def record(self, cycle_record: Dict[str, object]) -> None:
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(cycle_record)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self) -> List[Dict[str, object]]:
        """Oldest-first copy of the ring (the dump body)."""
        with self._lock:
            return list(self._ring)

    def document(
        self, reason: str, extra: Optional[Dict[str, object]] = None
    ) -> Dict[str, object]:
        doc = {
            "version": FLIGHT_DUMP_VERSION,
            "reason": reason,
            "dumped_at_unix": self._wall_clock(),
            "config": dict(self.config),
            "dropped_cycles": self.dropped,
            "cycles": self.snapshot(),
        }
        if extra:
            doc["extra"] = dict(extra)
        return doc

    def dump(
        self, reason: str, extra: Optional[Dict[str, object]] = None
    ) -> Optional[str]:
        """Write the ring under ``<state_dir>/flight/``; returns the
        path, or None when no state dir is configured, the document
        fails its own schema, or the write fails (a diagnostics dump
        must never take the serving path down with it).  ``extra``
        carries trigger-specific scalars (e.g. the demoted bucket)."""
        if not self.state_dir:
            return None
        if reason != "sigusr1":
            now = time.monotonic()
            with self._lock:
                last = self._last_dump.get(reason)
                if last is not None and now - last < self.min_dump_interval_s:
                    self.dumps_suppressed += 1
                    return None
        doc = self.document(reason, extra=extra)
        problems = validate_flight_dump(doc)
        if problems:
            logger.error(
                "flight dump (%s) failed schema validation, suppressed: %s",
                reason, "; ".join(problems),
            )
            return None
        flight_dir = os.path.join(self.state_dir, "flight")
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        name = f"koord-flight-{int(doc['dumped_at_unix'])}-{seq:04d}-{reason}.json"
        path = os.path.join(flight_dir, name)
        try:
            os.makedirs(flight_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
            self._prune(flight_dir)
        except OSError as exc:
            logger.error("flight dump (%s) write failed: %s", reason, exc)
            return None
        # stamp the rate limit only AFTER a successful write: a failed
        # attempt (ENOSPC, permissions) must not close the post-mortem
        # window for the retry that would have succeeded
        with self._lock:
            self._last_dump[reason] = time.monotonic()
        return path

    @staticmethod
    def _prune(flight_dir: str) -> None:
        try:
            dumps = sorted(
                f for f in os.listdir(flight_dir)
                if f.startswith("koord-flight-") and f.endswith(".json")
            )
            for stale in dumps[:-MAX_DUMPS_KEPT]:
                os.unlink(os.path.join(flight_dir, stale))
        except OSError:
            logger.warning(
                "flight dump pruning failed in %s", flight_dir, exc_info=True
            )

    def install_sigusr1(self) -> bool:
        """Dump on SIGUSR1 (operator: ``kill -USR1 <daemon pid>``).
        Returns False off the main thread (signal.signal's constraint) —
        callers treat that as "no signal trigger", not an error."""
        def _on_sigusr1(signum, frame):
            self.dump("sigusr1")

        try:
            signal.signal(signal.SIGUSR1, _on_sigusr1)
        except ValueError:
            return False
        return True
