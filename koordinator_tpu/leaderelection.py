"""Lease-based leader election for the central components.

The reference's scheduler, manager and descheduler are all
leader-elected singletons via client-go resource locks (reference
``cmd/koord-scheduler/app/server.go:225``,
``cmd/koord-manager/main.go:116-127``,
``cmd/koord-descheduler/app/server.go:182-200``).  Without an apiserver,
the shared lock here is a LEASE FILE on a filesystem all replicas see
(the deployment's PVC/configdir), with client-go's Lease semantics:

* ``lease_duration`` — how long a lease is valid after its last renewal;
  followers may claim it only after expiry (default 15s upstream).
* ``renew_deadline`` — a leader that cannot renew within this gives up
  leadership (default 10s).
* ``retry_period`` — acquire/renew polling interval (default 2s).

Writes are atomic (tempfile + rename) and guarded by a same-host flock,
and every renew re-reads the file and verifies the holder: a leader that
lost its lease (clock pause, file takeover) steps down instead of
split-braining — the same fencing the client-go leaderelector does via
resourceVersion-checked updates.
"""

from __future__ import annotations

import dataclasses
import fcntl
import json
import os
import tempfile
import threading
import time
from typing import Callable, Optional

DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 2.0


@dataclasses.dataclass
class LeaseRecord:
    """client-go LeaderElectionRecord analog."""

    holder: str
    acquire_time: float
    renew_time: float
    lease_duration: float
    leader_transitions: int = 0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, text: str) -> "LeaseRecord":
        return cls(**json.loads(text))


class LeaderElector:
    def __init__(
        self,
        lease_path: str,
        identity: str,
        lease_duration: float = DEFAULT_LEASE_DURATION,
        renew_deadline: float = DEFAULT_RENEW_DEADLINE,
        retry_period: float = DEFAULT_RETRY_PERIOD,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        on_new_leader: Optional[Callable[[str], None]] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.lease_path = lease_path
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.on_new_leader = on_new_leader
        self.clock = clock
        self.is_leader = False
        self._observed_leader: Optional[str] = None
        self._stop = threading.Event()
        os.makedirs(os.path.dirname(lease_path) or ".", exist_ok=True)

    # -- lease file primitives (atomic read/modify/write under flock) --
    def _read(self) -> Optional[LeaseRecord]:
        try:
            with open(self.lease_path) as fh:
                return LeaseRecord.from_json(fh.read())
        except (OSError, ValueError, TypeError):
            return None

    def _write(self, record: LeaseRecord) -> None:
        d = os.path.dirname(self.lease_path) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".lease-")
        try:
            with os.fdopen(fd, "w") as fh:
                fh.write(record.to_json())
            os.replace(tmp, self.lease_path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _with_lock(self, fn):
        lock_path = self.lease_path + ".lock"
        with open(lock_path, "a+") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                return fn()
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)

    # -- election steps --
    def try_acquire_or_renew(self, now: Optional[float] = None) -> bool:
        """One election step (client-go tryAcquireOrRenew): returns whether
        this identity holds the lease afterwards."""
        now = self.clock() if now is None else now

        def step() -> bool:
            record = self._read()
            if record is not None and record.holder != self.identity:
                expired = now >= record.renew_time + record.lease_duration
                if not expired:
                    self._observe(record.holder)
                    return False
                transitions = record.leader_transitions + 1
            else:
                transitions = record.leader_transitions if record else 0
            acquire = (
                record.acquire_time
                if record and record.holder == self.identity
                else now
            )
            self._write(
                LeaseRecord(
                    holder=self.identity,
                    acquire_time=acquire,
                    renew_time=now,
                    lease_duration=self.lease_duration,
                    leader_transitions=transitions,
                )
            )
            self._observe(self.identity)
            return True

        return self._with_lock(step)

    def _observe(self, leader: str):
        if leader != self._observed_leader:
            self._observed_leader = leader
            if self.on_new_leader:
                self.on_new_leader(leader)

    def release(self):
        """Voluntary step-down: zero the lease so followers claim it
        immediately (client-go releaseOnCancel)."""

        def step():
            record = self._read()
            if record and record.holder == self.identity:
                record.renew_time = 0.0
                record.lease_duration = 0.0
                self._write(record)

        self._with_lock(step)
        if self.is_leader:
            self.is_leader = False
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def stop(self):
        self._stop.set()

    def run(self, max_iterations: Optional[int] = None, sleep=None):
        """Blocking election loop (client-go LeaderElector.Run): acquire,
        then renew every retry_period; step down when the renew deadline
        passes or another holder takes the lease."""
        sleep = sleep or (lambda s: self._stop.wait(s))
        iterations = 0
        last_renew = None
        while not self._stop.is_set():
            if max_iterations is not None and iterations >= max_iterations:
                break
            iterations += 1
            now = self.clock()
            try:
                got = self.try_acquire_or_renew(now)
                renew_error = False
            except OSError:
                # lease storage briefly unwritable: NOT a lost election,
                # but not a renewal either — the deadline below decides
                got = False
                renew_error = True
            if got:
                last_renew = now
                if not self.is_leader:
                    self.is_leader = True
                    if self.on_started_leading:
                        self.on_started_leading()
            elif self.is_leader:
                observed_other = not renew_error
                past_deadline = (
                    last_renew is not None
                    and now - last_renew >= self.renew_deadline
                )
                if observed_other or past_deadline:
                    # fencing: the lease is observably held by another
                    # identity, or we failed to renew past renew_deadline
                    # ("a leader that cannot renew gives up leadership") —
                    # step down so a split brain cannot form
                    self.is_leader = False
                    if self.on_stopped_leading:
                        self.on_stopped_leading()
            sleep(self.retry_period)
        # releaseOnCancel: relinquish on shutdown; a bounded run (test/tool
        # driving discrete steps) keeps the lease for the next call
        if self._stop.is_set() and self.is_leader:
            self.release()
