"""koord-manager app/server: leader-elected controller manager + webhook.

Mirrors ``cmd/koord-manager/main.go``: a controller-runtime manager with
leader election (:116-127) running the slo-controller reconcilers
(nodemetric, noderesource, nodeslo — registered in
``options/controllers.go:34-39``), the quota-profile controller, and the
webhook server (``pkg/webhook/server.go:80``), all as ticking reconcile
loops gated on leadership.  State flows through a pluggable ``Cluster``
view (nodes/pods/NodeMetrics/configmaps) the way the reference flows
through the apiserver.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Mapping, Optional

from koordinator_tpu.httpserving import (
    HTTPLifecycle,
    format_thread_stacks,
    reply_text,
)
from koordinator_tpu.leaderelection import LeaderElector
from koordinator_tpu.manager.nodemetric import reconcile_nodemetrics
from koordinator_tpu.manager.noderesource import calculate_batch_resource
from koordinator_tpu.manager.nodeslo import render_nodeslo
from koordinator_tpu.manager.quota_profile import reconcile_profiles
from koordinator_tpu.manager.sloconfig import ColocationStrategy


@dataclasses.dataclass
class ClusterView:
    """The manager's world state (the apiserver stand-in): callers supply
    getters; reconcilers write their outputs back through the setters."""

    nodes_fn: Callable[[], List[Mapping]] = lambda: []
    pods_fn: Callable[[], List[Mapping]] = lambda: []
    node_metrics_fn: Callable[[], Dict[str, Mapping]] = dict
    strategy_fn: Callable[[], ColocationStrategy] = ColocationStrategy
    quota_profiles_fn: Callable[[], List[Mapping]] = lambda: []
    # outputs
    nodemetric_specs: Dict[str, Optional[Dict]] = dataclasses.field(
        default_factory=dict
    )
    node_extended_resources: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict
    )
    nodeslos: Dict[str, Dict] = dataclasses.field(default_factory=dict)
    quotas: Dict[str, Dict] = dataclasses.field(default_factory=dict)


class ManagerServer:
    """Leader-elected reconcile loops + healthz (+ optional webhook)."""

    def __init__(
        self,
        cluster: ClusterView,
        *,
        lease_path: str = "/tmp/koord-manager/leader.lease",
        identity: Optional[str] = None,
        resync_seconds: float = 60.0,
        http_host: str = "127.0.0.1",
        http_port: int = 0,
        webhook_cert_dir: Optional[str] = None,
    ):
        self.cluster = cluster
        self.resync_seconds = resync_seconds
        self.elector = LeaderElector(
            lease_path, identity or f"{socket.gethostname()}-{os.getpid()}"
        )
        self.reconciles = 0
        self.last_error: Optional[str] = None
        self.webhook = None
        if webhook_cert_dir:
            from koordinator_tpu.manager.webhook_server import WebhookServer

            self.webhook = WebhookServer(webhook_cert_dir)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/debug/stacks":
                    reply_text(self, format_thread_stacks())
                    return
                doc = {
                    "ok": outer.last_error is None,
                    "leader": outer.elector.is_leader,
                    "reconciles": outer.reconciles,
                    "last_error": outer.last_error,
                }
                data = json.dumps(doc).encode()
                self.send_response(200 if self.path == "/healthz" else 404)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((http_host, http_port), Handler)
        self._http = HTTPLifecycle(self._httpd)

    @property
    def http_port(self) -> int:
        return self._httpd.server_address[1]

    # -- one reconcile pass over every controller ---------------------------
    def reconcile_once(self) -> None:
        c = self.cluster
        nodes = c.nodes_fn()
        pods = c.pods_fn()
        metrics = c.node_metrics_fn()
        strategy = c.strategy_fn()

        # nodemetric controller (slo-controller/nodemetric):
        # desired NodeMetric spec per node, None = GC
        c.nodemetric_specs = reconcile_nodemetrics(nodes, metrics, strategy)

        # noderesource controller (slo-controller/noderesource):
        # batch/mid overcommit -> node extended resources
        now = time.time()
        by_node: Dict[str, List[Mapping]] = {}
        for p in pods:
            if p.get("node"):
                by_node.setdefault(p["node"], []).append(p)
        c.node_extended_resources = {}
        for n in nodes:
            name = n.get("name", "")
            nm = metrics.get(name, {})
            result = calculate_batch_resource(
                strategy,
                n.get("allocatable", {}),
                None,
                n.get("kubelet_reserved"),
                nm.get("system_usage", {}),
                by_node.get(name, []),
                nm.get("pod_metrics", {}),
                metric_update_time=nm.get("update_time"),
                now=now,
            )
            c.node_extended_resources[name] = result.as_extended_resources()

        # nodeslo controller (slo-controller/nodeslo): per-node NodeSLO
        c.nodeslos = {
            n.get("name", ""): render_nodeslo(n.get("labels", {}) or {})
            for n in nodes
        }

        # quota-profile controller (pkg/quota-controller/profile)
        c.quotas = reconcile_profiles(c.quota_profiles_fn(), nodes)

        # webhook cert rotation tick rides the reconcile loop
        if self.webhook is not None:
            self.webhook.rotate_if_needed()
        self.reconciles += 1

    # -- loops --------------------------------------------------------------
    def _loop(self):
        while not self._stop.is_set():
            if self.elector.is_leader:
                try:
                    self.reconcile_once()
                    self.last_error = None
                except Exception as exc:  # requeue like controller-runtime
                    self.last_error = str(exc)
                self._stop.wait(self.resync_seconds)
            else:
                self._stop.wait(self.elector.retry_period)

    def start(self) -> "ManagerServer":
        if self.webhook is not None:
            self.webhook.start()
        for target in (
            lambda: self.elector.run(),
            self._loop,
        ):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        self._http.start()
        return self

    def stop(self):
        self._stop.set()
        self.elector.stop()
        self._http.stop()
        if self.webhook is not None:
            self.webhook.stop()
        for t in self._threads[:2]:
            t.join(timeout=5)
