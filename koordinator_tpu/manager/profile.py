"""ClusterColocationProfile pod mutation (the mutating-webhook analog).

Reference: ``pkg/webhook/pod/mutating/cluster_colocation_profile.go``
(``doMutateByColocationProfile`` :157, ``mutatePodResourceSpec`` :221,
``replaceAndEraseResource`` :247): a matching profile stamps labels /
annotations / scheduler name / QoS / priority onto the pod, and non-prod
pods get their native cpu/memory requests translated to the extended
batch/mid resources so the scheduler fits them against overcommitted
capacity.

Pods are plain dicts (same shape the harness generators produce).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Mapping, Optional, Sequence

from koordinator_tpu.manager.noderesource import (
    PRIORITY_BATCH,
    PRIORITY_MID,
    priority_class_of,
)
from koordinator_tpu.model import resources as res

LABEL_POD_QOS = "koordinator.sh/qosClass"
LABEL_POD_PRIORITY = "koordinator.sh/priority"

# reference ``apis/extension/resource.go ResourceNameMap``: which extended
# resource a native cpu/memory request becomes, per priority class.
RESOURCE_NAME_MAP = {
    PRIORITY_BATCH: {res.CPU: res.BATCH_CPU, res.MEMORY: res.BATCH_MEMORY},
    PRIORITY_MID: {res.CPU: res.MID_CPU, res.MEMORY: res.MID_MEMORY},
}


def selector_matches(selector: Optional[Mapping[str, Any]], labels: Mapping[str, str]) -> bool:
    """matchLabels + matchExpressions(In/NotIn/Exists/DoesNotExist)."""
    if selector is None:
        return True
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or ():
        key, op = expr["key"], expr["operator"]
        values = expr.get("values", ())
        if op == "In" and labels.get(key) not in values:
            return False
        if op == "NotIn" and labels.get(key) in values:
            return False
        if op == "Exists" and key not in labels:
            return False
        if op == "DoesNotExist" and key in labels:
            return False
    return True


def apply_profile(pod: Mapping[str, Any], profile: Mapping[str, Any]) -> Dict[str, Any]:
    """Return a mutated copy of ``pod`` with the profile applied
    (reference ``doMutateByColocationProfile`` :157-218)."""
    out = copy.deepcopy(dict(pod))
    spec = profile.get("spec", profile)
    labels = dict(out.get("labels", {}))
    annotations = dict(out.get("annotations", {}))
    labels.update(spec.get("labels", {}))
    annotations.update(spec.get("annotations", {}))
    if spec.get("schedulerName"):
        out["scheduler_name"] = spec["schedulerName"]
    if spec.get("qosClass"):
        labels[LABEL_POD_QOS] = spec["qosClass"]
        out["qos"] = spec["qosClass"]
    if spec.get("priorityClassName"):
        out["priority_class"] = spec["priorityClassName"]
        if "priorityClassValue" in spec:
            out["priority"] = spec["priorityClassValue"]
    if spec.get("koordinatorPriority") is not None:
        labels[LABEL_POD_PRIORITY] = str(spec["koordinatorPriority"])
    out["labels"] = labels
    out["annotations"] = annotations
    return out


def mutate_pod_resources(pod: Mapping[str, Any]) -> Dict[str, Any]:
    """Translate native cpu/memory requests+limits to extended batch/mid
    resources for batch/mid pods (reference ``mutatePodResourceSpec``
    :221-244; cpu becomes integer *milli* quantities, ``:255-258``).
    Prod/none — and free, which has no ResourceNameMap entry
    (``apis/extension/resource.go:40``) — pass through unchanged."""
    pc = priority_class_of(pod)
    name_map = RESOURCE_NAME_MAP.get(pc)
    if name_map is None:
        return dict(pod)
    out = copy.deepcopy(dict(pod))
    for section in ("requests", "limits"):
        rl = out.get(section)
        if not rl:
            continue
        for native, extended in name_map.items():
            if native in rl:
                qty = res.parse_quantity(rl.pop(native), native)
                # axis units (milli / MiB) must round-trip through a
                # second parse when the mutated pod is encoded again
                rl[extended] = res.format_quantity(qty, extended)
    return out


def mutate_by_profiles(
    pod: Mapping[str, Any],
    profiles: Sequence[Mapping[str, Any]],
    namespace_labels: Optional[Mapping[str, str]] = None,
) -> Dict[str, Any]:
    """Apply every matching profile in name order then the resource
    translation, mirroring the webhook handler's flow."""
    out = dict(pod)
    pod_labels = out.get("labels", {})
    for profile in sorted(profiles, key=lambda p: p.get("name", "")):
        spec = profile.get("spec", profile)
        if not selector_matches(spec.get("namespaceSelector"), namespace_labels or {}):
            continue
        if not selector_matches(spec.get("selector"), pod_labels):
            continue
        out = apply_profile(out, profile)
        pod_labels = out.get("labels", {})
    return mutate_pod_resources(out)
