"""NodeSLO rendering: per-node SLO spec from cluster strategy ConfigMaps.

Reference: ``pkg/slo-controller/nodeslo`` (``nodeslo_controller.go:128
Reconcile`` renders the merged resource-threshold / resource-qos /
cpu-burst / system strategies into each node's NodeSLO CR) with defaults
from ``pkg/util/sloconfig/nodeslo_config.go``.

Specs are plain nested dicts (the CR's JSON form); merging is deep
field-wise with the node-selector override winning, like the reference's
``mergeNodeSLOSpec``.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Mapping, Optional, Sequence

from koordinator_tpu.manager.sloconfig import node_selector_matches

QOS_CLASSES = ("LSR", "LS", "BE", "SYSTEM")


def default_resource_threshold_strategy() -> Dict[str, Any]:
    """reference ``sloconfig.DefaultResourceThresholdStrategy`` (:51-59)."""
    return {
        "enable": False,
        "cpuSuppressThresholdPercent": 65,
        "cpuSuppressPolicy": "cpuset",
        "memoryEvictThresholdPercent": 70,
        "cpuEvictPolicy": "evictByRealLimit",
    }


def default_cpu_qos(qos: str) -> Optional[Dict[str, Any]]:
    """Group-identity (bvt) values per QoS (reference
    ``sloconfig.DefaultCPUQOS``: LSR/LS=2, BE=-1, SYSTEM=0)."""
    return {
        "LSR": {"groupIdentity": 2},
        "LS": {"groupIdentity": 2},
        "BE": {"groupIdentity": -1},
        "SYSTEM": {"groupIdentity": 0},
    }.get(qos)


def default_resctrl_qos(qos: str) -> Optional[Dict[str, Any]]:
    """L3 CAT / MBA percentages per QoS (reference
    ``sloconfig.DefaultResctrlQOS``: BE capped to 30% of LLC ways)."""
    base = {"catRangeStartPercent": 0, "catRangeEndPercent": 100, "mbaPercent": 100}
    if qos == "BE":
        return {**base, "catRangeEndPercent": 30}
    if qos in QOS_CLASSES:
        return dict(base)
    return None


def default_memory_qos(qos: str) -> Optional[Dict[str, Any]]:
    """memcg qos knobs per QoS (reference ``sloconfig.DefaultMemoryQOS``:
    async-reclaim watermarks on, all limits off; BE gets a positive
    wmark_min_adj, LSR/LS a negative one)."""
    if qos not in QOS_CLASSES:
        return None
    wmark_min_adj = {"LSR": -25, "LS": -25, "BE": 50, "SYSTEM": 0}[qos]
    wmark_ratio = 0 if qos == "SYSTEM" else 95
    wmark_scale = 50 if qos == "SYSTEM" else 20
    return {
        "minLimitPercent": 0,
        "lowLimitPercent": 0,
        "throttlingPercent": 0,
        "wmarkRatio": wmark_ratio,
        "wmarkScalePermill": wmark_scale,
        "wmarkMinAdj": wmark_min_adj,
        "priorityEnable": 0,
        "priority": 0,
        "oomKillGroup": 0,
    }


def default_resource_qos_strategy() -> Dict[str, Any]:
    """reference ``sloconfig.DefaultResourceQOSStrategy``: per-class cpu /
    resctrl / memory QoS configs, all gated off by default."""
    out: Dict[str, Any] = {}
    for qos in QOS_CLASSES:
        out[f"{qos.lower()}Class"] = {
            "cpuQOS": {"enable": False, **(default_cpu_qos(qos) or {})},
            "resctrlQOS": {"enable": False, **(default_resctrl_qos(qos) or {})},
            "memoryQOS": {"enable": False, **(default_memory_qos(qos) or {})},
        }
    return out


def default_cpu_burst_strategy() -> Dict[str, Any]:
    """reference ``sloconfig.DefaultCPUBurstStrategy``."""
    return {
        "policy": "none",
        "cpuBurstPercent": 1000,
        "cfsQuotaBurstPercent": 300,
        "cfsQuotaBurstPeriodSeconds": -1,
        "sharePoolThresholdPercent": 50,
    }


def default_system_strategy() -> Dict[str, Any]:
    """reference ``sloconfig.DefaultSystemStrategy``."""
    return {
        "minFreeKbytesFactor": 100,
        "watermarkScaleFactor": 150,
        "memcgReapBackGround": 0,
    }


def default_nodeslo_spec() -> Dict[str, Any]:
    return {
        "resourceUsedThresholdWithBE": default_resource_threshold_strategy(),
        "resourceQOSStrategy": default_resource_qos_strategy(),
        "cpuBurstStrategy": default_cpu_burst_strategy(),
        "systemStrategy": default_system_strategy(),
    }


def deep_merge(base: Mapping[str, Any], override: Mapping[str, Any]) -> Dict[str, Any]:
    """Field-wise deep merge; override's non-None leaves win (the
    reference merges via JSON merge-patch of the ConfigMap strategy onto
    defaults)."""
    out: Dict[str, Any] = copy.deepcopy(dict(base))
    for k, v in override.items():
        if v is None:
            continue
        if isinstance(v, Mapping) and isinstance(out.get(k), Mapping):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out


def render_nodeslo(
    node_labels: Mapping[str, str],
    cluster_strategies: Optional[Mapping[str, Any]] = None,
    node_strategies: Sequence[Mapping[str, Any]] = (),
) -> Dict[str, Any]:
    """Render one node's NodeSLO spec: defaults <- cluster ConfigMap
    strategies <- matching node-selector overrides (reference
    ``nodeslo/resource_strategy.go`` get*Spec helpers)."""
    spec = default_nodeslo_spec()
    if cluster_strategies:
        spec = deep_merge(spec, cluster_strategies)
    for cfg in node_strategies:
        selector = cfg.get("nodeSelector", {}).get("matchLabels")
        if node_selector_matches(selector, node_labels):
            spec = deep_merge(spec, cfg.get("strategies", {}))
    return spec
