"""Validating admission: pod QoS/priority/resource rules + quota tree guard.

Reference: ``pkg/webhook/pod/validating/cluster_colocation_profile.go:35``
(required BE QoS with batch resources, immutable QoS/priority, forbidden
QoS+priorityClass combos, LSR/LSE integer-CPU requirement) and
``pkg/webhook/elasticquota`` (quota tree topology checks: parent exists,
min <= max, children min sum <= parent min).
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.model import resources as res

LABEL_POD_QOS = "koordinator.sh/qosClass"
LABEL_POD_PRIORITY = "koordinator.sh/priority"

# forbidden QoS / priority-class combinations
# (cluster_colocation_profile.go:58-59)
_FORBIDDEN = {
    "BE": {"", "koord-prod"},  # BE + None/Prod forbidden
    "LSR": {"", "koord-mid", "koord-batch", "koord-free"},
    "LSE": {"", "koord-mid", "koord-batch", "koord-free"},
}


def _safe_parse(rl: Mapping, errs: List[str], where: str) -> Dict[str, int]:
    """Parse a resource list; malformed quantities become admission error
    strings (the reference denies with a field error, never crashes)."""
    out: Dict[str, int] = {}
    for name, qty in (rl or {}).items():
        try:
            out[name] = res.parse_quantity(qty, name)
        except ValueError:
            errs.append(f"{where}[{name}]: unparseable quantity {qty!r}")
    return out


def validate_pod(
    pod: Mapping[str, Any], old_pod: Optional[Mapping[str, Any]] = None
) -> List[str]:
    """Returns error strings; empty = admitted."""
    errs: List[str] = []
    labels = pod.get("labels") or {}
    qos = labels.get(LABEL_POD_QOS, pod.get("qos", ""))
    priority_class = pod.get("priority_class", "") or ""
    requests = _safe_parse(pod.get("requests") or {}, errs, "requests")

    if old_pod is not None:
        old_labels = old_pod.get("labels") or {}
        if old_labels.get(LABEL_POD_QOS, old_pod.get("qos", "")) != qos:
            errs.append(f"labels.{LABEL_POD_QOS}: field is immutable")
        if (old_pod.get("priority_class") or "") != priority_class:
            errs.append("spec.priority: field is immutable")
        if old_labels.get(LABEL_POD_PRIORITY) != labels.get(LABEL_POD_PRIORITY):
            errs.append(f"labels.{LABEL_POD_PRIORITY}: field is immutable")

    # batch resources require QoS BE (validateRequiredQoSClass)
    if (
        requests.get(res.BATCH_CPU, 0) or requests.get(res.BATCH_MEMORY, 0)
    ) and qos != "BE":
        errs.append(
            f"labels.{LABEL_POD_QOS}: must specify koordinator QoS BE with "
            "koordinator colocation resources"
        )

    # forbidden combos (forbidSpecialQoSClassAndPriorityClass)
    if priority_class in _FORBIDDEN.get(qos, ()):  # "" = PriorityNone
        errs.append(
            f"{LABEL_POD_QOS}={qos} and priorityClass={priority_class or 'none'} "
            "cannot be used in combination"
        )

    # LSR/LSE need integer CPU (validateResources)
    if qos in ("LSR", "LSE"):
        cpu_milli = requests.get(res.CPU, 0)
        if cpu_milli == 0:
            errs.append("LSR Pod must declare the requested CPUs")
        elif cpu_milli % 1000 != 0:
            errs.append("the requested CPUs of LSR Pod must be integer")
    return errs


def validate_quota_tree(quotas: Sequence[Mapping[str, Any]]) -> List[str]:
    """ElasticQuota topology guard (pkg/webhook/elasticquota): every
    parent exists, min <= max per dimension, and each parent's min covers
    the sum of its children's min."""
    errs: List[str] = []
    by_name = {q["name"]: q for q in quotas}

    def vec(m, where):
        return _safe_parse(m or {}, errs, where)

    children: Dict[str, List[str]] = {}
    for q in quotas:
        name = q["name"]
        parent = q.get("parent")
        if parent:
            if parent not in by_name:
                errs.append(f"{name}: parent quota {parent} does not exist")
            else:
                children.setdefault(parent, []).append(name)
        mn, mx = vec(q.get("min"), f"{name}.min"), vec(q.get("max"), f"{name}.max")
        for dim, v in mn.items():
            if dim in mx and v > mx[dim]:
                errs.append(f"{name}: min[{dim}] {v} exceeds max {mx[dim]}")

    for parent, kids in children.items():
        pmin = vec(by_name[parent].get("min"), f"{parent}.min") if parent in by_name else {}
        total: Dict[str, int] = {}
        for kid in kids:
            for dim, v in vec(by_name[kid].get("min"), f"{kid}.min").items():
                total[dim] = total.get(dim, 0) + v
        for dim, v in total.items():
            if v > pmin.get(dim, 0):
                errs.append(
                    f"{parent}: children min sum {v} exceeds parent min "
                    f"{pmin.get(dim, 0)} for {dim}"
                )
    return errs


def validate_node_colocation(node: Mapping[str, Any]) -> List[str]:
    """Node validating webhook (pkg/webhook/node): batch allocatable must
    not exceed node capacity."""
    errs: List[str] = []
    cap = _safe_parse(node.get("capacity") or {}, errs, "capacity")
    alloc = _safe_parse(node.get("allocatable") or {}, errs, "allocatable")
    pairs = [(res.BATCH_CPU, res.CPU), (res.BATCH_MEMORY, res.MEMORY)]
    for batch_name, native_name in pairs:
        b = alloc.get(batch_name, 0)
        c = cap.get(native_name, 0)
        if b and c and b > c:
            errs.append(
                f"{batch_name} allocatable {b} exceeds node {native_name} "
                f"capacity {c}"
            )
    return errs
