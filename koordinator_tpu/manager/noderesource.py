"""Batch / Mid overcommit calculation (the noderesource controller).

Reference: ``pkg/slo-controller/noderesource`` — BatchResource plugin
(``plugins/batchresource/plugin.go:136 Calculate``, formula helpers
``util.go:38-70``), MidResource plugin (``plugins/midresource/plugin.go``),
degrade-on-stale-metric (``batchresource/plugin.go:370-388``), and the
sync-needed diff check (``util.IsResourceDiff``).

The math runs on dense ``[cpu_milli, memory_mib]`` numpy vectors —
exact integer arithmetic, matching the reference's resource.Quantity
accounting.  For whole-cluster reconciliation, ``batch_allocatable_batch``
computes every node at once as one vectorized program (the TPU-friendly
form the per-node Go loop cannot take).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.manager.sloconfig import (
    CALCULATE_BY_POD_REQUEST,
    ColocationStrategy,
)
from koordinator_tpu.model import resources as res

# dense axis for this module: [cpu (milli), memory (MiB)]
CPU, MEM = 0, 1

PRIORITY_PROD = "koord-prod"
PRIORITY_MID = "koord-mid"
PRIORITY_BATCH = "koord-batch"
PRIORITY_FREE = "koord-free"

# Priority value bands (reference apis/extension/priority.go:38-49).
PRIORITY_BANDS = {
    PRIORITY_PROD: (9000, 9999),
    PRIORITY_MID: (7000, 7999),
    PRIORITY_BATCH: (5000, 5999),
    PRIORITY_FREE: (3000, 3999),
}


def priority_class_of(pod: Mapping) -> str:
    """reference ``extension.GetPodPriorityClassWithDefault``: explicit
    priority-class label wins, else derive from the numeric priority band,
    else prod (HP) by default."""
    pc = pod.get("priority_class", "")
    if pc in PRIORITY_BANDS:
        return pc
    prio = pod.get("priority")
    if prio is not None:
        for name, (lo, hi) in PRIORITY_BANDS.items():
            if lo <= int(prio) <= hi:
                return name
    return PRIORITY_PROD


def _vec(rl: Optional[Mapping[str, object]]) -> np.ndarray:
    """[cpu_milli, mem_mib] int64 vector from a resource dict."""
    out = np.zeros(2, dtype=np.int64)
    if rl:
        v = res.resource_vector(rl)
        out[CPU] = v[res.RESOURCE_INDEX[res.CPU]]
        out[MEM] = v[res.RESOURCE_INDEX[res.MEMORY]]
    return out


@dataclasses.dataclass
class BatchResourceResult:
    batch_cpu_milli: int
    batch_memory_mib: int
    degraded: bool
    message: str

    def as_extended_resources(self) -> Dict[str, int]:
        if self.degraded:
            return {}
        return {
            res.BATCH_CPU: self.batch_cpu_milli,
            res.BATCH_MEMORY: res.format_quantity(
                self.batch_memory_mib, res.BATCH_MEMORY
            ),
        }


def is_degrade_needed(
    strategy: ColocationStrategy,
    metric_update_time: Optional[float],
    now: float,
) -> bool:
    """reference ``batchresource/plugin.go:370 isDegradeNeeded``: nil or
    stale (> DegradeTimeMinutes) NodeMetric freezes the batch resources."""
    if metric_update_time is None:
        return True
    return now > metric_update_time + strategy.degrade_time_minutes * 60.0


def calculate_batch_resource(
    strategy: ColocationStrategy,
    node_capacity: Mapping[str, object],
    node_annotation_reserved: Optional[Mapping[str, object]],
    kubelet_reserved: Optional[Mapping[str, object]],
    system_usage: Mapping[str, object],
    pods: Sequence[Mapping],
    pod_metrics: Mapping[str, Mapping[str, object]],
    metric_update_time: Optional[float] = None,
    now: float = 0.0,
    cpu_normalization_ratio: float = -1.0,
) -> BatchResourceResult:
    """One node's batch-allocatable.

    Formula (reference ``util.go:38-49``)::

        System.Used        = max(system_usage, System.Reserved)
        System.Reserved    = max(node_anno_reserved, kubelet_reserved)
        byUsage   = max(0, capacity - nodeReservation - System.Used - podHPUsed)
        byRequest = max(0, capacity - nodeReservation - System.Reserved - podHPRequest)

    CPU always uses byUsage; memory uses byRequest when the strategy's
    ``memory_calculate_policy`` is ``request`` (``util.go:57``).  HP pods
    are all running/pending pods not in the batch/free bands
    (``plugin.go:184-198``); pods reported in metrics but absent from the
    pod list count into HP used (``plugin.go:201-203``).
    """
    if is_degrade_needed(strategy, metric_update_time, now):
        return BatchResourceResult(0, 0, True, "degradedByBatchResource: stale or missing NodeMetric")

    cap = _vec(node_capacity)
    sys_reserved = np.maximum(_vec(node_annotation_reserved), _vec(kubelet_reserved))
    sys_used = np.maximum(_vec(system_usage), sys_reserved)

    hp_request = np.zeros(2, dtype=np.int64)
    hp_used = np.zeros(2, dtype=np.int64)
    known_used = np.zeros(2, dtype=np.int64)
    all_used = np.zeros(2, dtype=np.int64)
    for key, m in pod_metrics.items():
        all_used += _vec(m)

    for pod in pods:
        phase = pod.get("phase", "Running")
        if phase not in ("Running", "Pending"):
            continue
        key = pod.get("name", "")
        metric = pod_metrics.get(key)
        if metric is not None:
            known_used += _vec(metric)
        if priority_class_of(pod) in (PRIORITY_BATCH, PRIORITY_FREE):
            continue  # ignore LP pods
        preq = _vec(pod.get("requests"))
        hp_request += preq
        if metric is None:
            hp_used += preq
        elif pod.get("qos") == "LSE":
            # LSE pods do not reclaim CPU: request for cpu, usage for memory
            # (reference plugin.go:193-195).
            mu = _vec(metric)
            hp_used += np.array([preq[CPU], mu[MEM]], dtype=np.int64)
        else:
            hp_used += _vec(metric)

    # pods with metrics but not in the list: unknown priority -> HP used
    hp_used += all_used - known_used

    node_reservation = _node_reservation(strategy, cap)

    by_usage = np.maximum(cap - node_reservation - sys_used - hp_used, 0)
    by_request = np.maximum(cap - node_reservation - sys_reserved - hp_request, 0)

    batch = by_usage.copy()
    if strategy.memory_calculate_policy == CALCULATE_BY_POD_REQUEST:
        batch[MEM] = by_request[MEM]

    batch_cpu = int(batch[CPU])
    # amplify batch cpu by the cpu-normalization ratio (util.go:80-91)
    if cpu_normalization_ratio > 1.0:
        batch_cpu = int(batch_cpu * cpu_normalization_ratio)

    msg = (
        f"batchAllocatable[CPU(Milli-Core)]:{batch_cpu} = nodeCapacity:{cap[CPU]}"
        f" - nodeReservation:{node_reservation[CPU]} - systemUsageOrReserved:{sys_used[CPU]}"
        f" - podHPUsed:{hp_used[CPU]}"
    )
    return BatchResourceResult(batch_cpu, int(batch[MEM]), False, msg)


def _node_reservation(strategy: ColocationStrategy, cap: np.ndarray) -> np.ndarray:
    """reference ``util.go:178-186 getNodeReservation``: reserve
    (100 - reclaimPercent)% of allocatable."""
    cpu = cap[CPU] * (100 - strategy.cpu_reclaim_threshold_percent) // 100
    mem = cap[MEM] * (100 - strategy.memory_reclaim_threshold_percent) // 100
    return np.array([cpu, mem], dtype=np.int64)


def calculate_mid_resource(
    strategy: ColocationStrategy,
    node_allocatable: Mapping[str, object],
    prod_reclaimable: Optional[Mapping[str, object]],
    metric_update_time: Optional[float] = None,
    now: float = 0.0,
) -> BatchResourceResult:
    """Mid-tier resources: ``min(ProdReclaimable, allocatable * midThresholdRatio)``
    (reference ``midresource/plugin.go:84-120``; degrade when the prod
    reclaimable metric is absent or stale)."""
    if prod_reclaimable is None or is_degrade_needed(strategy, metric_update_time, now):
        return BatchResourceResult(0, 0, True, "degradedByMidResource: stale or missing ProdReclaimable")
    alloc = _vec(node_allocatable)
    reclaimable = _vec(prod_reclaimable)
    cap = np.array(
        [
            alloc[CPU] * strategy.mid_cpu_threshold_percent // 100,
            alloc[MEM] * strategy.mid_memory_threshold_percent // 100,
        ],
        dtype=np.int64,
    )
    mid = np.minimum(reclaimable, cap)
    result = BatchResourceResult(int(mid[CPU]), int(mid[MEM]), False, "midAllocatable=min(prodReclaimable, allocatable*ratio)")
    return result


def need_sync(
    strategy: ColocationStrategy,
    old_allocatable: Mapping[str, int],
    new_allocatable: Mapping[str, int],
    resource_names: Sequence[str] = (res.BATCH_CPU, res.BATCH_MEMORY),
) -> bool:
    """reference ``util.IsResourceDiff`` used by ``NeedSync``
    (``batchresource/plugin.go`` / ``midresource/plugin.go:50``): resync when
    any tracked resource moved by more than ResourceDiffThreshold
    (relative to the old value; new-vs-missing counts as diff)."""
    for name in resource_names:
        old = old_allocatable.get(name)
        new = new_allocatable.get(name)
        if (old is None) != (new is None):
            return True
        if old is None or new is None:
            continue
        old = res.parse_quantity(old, name)
        new = res.parse_quantity(new, name)
        if old == 0:
            if new != 0:
                return True
            continue
        if abs(new - old) / abs(old) > strategy.resource_diff_threshold:
            return True
    return False


def batch_allocatable_batch(
    strategy: ColocationStrategy,
    capacity: np.ndarray,          # [N, 2] int64
    sys_reserved: np.ndarray,      # [N, 2]
    sys_usage: np.ndarray,         # [N, 2]
    hp_request: np.ndarray,        # [N, 2]
    hp_used: np.ndarray,           # [N, 2]
) -> np.ndarray:
    """Vectorized whole-cluster batch-allocatable: same formula as
    ``calculate_batch_resource`` evaluated for all N nodes at once.  This is
    the shape the TPU reconciler consumes (one fused program per cluster
    sweep rather than the reference's per-node Reconcile)."""
    reclaim = np.array(
        [100 - strategy.cpu_reclaim_threshold_percent, 100 - strategy.memory_reclaim_threshold_percent],
        dtype=np.int64,
    )
    node_reservation = capacity * reclaim // 100
    sys_used = np.maximum(sys_usage, sys_reserved)
    by_usage = np.maximum(capacity - node_reservation - sys_used - hp_used, 0)
    by_request = np.maximum(capacity - node_reservation - sys_reserved - hp_request, 0)
    out = by_usage
    if strategy.memory_calculate_policy == CALCULATE_BY_POD_REQUEST:
        out = np.stack([by_usage[:, CPU], by_request[:, MEM]], axis=1)
    return out
