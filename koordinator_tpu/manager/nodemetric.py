"""NodeMetric CR lifecycle: ensure one per node, push the collect policy.

Reference: ``pkg/slo-controller/nodemetric`` (``nodemetric_controller.go:59
Reconcile`` creates/deletes NodeMetric alongside its Node and stamps the
spec's ``CollectPolicy`` from the merged colocation strategy;
``collect_policy.go`` derives the policy fields).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Sequence

from koordinator_tpu.manager.sloconfig import ColocationStrategy, merge_node_strategy


def collect_policy(strategy: ColocationStrategy) -> Dict[str, Any]:
    """reference ``collect_policy.go getNodeMetricCollectPolicy``."""
    return {
        "aggregateDurationSeconds": strategy.metric_aggregate_duration_seconds,
        "reportIntervalSeconds": strategy.metric_report_interval_seconds,
        "nodeAggregatePolicy": {
            "durations": list(strategy.metric_aggregate_durations_seconds),
        },
        "nodeMemoryCollectPolicy": strategy.metric_memory_collect_policy,
    }


def reconcile_nodemetrics(
    nodes: Sequence[Mapping[str, Any]],
    existing: Mapping[str, Mapping[str, Any]],
    cluster_strategy: ColocationStrategy,
    node_cfgs: Sequence[Mapping[str, Any]] = (),
) -> Dict[str, Optional[Dict[str, Any]]]:
    """Desired NodeMetric spec per node name; ``None`` marks a NodeMetric
    whose Node is gone and should be garbage-collected (the reference
    relies on ownerReferences for that)."""
    desired: Dict[str, Optional[Dict[str, Any]]] = {}
    node_names = set()
    for node in nodes:
        name = node["name"]
        node_names.add(name)
        strategy = merge_node_strategy(cluster_strategy, node.get("labels", {}), node_cfgs)
        desired[name] = {"metricCollectPolicy": collect_policy(strategy)}
    for name in existing:
        if name not in node_names:
            desired[name] = None
    return desired
