"""ElasticQuotaProfile -> per-node-selector ElasticQuota tree roots.

Reference: ``pkg/quota-controller/profile/profile_controller.go``
(``Reconcile`` :79, ``decorateTotalResource``/``DecorateResourceByResourceRatio``
:57-271): sum the allocatable of the nodes matching the profile's node
selector, scale by the profile's resource ratio, and emit/refresh a root
ElasticQuota (min = max = scaled total) tagged with the profile's tree ID.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.manager.sloconfig import node_selector_matches
from koordinator_tpu.model import resources as res

LABEL_QUOTA_TREE_ID = "quota.scheduling.koordinator.sh/tree-id"
LABEL_QUOTA_IS_ROOT = "quota.scheduling.koordinator.sh/is-root"


def sum_matching_allocatable(
    nodes: Sequence[Mapping[str, Any]],
    node_selector: Optional[Mapping[str, str]],
) -> Dict[str, int]:
    total: Dict[str, int] = {}
    for node in nodes:
        labels = node.get("labels", {})
        if node_selector and not node_selector_matches(node_selector, labels):
            continue
        for name, qty in node.get("allocatable", {}).items():
            total[name] = total.get(name, 0) + res.parse_quantity(qty, name)
    return total


def scale_total(total: Mapping[str, int], ratio: Optional[float]) -> Dict[str, int]:
    """reference ``DecorateResourceByResourceRatio`` :259-271."""
    if ratio is None:
        return dict(total)
    return {name: int(v * float(ratio)) for name, v in total.items()}


def reconcile_profile(
    profile: Mapping[str, Any],
    nodes: Sequence[Mapping[str, Any]],
) -> Dict[str, Any]:
    """Build the root ElasticQuota object for one profile."""
    spec = profile.get("spec", profile)
    total = sum_matching_allocatable(nodes, spec.get("nodeSelector", {}).get("matchLabels"))
    ratio = spec.get("resourceRatio")
    scaled = scale_total(total, float(ratio) if ratio is not None else None)
    tree_id = spec.get("treeID") or profile.get("name", "")
    # axis-unit ints must round-trip through a later parse_quantity
    quantities = {n: res.format_quantity(v, n) for n, v in scaled.items()}
    return {
        "name": spec.get("quotaName", profile.get("name", "")),
        "labels": {LABEL_QUOTA_TREE_ID: tree_id, LABEL_QUOTA_IS_ROOT: "true"},
        "min": dict(quantities),
        "max": dict(quantities),
    }


def reconcile_profiles(
    profiles: Sequence[Mapping[str, Any]],
    nodes: Sequence[Mapping[str, Any]],
) -> List[Dict[str, Any]]:
    return [reconcile_profile(p, nodes) for p in profiles]
