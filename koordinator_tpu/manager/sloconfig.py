"""Colocation / SLO strategy configuration: parse, validate, merge.

Mirrors the reference's ConfigMap-borne strategy handling
(``pkg/util/sloconfig/colocation_config.go``; types at
``apis/configuration/slo_controller_config.go:211``): a cluster-level
``ColocationStrategy`` plus per-node-selector overrides, merged
field-by-field (the reference merges via JSON patch of non-nil fields).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence

# Memory calculate policies (reference apis/configuration:
# CalculateByPodUsage / CalculateByPodRequest / CalculateByPodMaxUsageRequest).
CALCULATE_BY_POD_USAGE = "usage"
CALCULATE_BY_POD_REQUEST = "request"
CALCULATE_BY_POD_MAX_USAGE_REQUEST = "maxUsageRequest"


@dataclasses.dataclass
class ColocationStrategy:
    """Cluster colocation strategy (reference
    ``apis/configuration/slo_controller_config.go:211``, defaults at
    ``pkg/util/sloconfig/colocation_config.go:44-68``)."""

    enable: bool = False
    metric_aggregate_duration_seconds: int = 300
    metric_report_interval_seconds: int = 60
    # aggregate windows used by the percentile usage model (5m / 10m / 30m)
    metric_aggregate_durations_seconds: Sequence[int] = (300, 600, 1800)
    metric_memory_collect_policy: str = "usageWithoutPageCache"
    cpu_reclaim_threshold_percent: int = 60
    memory_reclaim_threshold_percent: int = 65
    memory_calculate_policy: str = CALCULATE_BY_POD_USAGE
    degrade_time_minutes: int = 15
    update_time_threshold_seconds: int = 300
    resource_diff_threshold: float = 0.1
    # Mid-tier: fraction of node allocatable usable as mid resources
    mid_cpu_threshold_percent: int = 100
    mid_memory_threshold_percent: int = 100

    def replace(self, **overrides) -> "ColocationStrategy":
        kept = {k: v for k, v in overrides.items() if v is not None}
        return dataclasses.replace(self, **kept)


def default_colocation_strategy() -> ColocationStrategy:
    return ColocationStrategy()


def is_strategy_valid(s: Optional[ColocationStrategy]) -> bool:
    """reference ``sloconfig.IsColocationStrategyValid`` (:70-80): every set
    numeric knob must be positive."""
    if s is None:
        return False
    return (
        s.metric_aggregate_duration_seconds > 0
        and s.metric_report_interval_seconds > 0
        and s.cpu_reclaim_threshold_percent > 0
        and s.memory_reclaim_threshold_percent > 0
        and s.degrade_time_minutes > 0
        and s.update_time_threshold_seconds > 0
        and s.resource_diff_threshold > 0
        and len(s.metric_memory_collect_policy) > 0
    )


_CAMEL_TO_FIELD = {
    "enable": "enable",
    "metricAggregateDurationSeconds": "metric_aggregate_duration_seconds",
    "metricReportIntervalSeconds": "metric_report_interval_seconds",
    "metricAggregateDurationsSeconds": "metric_aggregate_durations_seconds",
    "cpuReclaimThresholdPercent": "cpu_reclaim_threshold_percent",
    "memoryReclaimThresholdPercent": "memory_reclaim_threshold_percent",
    "memoryCalculatePolicy": "memory_calculate_policy",
    "degradeTimeMinutes": "degrade_time_minutes",
    "updateTimeThresholdSeconds": "update_time_threshold_seconds",
    "resourceDiffThreshold": "resource_diff_threshold",
    "metricMemoryCollectPolicy": "metric_memory_collect_policy",
    "midCPUThresholdPercent": "mid_cpu_threshold_percent",
    "midMemoryThresholdPercent": "mid_memory_threshold_percent",
}
_FIELD_NAMES = {f.name for f in dataclasses.fields(ColocationStrategy)}


def _normalize_overrides(cfg: Mapping[str, Any]) -> Dict[str, Any]:
    """Accept both camelCase (ConfigMap JSON) and snake_case keys; keep
    only fields the strategy actually has, with the given values."""
    out: Dict[str, Any] = {}
    for key, value in cfg.items():
        field = _CAMEL_TO_FIELD.get(key, key if key in _FIELD_NAMES else None)
        if field is not None and value is not None:
            out[field] = value
    return out


def parse_strategy(cfg: Mapping[str, Any]) -> ColocationStrategy:
    """Parse a ConfigMap-style JSON dict (camelCase keys like the
    reference's ``colocation-config`` data) into a strategy, applying
    defaults for missing fields."""
    return default_colocation_strategy().replace(**_normalize_overrides(cfg))


def node_selector_matches(selector: Optional[Mapping[str, str]], labels: Mapping[str, str]) -> bool:
    """matchLabels-only selector, as used by NodeColocationCfg
    (reference ``sloconfig.IsNodeColocationCfgValid``)."""
    if not selector:
        return False
    return all(labels.get(k) == v for k, v in selector.items())


def merge_node_strategy(
    cluster: ColocationStrategy,
    node_labels: Mapping[str, str],
    node_cfgs: Sequence[Mapping[str, Any]],
) -> ColocationStrategy:
    """Apply matching per-node-selector overrides on top of the cluster
    strategy (reference ``colocation_config.go`` node-cfg merge: later
    matching entries win field-by-field)."""
    merged = cluster
    for cfg in node_cfgs:
        if node_selector_matches(cfg.get("nodeSelector", {}).get("matchLabels"), node_labels):
            merged = dataclasses.replace(merged, **_normalize_overrides(cfg.get("strategy", {})))
    return merged
