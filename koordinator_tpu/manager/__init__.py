"""Central control-plane analog of koord-manager (reference
``pkg/slo-controller``, ``pkg/webhook``, ``pkg/quota-controller``).

Pure, host-side reconciliation math: the durable state lives in the cluster
store (``koordinator_tpu.cluster``-style dict objects), mirroring how the
reference keeps all controller state in apiserver CRs.

Modules
-------
- ``sloconfig``     — colocation/SLO strategy parse, merge, validate
                      (reference ``pkg/util/sloconfig``).
- ``noderesource``  — Batch/Mid overcommit calculator
                      (reference ``pkg/slo-controller/noderesource``).
- ``nodeslo``       — per-node NodeSLO spec rendering
                      (reference ``pkg/slo-controller/nodeslo``).
- ``nodemetric``    — NodeMetric CR lifecycle + collect policy
                      (reference ``pkg/slo-controller/nodemetric``).
- ``profile``       — ClusterColocationProfile pod mutation (the mutating
                      webhook, reference
                      ``pkg/webhook/pod/mutating/cluster_colocation_profile.go``).
- ``quota_profile`` — ElasticQuotaProfile -> quota-tree reconciler
                      (reference ``pkg/quota-controller/profile``).
"""

from koordinator_tpu.manager.sloconfig import (  # noqa: F401
    ColocationStrategy,
    default_colocation_strategy,
    is_strategy_valid,
    merge_node_strategy,
)
from koordinator_tpu.manager.noderesource import (  # noqa: F401
    BatchResourceResult,
    calculate_batch_resource,
    calculate_mid_resource,
    need_sync,
)
