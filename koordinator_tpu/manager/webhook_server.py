"""Webhook serving machinery: HTTPS admission server + cert management.

The reference's webhook framework (``pkg/webhook/server.go:80
SetupWithManager``) brings three pieces the decision logic alone lacks:

* **cert generation** — a self-signed CA + server certificate written to
  the cert dir (``pkg/webhook/util/generator``): here via the
  ``cryptography`` package, SANs covering the service DNS names.
* **cert rotation** — certs are re-generated before expiry and the
  server re-wraps its socket so new connections use the fresh cert
  (``pkg/webhook/util/controller`` keeps the webhook configuration's
  caBundle in sync; ``ca_bundle()`` is that output).
* **the admission HTTP surface** — ``/mutate-pod``, ``/validate-pod``,
  ``/validate-quota``, ``/validate-node`` endpoints speaking the
  AdmissionReview JSON envelope, dispatching to the existing handlers
  (manager/profile.py mutating, manager/validating.py validating);
  mutating replies carry an RFC-6902 JSON patch like the real thing.
"""

from __future__ import annotations

import base64
import datetime
import json
import os
import ssl
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from koordinator_tpu.httpserving import HTTPLifecycle
from koordinator_tpu.manager.profile import mutate_by_profiles
from koordinator_tpu.manager.validating import (
    validate_node_colocation,
    validate_pod,
    validate_quota_tree,
)

DEFAULT_CERT_VALIDITY_DAYS = 365  # generator.NewSelfSignedCert default
DEFAULT_ROTATE_BEFORE = 30 * 24 * 3600.0  # rotate within 30d of expiry


# ---------------------------------------------------------------------------
# Cert generation / rotation (pkg/webhook/util/generator analog)
# ---------------------------------------------------------------------------


class CertManager:
    """Self-signed CA + server cert in ``cert_dir``; rotation regenerates
    both when the server cert nears expiry."""

    def __init__(
        self,
        cert_dir: str,
        dns_names: Tuple[str, ...] = ("koord-webhook-service",),
        validity_days: int = DEFAULT_CERT_VALIDITY_DAYS,
        rotate_before_seconds: float = DEFAULT_ROTATE_BEFORE,
        clock: Callable[[], float] = time.time,
    ):
        self.cert_dir = cert_dir
        self.dns_names = dns_names
        self.validity_days = validity_days
        self.rotate_before = rotate_before_seconds
        self.clock = clock
        self.rotations = 0
        # set when generation tooling is PROVEN absent (FileNotFoundError
        # from the openssl exec): a condition that cannot change at
        # runtime, so later ticks skip the attempt instead of re-warning
        self._tooling_absent = False
        os.makedirs(cert_dir, exist_ok=True)

    @property
    def ca_path(self) -> str:
        return os.path.join(self.cert_dir, "ca.crt")

    @property
    def cert_path(self) -> str:
        return os.path.join(self.cert_dir, "tls.crt")

    @property
    def key_path(self) -> str:
        return os.path.join(self.cert_dir, "tls.key")

    def ensure(self) -> bool:
        """Generate certs if absent or near expiry; returns True when new
        certs were written (the caller re-wraps its TLS socket).

        When generation fails but a cert EXISTS (no tooling on a minimal
        image, a read-only operator-mounted cert_dir, transient ENOSPC),
        the existing cert keeps being served with a warning naming the
        real error instead of crashing the rotate tick — safe because
        both generators write to temp names and commit with os.replace
        only after every artifact succeeded, so a failed attempt never
        tears the served cert/CA pair.  A MISSING cert still raises
        (nothing to serve).  Proven-absent tooling (FileNotFoundError
        from the openssl exec — cannot change at runtime) is cached so
        the warning fires once, not every tick."""
        missing = not os.path.exists(self.cert_path)
        if not missing and (self._tooling_absent or not self._near_expiry()):
            return False
        try:
            self._generate()
        except OSError as exc:
            if missing:
                raise
            if isinstance(exc, FileNotFoundError):
                self._tooling_absent = True
            import logging

            logging.getLogger(__name__).warning(
                "cannot rotate webhook certs (%s); continuing to serve "
                "the existing certificate",
                exc,
            )
            return False
        return True

    def ca_bundle(self) -> str:
        """base64 CA cert — what the webhook-configuration controller
        patches into ValidatingWebhookConfiguration.caBundle."""
        with open(self.ca_path, "rb") as fh:
            return base64.b64encode(fh.read()).decode()

    def _near_expiry(self) -> bool:
        expires = self._cert_expiry()
        if expires is None:
            return True
        return self.clock() >= expires - self.rotate_before

    def _cert_expiry(self) -> Optional[float]:
        """The server cert's notAfter as a unix timestamp, or None when
        unreadable (treated as expired)."""
        try:
            from cryptography import x509

            with open(self.cert_path, "rb") as fh:
                cert = x509.load_pem_x509_certificate(fh.read())
            return cert.not_valid_after_utc.timestamp()
        except ImportError:
            pass
        except (OSError, ValueError):
            return None
        # no ``cryptography`` in this environment: the openssl CLI reads
        # the same field ("notAfter=<C-locale date> GMT")
        import subprocess

        try:
            proc = subprocess.run(
                ["openssl", "x509", "-enddate", "-noout", "-in", self.cert_path],
                capture_output=True,
                text=True,
            )
        except OSError:
            # no openssl binary either: honor the documented "None when
            # unreadable" contract; ensure() then decides whether to
            # keep serving the existing cert or fail loudly
            return None
        if proc.returncode != 0:
            return None
        # openssl prints C-locale dates ("notAfter=Aug  3 05:00:00 2027
        # GMT"); parse by hand — strptime's %b is LC_TIME-dependent and
        # would misread every cert under a non-English locale, churning
        # rotations forever
        months = {
            m: i + 1
            for i, m in enumerate(
                "Jan Feb Mar Apr May Jun Jul Aug Sep Oct Nov Dec".split()
            )
        }
        try:
            mon, day, clock, year = proc.stdout.strip().split(
                "=", 1
            )[1].split()[:4]
            hh, mm, ss = (int(v) for v in clock.split(":"))
            dt = datetime.datetime(
                int(year), months[mon], int(day), hh, mm, ss,
                tzinfo=datetime.timezone.utc,
            )
            return dt.timestamp()
        except (IndexError, KeyError, ValueError):
            return None

    def _commit_triple(self, ca_tmp: str, cert_tmp: str, key_tmp: str) -> None:
        """Atomically-as-possible swap the generated temp files into
        place.  Three files cannot be renamed as one transaction, so a
        mid-commit failure rolls already-replaced files back from saved
        bytes (best-effort) — the served cert/key/CA triple must never
        be left mismatched (a new ca.crt that did not sign the served
        tls.crt breaks every webhook call until the next rotation)."""
        saved = {}
        for final in (self.ca_path, self.cert_path, self.key_path):
            if os.path.exists(final):
                with open(final, "rb") as fh:
                    saved[final] = fh.read()
        done = []
        try:
            for tmp, final in (
                (ca_tmp, self.ca_path),
                (cert_tmp, self.cert_path),
                (key_tmp, self.key_path),
            ):
                os.replace(tmp, final)
                done.append(final)
        except OSError:
            for final in done:
                if final in saved:
                    try:
                        with open(final, "wb") as fh:
                            fh.write(saved[final])
                    except OSError:
                        pass  # best-effort: the original raise wins
            raise

    def _generate(self) -> None:
        """Self-signed CA + SAN server cert, via the ``cryptography``
        package when importable, else the openssl CLI (same artifacts:
        ca.crt / tls.crt / tls.key; the CLI path exists because minimal
        images carry the openssl binary but not the Python bindings)."""
        try:
            import cryptography  # noqa: F401
        except ImportError:
            self._generate_openssl()
        else:
            self._generate_cryptography()
        self.rotations += 1

    def _generate_openssl(self) -> None:
        import subprocess

        def run(*argv):
            subprocess.run(argv, capture_output=True, check=True)

        ca_key = os.path.join(self.cert_dir, "ca.key")
        csr = os.path.join(self.cert_dir, "server.csr")
        cnf = os.path.join(self.cert_dir, "openssl.cnf")
        srl = os.path.join(self.cert_dir, "ca.srl")
        # generate into temp names; only a fully successful sequence is
        # committed (os.replace), so a mid-sequence failure can never
        # leave a mismatched cert/key/CA triple being served
        ca_tmp, cert_tmp, key_tmp = (
            p + ".tmp" for p in (self.ca_path, self.cert_path, self.key_path)
        )
        sans = ",".join(
            f"DNS:{n}" for n in tuple(self.dns_names) + ("localhost",)
        )
        # explicit config: relying on the system default config risks
        # duplicate x509v3 extensions (-addext on top of the distro's
        # v3_ca section), which poisons chain validation
        with open(cnf, "w") as fh:
            fh.write(
                "[req]\n"
                "distinguished_name = dn\n"
                "prompt = no\n"
                "[dn]\n"
                "CN = placeholder\n"
                "[v3_ca]\n"
                "basicConstraints = critical,CA:TRUE\n"
                "subjectKeyIdentifier = hash\n"
                "[v3_server]\n"
                f"subjectAltName = {sans}\n"
            )
        days = str(self.validity_days)
        try:
            run(
                "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
                "-keyout", ca_key, "-out", ca_tmp, "-days", days,
                "-subj", "/CN=koordinator-webhook-ca",
                "-config", cnf, "-extensions", "v3_ca",
            )
            run(
                "openssl", "req", "-newkey", "rsa:2048", "-nodes",
                "-keyout", key_tmp, "-out", csr,
                "-subj", f"/CN={self.dns_names[0]}", "-config", cnf,
            )
            run(
                "openssl", "x509", "-req", "-in", csr, "-CA", ca_tmp,
                "-CAkey", ca_key, "-CAcreateserial", "-out", cert_tmp,
                "-days", days, "-extfile", cnf, "-extensions", "v3_server",
            )
            self._commit_triple(ca_tmp, cert_tmp, key_tmp)
        finally:
            # parity with the cryptography path, which keeps the CA key
            # in memory only: a CA key (or CSR/config/serial scratch)
            # left in cert_dir would let anything that reads the dir —
            # or a volume snapshot of it — mint certs chaining to the
            # installed caBundle.  Runs even when the openssl binary is
            # absent (FileNotFoundError from the first run).
            # -CAcreateserial names the serial after the -CA file
            # (ca.crt.tmp -> ca.crt.srl); sweep both spellings
            for scratch in (ca_key, csr, cnf, srl,
                            os.path.splitext(ca_tmp)[0] + ".srl",
                            ca_tmp, cert_tmp, key_tmp):
                try:
                    os.unlink(scratch)
                except OSError:
                    pass

    def _generate_cryptography(self) -> None:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID

        now = datetime.datetime.fromtimestamp(
            self.clock(), tz=datetime.timezone.utc
        )
        until = now + datetime.timedelta(days=self.validity_days)

        ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        ca_name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, "koordinator-webhook-ca")]
        )
        ca_cert = (
            x509.CertificateBuilder()
            .subject_name(ca_name)
            .issuer_name(ca_name)
            .public_key(ca_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(until)
            .add_extension(x509.BasicConstraints(ca=True, path_length=0), True)
            .sign(ca_key, hashes.SHA256())
        )

        key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        cert = (
            x509.CertificateBuilder()
            .subject_name(
                x509.Name(
                    [x509.NameAttribute(NameOID.COMMON_NAME, self.dns_names[0])]
                )
            )
            .issuer_name(ca_name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(until)
            .add_extension(
                x509.SubjectAlternativeName(
                    [x509.DNSName(n) for n in self.dns_names]
                    + [x509.DNSName("localhost")]
                ),
                False,
            )
            .sign(ca_key, hashes.SHA256())
        )

        # temp-then-rename: a mid-write failure (ENOSPC, kill) must not
        # leave a new ca.crt beside an old tls.crt — ca_bundle() would
        # publish a CA that never signed the served cert
        payloads = (
            (self.ca_path, ca_cert.public_bytes(serialization.Encoding.PEM)),
            (self.cert_path, cert.public_bytes(serialization.Encoding.PEM)),
            (
                self.key_path,
                key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.TraditionalOpenSSL,
                    serialization.NoEncryption(),
                ),
            ),
        )
        try:
            for path, data in payloads:
                with open(path + ".tmp", "wb") as fh:
                    fh.write(data)
            self._commit_triple(
                self.ca_path + ".tmp",
                self.cert_path + ".tmp",
                self.key_path + ".tmp",
            )
        finally:
            for path, _ in payloads:
                try:
                    os.unlink(path + ".tmp")
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# Admission endpoints (AdmissionReview envelope)
# ---------------------------------------------------------------------------


def _json_patch(original: Mapping, mutated: Mapping) -> List[Dict]:
    """Top-level RFC-6902 add/replace/remove ops for changed keys (the
    reference computes the patch from the mutated object the same way)."""
    ops = []
    for key, value in mutated.items():
        if key not in original:
            ops.append({"op": "add", "path": f"/{key}", "value": value})
        elif original[key] != value:
            ops.append({"op": "replace", "path": f"/{key}", "value": value})
    for key in original:
        if key not in mutated:
            ops.append({"op": "remove", "path": f"/{key}"})
    return ops


def admission_response(uid: str, allowed: bool, errs=(), patch=None) -> Dict:
    resp: Dict = {"uid": uid, "allowed": allowed}
    if errs:
        resp["status"] = {"message": "; ".join(errs)}
    if patch:
        resp["patchType"] = "JSONPatch"
        resp["patch"] = base64.b64encode(json.dumps(patch).encode()).decode()
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview", "response": resp}


class WebhookServer:
    """HTTPS admission server with managed certs.

    ``profiles_fn`` supplies the live ClusterColocationProfiles for the
    mutating path (the reference watches them as CRs).
    """

    def __init__(
        self,
        cert_dir: str,
        host: str = "127.0.0.1",
        port: int = 0,
        profiles_fn: Callable[[], List[Mapping]] = lambda: [],
        cert_manager: Optional[CertManager] = None,
    ):
        self.certs = cert_manager or CertManager(cert_dir)
        self.profiles_fn = profiles_fn
        self.certs.ensure()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                try:
                    review = json.loads(self.rfile.read(length) or b"{}")
                    body = outer.handle(self.path, review)
                    code = 200
                except Exception as exc:  # malformed review -> 400
                    body = {"error": str(exc)}
                    code = 400
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._wrap_tls()
        self._http = HTTPLifecycle(self._httpd)

    def _wrap_tls(self):
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.certs.cert_path, self.certs.key_path)
        self._ssl_context = ctx
        self._httpd.socket = ctx.wrap_socket(
            self._httpd.socket, server_side=True
        )

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "WebhookServer":
        self._http.start()
        return self

    def stop(self):
        self._http.stop()

    def rotate_if_needed(self) -> bool:
        """Cert rotation tick: regenerate near-expiry certs and reload the
        TLS context so NEW connections use them."""
        if self.certs.ensure():
            self._ssl_context.load_cert_chain(
                self.certs.cert_path, self.certs.key_path
            )
            return True
        return False

    # -- dispatch --
    def handle(self, path: str, review: Mapping) -> Dict:
        req = review.get("request") or {}
        uid = req.get("uid", "")
        obj = req.get("object") or {}
        if path == "/mutate-pod":
            mutated = mutate_by_profiles(obj, self.profiles_fn())
            return admission_response(
                uid, True, patch=_json_patch(obj, mutated)
            )
        if path == "/validate-pod":
            errs = validate_pod(obj)
            return admission_response(uid, not errs, errs)
        if path == "/validate-quota":
            errs = validate_quota_tree(obj.get("quotas") or [obj])
            return admission_response(uid, not errs, errs)
        if path == "/validate-node":
            errs = validate_node_colocation(obj)
            return admission_response(uid, not errs, errs)
        raise ValueError(f"unknown webhook path {path!r}")
