"""Pod eviction seam: rate limiting + bookkeeping.

Reference: ``pkg/descheduler/evictions`` — ``PodEvictor`` counts evictions
per node/namespace and enforces ``MaxNoOfPodsToEvictPerNode`` /
``MaxNoOfPodsToEvictPerNamespace`` (``evictions.go:65``); a token-bucket
``EvictionLimiter`` throttles the global eviction rate
(``eviction_limiter.go``).  Actual eviction is a callback so tests and the
dry-run mode plug in trivially.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Mapping, Optional


class TokenBucket:
    """qps/burst limiter (the reference wraps client-go's flowcontrol)."""

    def __init__(self, qps: float, burst: int, clock: Callable[[], float] = time.monotonic):
        self.qps = qps
        self.burst = max(1, burst)
        self._tokens = float(self.burst)
        self._clock = clock
        self._last = clock()

    def try_accept(self) -> bool:
        now = self._clock()
        if self.qps > 0:
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
        self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


@dataclasses.dataclass
class EvictionRecord:
    pod: str
    namespace: str
    node: str
    reason: str


class PodEvictor:
    """Counts and limits evictions; ``evict`` returns False when a limit or
    the rate limiter blocks the eviction (reference ``evictions.go:165``)."""

    def __init__(
        self,
        max_pods_per_node: Optional[int] = None,
        max_pods_per_namespace: Optional[int] = None,
        qps: float = 0.0,
        burst: int = 0,
        dry_run: bool = False,
        evict_fn: Optional[Callable[[Mapping, str], bool]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_pods_per_node = max_pods_per_node
        self.max_pods_per_namespace = max_pods_per_namespace
        self.limiter = TokenBucket(qps, burst, clock) if qps > 0 else None
        self.dry_run = dry_run
        self.evict_fn = evict_fn
        self.node_counts: Dict[str, int] = {}
        self.namespace_counts: Dict[str, int] = {}
        self.evicted: List[EvictionRecord] = []

    def total_evicted(self) -> int:
        return len(self.evicted)

    def reset(self) -> None:
        """Per-tick counter reset (descheduler.go:269 evictionLimiter.Reset
        before running the profiles); the eviction audit trail persists."""
        self.node_counts.clear()
        self.namespace_counts.clear()

    def evict(self, pod: Mapping, node: str, reason: str = "") -> bool:
        ns = pod.get("namespace", "default")
        if self.max_pods_per_node is not None and self.node_counts.get(node, 0) >= self.max_pods_per_node:
            return False
        if (
            self.max_pods_per_namespace is not None
            and self.namespace_counts.get(ns, 0) >= self.max_pods_per_namespace
        ):
            return False
        if self.limiter is not None and not self.limiter.try_accept():
            return False
        if not self.dry_run and self.evict_fn is not None:
            if not self.evict_fn(pod, reason):
                return False
        self.node_counts[node] = self.node_counts.get(node, 0) + 1
        self.namespace_counts[ns] = self.namespace_counts.get(ns, 0) + 1
        self.evicted.append(EvictionRecord(pod.get("name", ""), ns, node, reason))
        return True
