"""Node anomaly detector: a circuit-breaker style state machine.

Reference: ``pkg/descheduler/utils/anomaly/basic_detector.go`` — ``Mark``
feeds normal/abnormal observations; consecutive-abnormality counts trip the
detector into the anomaly state, consecutive normalities restore it, and an
open-state timeout rolls the generation so stale counts don't linger.
LowNodeLoad uses it to debounce eviction decisions
(``low_node_load.go:256 filterRealAbnormalNodes``).
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Callable, Optional


class State(enum.Enum):
    OK = "ok"
    ANOMALY = "anomaly"


@dataclasses.dataclass
class Counter:
    """Mirror of the reference's Counter: totals plus consecutive runs."""

    total: int = 0
    normalities: int = 0
    abnormalities: int = 0
    consecutive_normalities: int = 0
    consecutive_abnormalities: int = 0

    def on_normal(self):
        self.total += 1
        self.normalities += 1
        self.consecutive_normalities += 1
        self.consecutive_abnormalities = 0

    def on_abnormal(self):
        self.total += 1
        self.abnormalities += 1
        self.consecutive_abnormalities += 1
        self.consecutive_normalities = 0

    def clear(self):
        self.total = 0
        self.normalities = 0
        self.abnormalities = 0
        self.consecutive_normalities = 0
        self.consecutive_abnormalities = 0


# defaults per reference basic_detector.go:28-34
def default_anomaly_condition(c: Counter) -> bool:
    return c.consecutive_abnormalities > 5


def default_normal_condition(c: Counter) -> bool:
    return c.consecutive_normalities > 3


class BasicDetector:
    """State machine with a generation timeout (reference
    ``BasicDetector``): observations older than ``timeout`` roll into a new
    generation with cleared counters."""

    def __init__(
        self,
        name: str,
        timeout_seconds: float = 60.0,
        anomaly_condition: Optional[Callable[[Counter], bool]] = None,
        normal_condition: Optional[Callable[[Counter], bool]] = None,
        on_state_change: Optional[Callable[[str, State, State], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self.timeout = timeout_seconds if timeout_seconds > 0 else 60.0
        self._anomaly_cond = anomaly_condition or default_anomaly_condition
        self._normal_cond = normal_condition or default_normal_condition
        self._on_state_change = on_state_change
        self._clock = clock
        self._state = State.OK
        self.counter = Counter()
        # seeded on the FIRST observation, in the caller's time base: the
        # constructor's wall clock and a caller-driven simulated ``now``
        # would otherwise mix bases and roll generations spuriously
        self._expiration: Optional[float] = None

    def state(self, now: Optional[float] = None) -> State:
        self._maybe_roll_generation(now)
        return self._state

    def mark(self, normality: bool, now: Optional[float] = None) -> State:
        """Feed one observation; returns the post-observation state.
        ``now`` overrides the clock for callers driving simulated time."""
        self._maybe_roll_generation(now)
        if normality:
            self.counter.on_normal()
            if self._state is State.ANOMALY and self._normal_cond(self.counter):
                self._set_state(State.OK, now)
        else:
            self.counter.on_abnormal()
            if self._state is State.OK and self._anomaly_cond(self.counter):
                self._set_state(State.ANOMALY, now)
        return self._state

    def reset(self):
        """Back to OK with cleared counters (reference ``Reset``)."""
        self.counter.clear()
        self._set_state(State.OK)
        self._expiration = None

    def _maybe_roll_generation(self, now: Optional[float] = None):
        now = self._clock() if now is None else now
        if self._expiration is None:
            self._expiration = now + self.timeout
            return
        if now >= self._expiration:
            self.counter.clear()
            self._expiration = now + self.timeout

    def _set_state(self, new: State, now: Optional[float] = None):
        old = self._state
        if old is new:
            return
        self._state = new
        self._expiration = (self._clock() if now is None else now) + self.timeout
        if self._on_state_change:
            self._on_state_change(self.name, old, new)
