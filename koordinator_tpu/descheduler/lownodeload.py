"""LowNodeLoad Balance: utilization classification + eviction planning.

Reference: ``pkg/descheduler/framework/plugins/loadaware/low_node_load.go``
(``Balance`` :135, ``processOneNodePool`` :154, ``newThresholds`` :287) and
``utilization_util.go`` (``getNodeThresholds``, ``evictPodsFromSourceNodes``).

The classification is a thresholded reduction over a dense ``[N, R]``
usage/capacity tensor — the same shape the TPU scorer consumes — computed
here with numpy (``classify``) so it runs host-side inside the controller
loop and can be handed to ``jax.jit`` unchanged for cluster-scale sweeps
(the arrays are pure elementwise + reductions).

A node is *underutilized* when usage is under the low threshold for every
tracked resource, *overutilized* when over the high threshold for any one
(reference ``isNodeUnderutilized`` / ``isNodeOverutilized``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.descheduler.anomaly import BasicDetector, State
from koordinator_tpu.descheduler.evictions import PodEvictor
from koordinator_tpu.descheduler.sorter import sort_pods_for_eviction
from koordinator_tpu.model import resources as res

MIN_RESOURCE_PERCENTAGE = 0.0
MAX_RESOURCE_PERCENTAGE = 100.0


@dataclasses.dataclass
class NodePool:
    """reference config.LowNodeLoadNodePool."""

    name: str = "default"
    node_selector: Optional[Mapping[str, str]] = None
    low_thresholds: Mapping[str, float] = dataclasses.field(default_factory=dict)
    high_thresholds: Mapping[str, float] = dataclasses.field(default_factory=dict)
    use_deviation_thresholds: bool = False
    resource_weights: Mapping[str, int] = dataclasses.field(
        default_factory=lambda: {res.CPU: 1, res.MEMORY: 1}
    )
    # anomaly debounce: evict only after this many consecutive overloaded
    # observations (reference LoadAnomalyCondition)
    consecutive_abnormalities: int = 1
    anomaly_timeout_seconds: float = 60.0


@dataclasses.dataclass
class LowNodeLoadArgs:
    node_pools: Sequence[NodePool] = dataclasses.field(default_factory=lambda: [NodePool()])
    number_of_nodes: int = 0
    dry_run: bool = False
    node_fit: bool = True
    paused: bool = False


@dataclasses.dataclass
class NodeClassification:
    names: List[str]
    usage: np.ndarray        # [N, R] int64
    allocatable: np.ndarray  # [N, R] int64
    low_threshold: np.ndarray   # [N, R] quantity units
    high_threshold: np.ndarray  # [N, R]
    underutilized: np.ndarray   # [N] bool
    overutilized: np.ndarray    # [N] bool


def _resource_list_vec(rl: Mapping[str, object], names: Sequence[str]) -> np.ndarray:
    full = res.resource_vector(rl or {})
    return np.array([full[res.RESOURCE_INDEX[n]] for n in names], dtype=np.int64)


def resolved_thresholds(
    pool: NodePool, resource_names: Sequence[str]
) -> Tuple[np.ndarray, np.ndarray]:
    """reference ``newThresholds`` :287: unset resources default to 100%
    (absolute mode, i.e. never trips) or 0% (deviation mode)."""
    fill = MIN_RESOURCE_PERCENTAGE if pool.use_deviation_thresholds else MAX_RESOURCE_PERCENTAGE
    low = np.array([float(pool.low_thresholds.get(n, fill)) for n in resource_names])
    high = np.array([float(pool.high_thresholds.get(n, fill)) for n in resource_names])
    return low, high


def classify(
    names: Sequence[str],
    usage: np.ndarray,
    allocatable: np.ndarray,
    low_pct: np.ndarray,
    high_pct: np.ndarray,
    use_deviation: bool,
    unschedulable: Optional[np.ndarray] = None,
) -> NodeClassification:
    """Vectorized ``getNodeThresholds`` + ``classifyNodes``."""
    usage = np.asarray(usage, dtype=np.int64)
    allocatable = np.asarray(allocatable, dtype=np.int64)
    n, r = usage.shape
    if use_deviation:
        with np.errstate(divide="ignore", invalid="ignore"):
            pct = np.where(allocatable > 0, 100.0 * usage / np.maximum(allocatable, 1), 0.0)
        avg = pct.mean(axis=0)  # calcAverageResourceUsagePercent
        low_eff = np.clip(avg - low_pct, 0.0, 100.0)
        high_eff = np.clip(avg + high_pct, 0.0, 100.0)
        # resources with MinResourcePercentage pin thresholds to capacity
        pinned = low_pct == MIN_RESOURCE_PERCENTAGE
        low_eff = np.where(pinned, 100.0, low_eff)
        high_eff = np.where(pinned, 100.0, high_eff)
    else:
        low_eff, high_eff = low_pct, high_pct
    low_q = (low_eff[None, :] * 0.01 * allocatable).astype(np.int64)
    high_q = (high_eff[None, :] * 0.01 * allocatable).astype(np.int64)
    # usage equal to the threshold is still underutilized (isNodeUnderutilized
    # rejects only used.Cmp(threshold) > 0, utilization_util.go:406)
    under = (usage <= low_q).all(axis=1)
    if unschedulable is not None:
        under &= ~np.asarray(unschedulable, dtype=bool)
    over = (usage > high_q).any(axis=1)
    return NodeClassification(list(names), usage, allocatable, low_q, high_q, under, over)


def classify_nodes(nodes: Sequence[Mapping], pool: NodePool) -> Tuple[NodeClassification, List[str]]:
    resource_names = sorted(
        set(pool.low_thresholds) | set(pool.high_thresholds) | {res.MEMORY},
        key=lambda n: res.RESOURCE_INDEX.get(n, 99),
    )
    low_pct, high_pct = resolved_thresholds(pool, resource_names)
    usage = np.stack([_resource_list_vec(nd.get("usage", {}), resource_names) for nd in nodes])
    alloc = np.stack([_resource_list_vec(nd.get("allocatable", {}), resource_names) for nd in nodes])
    unsched = np.array([bool(nd.get("unschedulable")) for nd in nodes])
    return (
        classify([nd["name"] for nd in nodes], usage, alloc, low_pct, high_pct, pool.use_deviation_thresholds, unsched),
        resource_names,
    )


def balance(
    args: LowNodeLoadArgs,
    nodes: Sequence[Mapping],
    evictor: PodEvictor,
    detectors: Optional[Dict[str, BasicDetector]] = None,
    pod_filter: Optional[Callable[[Mapping], bool]] = None,
    now: Optional[float] = None,
) -> List[Dict]:
    """One Balance tick over all node pools (reference ``Balance`` :135).

    ``nodes`` are dicts: name, labels, allocatable, usage, unschedulable,
    pods (list of pod dicts with optional ``usage`` metric).  Returns the
    planned/performed evictions as dicts.
    """
    if args.paused:
        return []
    detectors = detectors if detectors is not None else {}
    planned: List[Dict] = []
    processed: set = set()
    for pool in args.node_pools:
        pool_nodes = [
            nd
            for nd in nodes
            if nd["name"] not in processed
            and (
                pool.node_selector is None
                or all(nd.get("labels", {}).get(k) == v for k, v in pool.node_selector.items())
            )
        ]
        if not pool_nodes:
            continue
        cls, resource_names = classify_nodes(pool_nodes, pool)
        low_idx = np.flatnonzero(cls.underutilized)
        high_idx = np.flatnonzero(cls.overutilized)
        # reference guards (:173-194); guard exits do NOT mark nodes as
        # processed — an overlapping later pool still evaluates them
        # (processOneNodePool inserts only sourceNodes, on success).
        for i in low_idx:  # underutilized nodes reset their detectors
            d = detectors.get(cls.names[i])
            if d:
                d.reset()
        if (
            len(low_idx) == 0
            or len(low_idx) <= args.number_of_nodes
            or len(low_idx) == len(pool_nodes)
            or len(high_idx) == 0
        ):
            continue

        abnormal = _filter_real_abnormal(cls, high_idx, pool, detectors, now)
        if not len(abnormal):
            continue

        # destination headroom per low node (node-fit check) and its total:
        # sum(highThreshold - usage) over underutilized nodes
        dest_headroom = cls.high_threshold[low_idx] - cls.usage[low_idx]
        total_available = dest_headroom.sum(axis=0)

        # most-loaded first (weighted usage fraction)
        weights = np.array(
            [float(pool.resource_weights.get(n, 0)) for n in resource_names]
        )
        frac = (cls.usage / np.maximum(cls.allocatable, 1)).astype(float)
        load = (frac * weights).sum(axis=1) / max(weights.sum(), 1e-9)
        abnormal = sorted(abnormal, key=lambda i: -load[i])

        name_to_node = {nd["name"]: nd for nd in pool_nodes}
        for i in abnormal:
            node = name_to_node[cls.names[i]]
            node_usage = cls.usage[i].copy()
            pods = [
                p
                for p in node.get("pods", [])
                if _removable(p, pod_filter)
                and (
                    not args.node_fit
                    or _fits_any(p, dest_headroom, resource_names)
                )
            ]
            if not pods:
                continue
            metrics = {p["name"]: p.get("usage", p.get("requests", {})) for p in pods}
            ordered = sort_pods_for_eviction(
                pods, metrics, node.get("allocatable", {}), pool.resource_weights
            )
            for pod in ordered:
                still_over = (node_usage > cls.high_threshold[i]).any()
                if not still_over:
                    d = detectors.get(cls.names[i])
                    if d:
                        d.reset()
                    break
                if (total_available <= 0).any():
                    break
                pod_vec = _resource_list_vec(metrics.get(pod["name"], {}), resource_names)
                if not args.dry_run and not evictor.evict(
                    pod, cls.names[i], reason=f"node overutilized in pool {pool.name}"
                ):
                    continue
                node_usage -= pod_vec
                total_available -= pod_vec
                planned.append({"pod": pod["name"], "node": cls.names[i], "pool": pool.name})
        # after the round every overutilized source node is marked normal
        # once (tryMarkNodesAsNormal, low_node_load.go:234: Mark(true) on
        # existing detectors only) and excluded from later pools
        # (low_node_load.go:235-237 inserts all sourceNodes)
        for i in high_idx:
            d = detectors.get(cls.names[i])
            if d:
                d.mark(True, now)
            processed.add(cls.names[i])
    return planned


def _removable(pod: Mapping, pod_filter) -> bool:
    if pod.get("non_removable") or pod.get("qos") == "SYSTEM":
        return False
    if pod_filter is not None and not pod_filter(pod):
        return False
    return True


def _fits_any(pod: Mapping, dest_headroom: np.ndarray, resource_names: Sequence[str]) -> bool:
    """NodeFit guard (reference wraps the pod filter with
    ``PodFitsAnyNode`` over the destination nodes): the pod's requests
    must fit into at least one underutilized node's headroom."""
    if len(dest_headroom) == 0:
        return False
    req = _resource_list_vec(pod.get("requests", {}), resource_names)
    return bool((dest_headroom >= req).all(axis=1).any())


def _filter_real_abnormal(
    cls: NodeClassification,
    high_idx: np.ndarray,
    pool: NodePool,
    detectors: Dict[str, BasicDetector],
    now: Optional[float] = None,
) -> List[int]:
    """reference ``filterRealAbnormalNodes`` :256: with a 1-observation
    condition every overutilized node qualifies; otherwise the per-node
    circuit breaker must have tripped."""
    if pool.consecutive_abnormalities <= 1:
        return list(high_idx)
    out = []
    for i in high_idx:
        name = cls.names[i]
        d = detectors.get(name)
        if d is None:
            d = BasicDetector(
                name,
                timeout_seconds=pool.anomaly_timeout_seconds,
                anomaly_condition=lambda c, k=pool.consecutive_abnormalities: c.consecutive_abnormalities > k,
            )
            detectors[name] = d
        if d.mark(False, now) is State.ANOMALY:
            out.append(i)
    return out
