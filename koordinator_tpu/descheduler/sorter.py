"""Multi-key pod/node ranking for eviction candidate selection.

Reference: ``pkg/descheduler/utils/sorter`` — ``OrderedBy`` chains compare
functions (``helper.go``); the canonical pod ordering is
KoordinatorPriorityClass, then numeric Priority, then Kubernetes QoS, then
Koordinator QoS, then pod deletion cost, then eviction cost, then a
caller-supplied key (usually Reverse(PodUsage)), then creation timestamp
(``pod.go:161 PodSorter``).  Lower-ranked pods are evicted first.
"""

from __future__ import annotations

import functools
from typing import Callable, Mapping, Optional, Sequence

from koordinator_tpu.manager.noderesource import priority_class_of
from koordinator_tpu.model import resources as res

CompareFn = Callable[[Mapping, Mapping], int]

# ascending eviction preference: free evicted before batch before mid before prod
_PRIORITY_CLASS_RANK = {"koord-free": 0, "koord-batch": 1, "koord-mid": 2, "koord-prod": 3}
_K8S_QOS_RANK = {"BestEffort": 0, "Burstable": 1, "Guaranteed": 2}
# reference apis/extension/qos.go: SYSTEM > LSE > LSR > LS > BE
_KOORD_QOS_RANK = {"BE": 0, "LS": 1, "LSR": 2, "LSE": 3, "SYSTEM": 4}


def _cmp(a, b) -> int:
    return (a > b) - (a < b)


def koordinator_priority_class(a: Mapping, b: Mapping) -> int:
    return _cmp(_PRIORITY_CLASS_RANK.get(priority_class_of(a), 3), _PRIORITY_CLASS_RANK.get(priority_class_of(b), 3))


def priority(a: Mapping, b: Mapping) -> int:
    return _cmp(a.get("priority", 0), b.get("priority", 0))


def kubernetes_qos_class(a: Mapping, b: Mapping) -> int:
    return _cmp(_K8S_QOS_RANK.get(a.get("k8s_qos", "Burstable"), 1), _K8S_QOS_RANK.get(b.get("k8s_qos", "Burstable"), 1))


def koordinator_qos_class(a: Mapping, b: Mapping) -> int:
    return _cmp(_KOORD_QOS_RANK.get(a.get("qos", "LS"), 1), _KOORD_QOS_RANK.get(b.get("qos", "LS"), 1))


def pod_deletion_cost(a: Mapping, b: Mapping) -> int:
    return _cmp(int(a.get("deletion_cost", 0)), int(b.get("deletion_cost", 0)))


def eviction_cost(a: Mapping, b: Mapping) -> int:
    return _cmp(int(a.get("eviction_cost", 0)), int(b.get("eviction_cost", 0)))


def creation_timestamp(a: Mapping, b: Mapping) -> int:
    return _cmp(a.get("creation_timestamp", 0), b.get("creation_timestamp", 0))


def reverse(cmp: CompareFn) -> CompareFn:
    """reference ``helper.go:107 Reverse``."""

    def inner(a, b):
        return -cmp(a, b)

    return inner


def pod_usage(
    pod_metrics: Mapping[str, Mapping[str, object]],
    node_allocatable: Mapping[str, object],
    resource_weights: Mapping[str, int],
) -> CompareFn:
    """Weighted mean usage fraction of node allocatable (reference
    ``scorer.go`` podUsageScorer); higher usage sorts first under
    ``reverse``."""
    alloc = res.resource_vector(node_allocatable)
    weights = res.weights_vector(resource_weights)

    def score(pod: Mapping) -> float:
        m = pod_metrics.get(pod.get("name", ""))
        if not m:
            return 0.0
        vec = res.resource_vector(m)
        total, wsum = 0.0, 0
        for v, a, w in zip(vec, alloc, weights):
            if w <= 0 or a <= 0:
                continue
            total += w * (v / a)
            wsum += w
        return total / wsum if wsum else 0.0

    def compare(a, b):
        return _cmp(score(a), score(b))

    return compare


def ordered_by(*comparators: CompareFn) -> Callable[[Sequence[Mapping]], list]:
    """reference ``helper.go OrderedBy``: stable multi-key sort."""

    def key_cmp(a, b):
        for cmp in comparators:
            r = cmp(a, b)
            if r:
                return r
        return 0

    def sort(items: Sequence[Mapping]) -> list:
        return sorted(items, key=functools.cmp_to_key(key_cmp))

    return sort


def sort_pods_for_eviction(
    pods: Sequence[Mapping],
    pod_metrics: Mapping[str, Mapping[str, object]],
    node_allocatable: Mapping[str, object],
    resource_weights: Mapping[str, int],
) -> list:
    """reference ``pod.go:175 SortPodsByUsage`` composed with the standard
    PodSorter chain; first element is the best eviction candidate."""
    return ordered_by(
        koordinator_priority_class,
        priority,
        kubernetes_qos_class,
        koordinator_qos_class,
        pod_deletion_cost,
        eviction_cost,
        reverse(pod_usage(pod_metrics, node_allocatable, resource_weights)),
        creation_timestamp,
    )(pods)


def sort_nodes_by_usage(
    nodes: Sequence[Mapping],
    usage_fraction: Callable[[Mapping], float],
    ascending: bool = False,
) -> list:
    """reference ``low_node_load.go sortNodesByUsage``: most-loaded first
    unless ascending."""
    return sorted(nodes, key=usage_fraction, reverse=not ascending)
