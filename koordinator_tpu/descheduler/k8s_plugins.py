"""Upstream-descheduler adaptor plugins.

Reference: ``pkg/descheduler/framework/plugins/kubernetes`` wraps
sigs.k8s.io/descheduler plugins (DefaultEvictor, RemovePodsViolating*,
RemoveDuplicates, RemovePodsHavingTooManyRestarts) into the koord
descheduler framework (``framework/types.go:80 DeschedulePlugin``).
Here the same plugin set as pure functions over pod/node dicts, composed
with the evictions/ rate-limited evictor the way the adaptor wires the
upstream evictor seam.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Mapping, Optional, Sequence

PRIORITY_CRITICAL = 2_000_000_000  # system-cluster-critical


@dataclasses.dataclass(frozen=True)
class DefaultEvictorArgs:
    """sigs.k8s.io defaultevictor semantics: which pods are evictable."""

    evict_system_critical_pods: bool = False
    evict_local_storage_pods: bool = False
    evict_failed_bare_pods: bool = False
    ignore_pvc_pods: bool = False
    priority_threshold: Optional[int] = None
    label_selector: Optional[Mapping[str, str]] = None


def _matches(selector: Optional[Mapping[str, str]], labels: Mapping) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


def default_evictor_filter(pod: Mapping, args: DefaultEvictorArgs) -> List[str]:
    """Reasons the pod is NOT evictable; empty list = evictable."""
    reasons: List[str] = []
    labels = pod.get("labels") or {}
    annotations = pod.get("annotations") or {}
    owner_kinds = {o.get("kind") for o in pod.get("owner_references") or []}
    if not owner_kinds:
        # upstream DefaultEvictor: bare pods (no controller to recreate
        # them) are never evictable, except Failed ones when
        # evictFailedBarePods is set
        if not (args.evict_failed_bare_pods and pod.get("phase") == "Failed"):
            reasons.append("pod is a bare pod without owner")
    if "DaemonSet" in owner_kinds:
        reasons.append("pod is owned by a DaemonSet")
    if pod.get("mirror") or "kubernetes.io/config.mirror" in annotations:
        reasons.append("pod is a static/mirror pod")
    prio = int(pod.get("priority") or 0)
    if not args.evict_system_critical_pods:
        if prio >= PRIORITY_CRITICAL:
            reasons.append("pod is system-critical")
        if args.priority_threshold is not None and prio >= args.priority_threshold:
            reasons.append("pod priority above threshold")
    if not args.evict_local_storage_pods and pod.get("has_local_storage"):
        reasons.append("pod uses local storage")
    if args.ignore_pvc_pods and pod.get("has_pvc"):
        reasons.append("pod uses a PVC")
    if annotations.get("descheduler.alpha.kubernetes.io/evict") in ("false", False):
        reasons.append("pod opted out of eviction")
    if not _matches(args.label_selector, labels):
        reasons.append("pod does not match the evictor label selector")
    return reasons


@dataclasses.dataclass(frozen=True)
class TooManyRestartsArgs:
    pod_restart_threshold: int = 100
    include_init_containers: bool = False


def remove_pods_having_too_many_restarts(
    pods: Sequence[Mapping], args: TooManyRestartsArgs
) -> List[Mapping]:
    """Upstream RemovePodsHavingTooManyRestarts: total container restarts
    >= threshold selects the pod for eviction."""
    out = []
    for pod in pods:
        restarts = sum(int(c.get("restart_count", 0)) for c in pod.get("containers", []))
        if args.include_init_containers:
            restarts += sum(
                int(c.get("restart_count", 0))
                for c in pod.get("init_containers", [])
            )
        if restarts >= args.pod_restart_threshold:
            out.append(pod)
    return out


def remove_duplicates(pods: Sequence[Mapping]) -> List[Mapping]:
    """Upstream RemoveDuplicates: for each (owner, node) keep one replica,
    select the rest for eviction so replicas spread across nodes."""
    seen: Dict[tuple, Mapping] = {}
    dupes: List[Mapping] = []
    for pod in pods:
        owners = tuple(
            sorted(
                (o.get("kind", ""), o.get("name", ""))
                for o in pod.get("owner_references") or []
            )
        )
        if not owners:
            continue
        key = (owners, pod.get("node"))
        if key in seen:
            dupes.append(pod)
        else:
            seen[key] = pod
    return dupes


def remove_pods_violating_node_affinity(
    pods: Sequence[Mapping], nodes: Sequence[Mapping]
) -> List[Mapping]:
    """Upstream RemovePodsViolatingNodeAffinity (requiredDuringScheduling
    IgnoredDuringExecution re-checked): pod's required node selector no
    longer matches the labels of the node it runs on."""
    node_labels = {n["name"]: n.get("labels") or {} for n in nodes}
    out = []
    for pod in pods:
        required = pod.get("node_selector") or {}
        if not required:
            continue
        labels = node_labels.get(pod.get("node"), {})
        if not _matches(required, labels):
            out.append(pod)
    return out


def _dedup_by_id(pods: Sequence[Mapping]) -> List[Mapping]:
    """Stable de-dup of pod dicts by object identity."""
    seen = set()
    uniq: List[Mapping] = []
    for p in pods:
        if id(p) not in seen:
            seen.add(id(p))
            uniq.append(p)
    return uniq


def remove_pods_violating_interpod_antiaffinity(
    pods: Sequence[Mapping],
) -> List[Mapping]:
    """Upstream RemovePodsViolatingInterPodAntiAffinity: a pod colocated
    on the same node with a pod whose required anti-affinity selector
    matches it is selected for eviction."""
    by_node: Dict[str, List[Mapping]] = {}
    for pod in pods:
        by_node.setdefault(pod.get("node", ""), []).append(pod)
    out = []
    for node_pods in by_node.values():
        for holder in node_pods:
            selector = holder.get("anti_affinity_selector")
            if not selector:
                continue
            for other in node_pods:
                if other is holder:
                    continue
                if _matches(selector, other.get("labels") or {}):
                    out.append(other)
    return _dedup_by_id(out)


@dataclasses.dataclass
class DeschedulePluginResult:
    selected: List[Mapping]
    evicted: List[Mapping]
    skipped: Dict[str, List[str]]


def run_deschedule_plugin(
    selector: Callable[[], List[Mapping]],
    evictor_args: DefaultEvictorArgs,
    evict: Callable[[Mapping], bool],
) -> DeschedulePluginResult:
    """The adaptor glue (framework/plugins/kubernetes): selection ->
    DefaultEvictor filter -> rate-limited eviction."""
    selected = selector()
    evicted: List[Mapping] = []
    skipped: Dict[str, List[str]] = {}
    for pod in selected:
        reasons = default_evictor_filter(pod, evictor_args)
        if reasons:
            skipped[pod.get("name", "?")] = reasons
            continue
        if evict(pod):
            evicted.append(pod)
    return DeschedulePluginResult(selected, evicted, skipped)


# ---------------------------------------------------------------------------
# RemovePodsViolatingNodeTaints
# ---------------------------------------------------------------------------


def _tolerates(toleration: Mapping, taint: Mapping) -> bool:
    """Upstream v1.Toleration.ToleratesTaint: operator Exists matches any
    value; Equal (the default) requires equal values; an empty key with
    Exists matches every taint; an empty effect matches every effect."""
    op = toleration.get("operator") or "Equal"
    t_effect = toleration.get("effect") or ""
    if t_effect and t_effect != taint.get("effect"):
        return False
    key = toleration.get("key") or ""
    if not key:
        return op == "Exists"
    if key != taint.get("key"):
        return False
    if op == "Exists":
        return True
    return (toleration.get("value") or "") == (taint.get("value") or "")


@dataclasses.dataclass(frozen=True)
class NodeTaintsArgs:
    """Upstream RemovePodsViolatingNodeTaints args: which taint keys are
    considered (None = all), and whether PreferNoSchedule counts."""

    excluded_taints: Sequence[str] = ()
    included_taints: Sequence[str] = ()  # empty = all
    include_prefer_no_schedule: bool = False


def remove_pods_violating_node_taints(
    pods: Sequence[Mapping],
    nodes: Sequence[Mapping],
    args: Optional[NodeTaintsArgs] = None,
) -> List[Mapping]:
    """Upstream RemovePodsViolatingNodeTaints: select pods whose node
    carries a NoSchedule (optionally PreferNoSchedule) taint the pod does
    not tolerate — the scheduler would no longer place them there."""
    args = args or NodeTaintsArgs()
    effects = {"NoSchedule"}
    if args.include_prefer_no_schedule:
        effects.add("PreferNoSchedule")
    node_taints: Dict[str, List[Mapping]] = {}
    for n in nodes:
        taints = []
        for t in n.get("taints") or []:
            key = t.get("key", "")
            if t.get("effect") not in effects:
                continue
            if args.excluded_taints and key in args.excluded_taints:
                continue
            if args.included_taints and key not in args.included_taints:
                continue
            taints.append(t)
        node_taints[n["name"]] = taints
    out = []
    for pod in pods:
        taints = node_taints.get(pod.get("node"), [])
        if not taints:
            continue
        tolerations = pod.get("tolerations") or []
        untolerated = any(
            not any(_tolerates(tol, taint) for tol in tolerations)
            for taint in taints
        )
        if untolerated:
            out.append(pod)
    return out


# ---------------------------------------------------------------------------
# RemoveFailedPods
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FailedPodsArgs:
    """Upstream RemoveFailedPods args (reasons/min lifetime/owner-kind
    exclusion; including_init_containers widens the reason scan)."""

    reasons: Sequence[str] = ()  # empty = any failure reason
    min_pod_lifetime_seconds: Optional[int] = None
    exclude_owner_kinds: Sequence[str] = ()
    including_init_containers: bool = False


def remove_failed_pods(
    pods: Sequence[Mapping],
    args: Optional[FailedPodsArgs] = None,
    now: float = 0.0,
) -> List[Mapping]:
    """Upstream RemoveFailedPods: Failed-phase pods (optionally filtered
    by failure reason and minimum age) are selected so their controllers
    recreate them."""
    args = args or FailedPodsArgs()
    out = []
    for pod in pods:
        if pod.get("phase") != "Failed":
            continue
        owner_kinds = {o.get("kind") for o in pod.get("owner_references") or []}
        if args.exclude_owner_kinds and owner_kinds & set(args.exclude_owner_kinds):
            continue
        if args.min_pod_lifetime_seconds is not None:
            start = pod.get("start_time")
            if start is None:
                continue  # unknown age cannot pass an age gate
            if now - float(start) < args.min_pod_lifetime_seconds:
                continue
        if args.reasons:
            reasons = {pod.get("reason", "")}
            containers = list(pod.get("containers") or [])
            if args.including_init_containers:
                containers += list(pod.get("init_containers") or [])
            for c in containers:
                reasons.add(c.get("reason", ""))
            if not reasons & set(args.reasons):
                continue
        out.append(pod)
    return out


# ---------------------------------------------------------------------------
# PodLifeTime
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PodLifeTimeArgs:
    """Upstream PodLifeTime args: age limit + optional phase/label gates."""

    max_pod_life_time_seconds: int = 86400
    states: Sequence[str] = ()  # empty = any phase
    label_selector: Optional[Mapping[str, str]] = None


def pod_life_time(
    pods: Sequence[Mapping],
    args: Optional[PodLifeTimeArgs] = None,
    now: float = 0.0,
) -> List[Mapping]:
    """Upstream PodLifeTime: pods older than the limit (matching the
    state/label gates) are selected for refresh."""
    args = args or PodLifeTimeArgs()
    out = []
    for pod in pods:
        if args.states and pod.get("phase", "Running") not in args.states:
            continue
        if not _matches(args.label_selector, pod.get("labels") or {}):
            continue
        start = pod.get("start_time")
        if start is None:
            continue  # unknown age: never treat as infinitely old
        if now - float(start) > args.max_pod_life_time_seconds:
            out.append(pod)
    return out


# ---------------------------------------------------------------------------
# RemovePodsViolatingTopologySpreadConstraint
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TopologySpreadArgs:
    """Upstream RemovePodsViolatingTopologySpreadConstraint: balance each
    constraint's domains until skew <= max_skew (hard constraints only
    unless include_soft_constraints)."""

    include_soft_constraints: bool = False


def remove_pods_violating_topology_spread(
    pods: Sequence[Mapping],
    nodes: Sequence[Mapping],
    args: Optional[TopologySpreadArgs] = None,
) -> List[Mapping]:
    """Upstream balanceDomains: group each constraint's matching pods by
    the topology value of their node; while (max - min) > maxSkew, move
    pods off the largest domains — the moved pods are the selection.

    Constraints ride the pods: ``{"topology_spread": [{"max_skew": 1,
    "topology_key": "zone", "when_unsatisfiable": "DoNotSchedule",
    "label_selector": {...}}]}`` — the reference reads them from each
    namespace's pods the same way.
    """
    args = args or TopologySpreadArgs()
    node_topo: Dict[str, Mapping] = {
        n["name"]: (n.get("labels") or {}) for n in nodes
    }
    out: List[Mapping] = []
    seen_constraints = set()
    for pod in pods:
        for c in pod.get("topology_spread") or []:
            unsat = c.get("when_unsatisfiable", "DoNotSchedule")
            if unsat != "DoNotSchedule" and not args.include_soft_constraints:
                continue
            key = (
                c.get("topology_key", ""),
                int(c.get("max_skew", 1)),
                tuple(sorted((c.get("label_selector") or {}).items())),
            )
            if key in seen_constraints:
                continue
            seen_constraints.add(key)
            topo_key, max_skew, selector = key[0], key[1], dict(key[2])

            domains: Dict[str, List[Mapping]] = {}
            # every node with the topology label is a domain, even when
            # empty (upstream counts zero-pod domains for skew)
            for n in nodes:
                val = (n.get("labels") or {}).get(topo_key)
                if val is not None:
                    domains.setdefault(val, [])
            for p in pods:
                if not _matches(selector, p.get("labels") or {}):
                    continue
                val = node_topo.get(p.get("node"), {}).get(topo_key)
                if val is None:
                    continue
                domains.setdefault(val, []).append(p)
            if len(domains) < 2:
                continue
            counts = {d: len(ps) for d, ps in domains.items()}
            moved: List[Mapping] = []
            while True:
                src = max(sorted(counts), key=lambda d: counts[d])
                dst = min(sorted(counts), key=lambda d: counts[d])
                diff = counts[src] - counts[dst]
                # moving one pod changes the gap by 2: when the gap is
                # already <= 1 no move can improve it (an unsatisfiable
                # max_skew=0 on an odd split must select nothing, not
                # ping-pong every pod out)
                if diff <= max(max_skew, 1):
                    break
                victims = [p for p in domains[src] if p not in moved]
                if not victims:
                    break
                moved.append(victims[-1])  # newest-listed first, like the
                # upstream sort preferring lower-priority/newer victims
                counts[src] -= 1
                counts[dst] += 1
            out.extend(moved)
    return _dedup_by_id(out)
