"""Upstream-descheduler adaptor plugins.

Reference: ``pkg/descheduler/framework/plugins/kubernetes`` wraps
sigs.k8s.io/descheduler plugins (DefaultEvictor, RemovePodsViolating*,
RemoveDuplicates, RemovePodsHavingTooManyRestarts) into the koord
descheduler framework (``framework/types.go:80 DeschedulePlugin``).
Here the same plugin set as pure functions over pod/node dicts, composed
with the evictions/ rate-limited evictor the way the adaptor wires the
upstream evictor seam.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Mapping, Optional, Sequence

PRIORITY_CRITICAL = 2_000_000_000  # system-cluster-critical


@dataclasses.dataclass(frozen=True)
class DefaultEvictorArgs:
    """sigs.k8s.io defaultevictor semantics: which pods are evictable."""

    evict_system_critical_pods: bool = False
    evict_local_storage_pods: bool = False
    evict_failed_bare_pods: bool = False
    ignore_pvc_pods: bool = False
    priority_threshold: Optional[int] = None
    label_selector: Optional[Mapping[str, str]] = None


def _matches(selector: Optional[Mapping[str, str]], labels: Mapping) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


def default_evictor_filter(pod: Mapping, args: DefaultEvictorArgs) -> List[str]:
    """Reasons the pod is NOT evictable; empty list = evictable."""
    reasons: List[str] = []
    labels = pod.get("labels") or {}
    annotations = pod.get("annotations") or {}
    owner_kinds = {o.get("kind") for o in pod.get("owner_references") or []}
    if not owner_kinds:
        # upstream DefaultEvictor: bare pods (no controller to recreate
        # them) are never evictable, except Failed ones when
        # evictFailedBarePods is set
        if not (args.evict_failed_bare_pods and pod.get("phase") == "Failed"):
            reasons.append("pod is a bare pod without owner")
    if "DaemonSet" in owner_kinds:
        reasons.append("pod is owned by a DaemonSet")
    if pod.get("mirror") or "kubernetes.io/config.mirror" in annotations:
        reasons.append("pod is a static/mirror pod")
    prio = int(pod.get("priority") or 0)
    if not args.evict_system_critical_pods:
        if prio >= PRIORITY_CRITICAL:
            reasons.append("pod is system-critical")
        if args.priority_threshold is not None and prio >= args.priority_threshold:
            reasons.append("pod priority above threshold")
    if not args.evict_local_storage_pods and pod.get("has_local_storage"):
        reasons.append("pod uses local storage")
    if args.ignore_pvc_pods and pod.get("has_pvc"):
        reasons.append("pod uses a PVC")
    if annotations.get("descheduler.alpha.kubernetes.io/evict") in ("false", False):
        reasons.append("pod opted out of eviction")
    if not _matches(args.label_selector, labels):
        reasons.append("pod does not match the evictor label selector")
    return reasons


@dataclasses.dataclass(frozen=True)
class TooManyRestartsArgs:
    pod_restart_threshold: int = 100
    include_init_containers: bool = False


def remove_pods_having_too_many_restarts(
    pods: Sequence[Mapping], args: TooManyRestartsArgs
) -> List[Mapping]:
    """Upstream RemovePodsHavingTooManyRestarts: total container restarts
    >= threshold selects the pod for eviction."""
    out = []
    for pod in pods:
        restarts = sum(int(c.get("restart_count", 0)) for c in pod.get("containers", []))
        if args.include_init_containers:
            restarts += sum(
                int(c.get("restart_count", 0))
                for c in pod.get("init_containers", [])
            )
        if restarts >= args.pod_restart_threshold:
            out.append(pod)
    return out


def remove_duplicates(pods: Sequence[Mapping]) -> List[Mapping]:
    """Upstream RemoveDuplicates: for each (owner, node) keep one replica,
    select the rest for eviction so replicas spread across nodes."""
    seen: Dict[tuple, Mapping] = {}
    dupes: List[Mapping] = []
    for pod in pods:
        owners = tuple(
            sorted(
                (o.get("kind", ""), o.get("name", ""))
                for o in pod.get("owner_references") or []
            )
        )
        if not owners:
            continue
        key = (owners, pod.get("node"))
        if key in seen:
            dupes.append(pod)
        else:
            seen[key] = pod
    return dupes


def remove_pods_violating_node_affinity(
    pods: Sequence[Mapping], nodes: Sequence[Mapping]
) -> List[Mapping]:
    """Upstream RemovePodsViolatingNodeAffinity (requiredDuringScheduling
    IgnoredDuringExecution re-checked): pod's required node selector no
    longer matches the labels of the node it runs on."""
    node_labels = {n["name"]: n.get("labels") or {} for n in nodes}
    out = []
    for pod in pods:
        required = pod.get("node_selector") or {}
        if not required:
            continue
        labels = node_labels.get(pod.get("node"), {})
        if not _matches(required, labels):
            out.append(pod)
    return out


def remove_pods_violating_interpod_antiaffinity(
    pods: Sequence[Mapping],
) -> List[Mapping]:
    """Upstream RemovePodsViolatingInterPodAntiAffinity: a pod colocated
    on the same node with a pod whose required anti-affinity selector
    matches it is selected for eviction."""
    by_node: Dict[str, List[Mapping]] = {}
    for pod in pods:
        by_node.setdefault(pod.get("node", ""), []).append(pod)
    out = []
    for node_pods in by_node.values():
        for holder in node_pods:
            selector = holder.get("anti_affinity_selector")
            if not selector:
                continue
            for other in node_pods:
                if other is holder:
                    continue
                if _matches(selector, other.get("labels") or {}):
                    out.append(other)
    # stable de-dup
    seen = set()
    uniq = []
    for p in out:
        key = id(p)
        if key not in seen:
            seen.add(key)
            uniq.append(p)
    return uniq


@dataclasses.dataclass
class DeschedulePluginResult:
    selected: List[Mapping]
    evicted: List[Mapping]
    skipped: Dict[str, List[str]]


def run_deschedule_plugin(
    selector: Callable[[], List[Mapping]],
    evictor_args: DefaultEvictorArgs,
    evict: Callable[[Mapping], bool],
) -> DeschedulePluginResult:
    """The adaptor glue (framework/plugins/kubernetes): selection ->
    DefaultEvictor filter -> rate-limited eviction."""
    selected = selector()
    evicted: List[Mapping] = []
    skipped: Dict[str, List[str]] = {}
    for pod in selected:
        reasons = default_evictor_filter(pod, evictor_args)
        if reasons:
            skipped[pod.get("name", "?")] = reasons
            continue
        if evict(pod):
            evicted.append(pod)
    return DeschedulePluginResult(selected, evicted, skipped)
