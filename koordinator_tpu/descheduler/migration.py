"""PodMigrationJob controller: arbitration + migration state machine.

Reference: ``pkg/descheduler/controllers/migration`` — ``controller.go:218
Reconcile`` / ``:241 doMigrate`` drive each job Pending -> (arbitration) ->
Running -> [reserve -> wait-bound ->] evict -> Succeeded/Failed, with TTL
abort; the arbitrator (``arbitrator/filter.go``) gates how many concurrent
migrations a node / namespace / workload may carry and sorts candidates;
``controller.go:661 evictPod`` performs the eviction.

Everything here is a host-side state machine over plain-dict jobs; the
eviction and reservation seams are callbacks so the scheduler's reservation
plugin and the evictor plug in.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Mapping, Optional, Sequence

PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"
ABORTED = "Aborted"

REASON_TIMEOUT = "Timeout"
REASON_FAILED_CREATE_RESERVATION = "FailedCreateReservation"
REASON_WAIT_RESERVATION = "WaitForReservationBound"
REASON_FAILED_EVICT = "FailedEvict"
REASON_EVICTING = "Evicting"


@dataclasses.dataclass
class MigrationControllerArgs:
    """reference config.MigrationControllerArgs (subset with defaults)."""

    max_concurrent_reclaims_per_node: Optional[int] = 1
    max_concurrent_reclaims_per_namespace: Optional[int] = None
    max_concurrent_reclaims_per_workload: Optional[int] = None
    max_unavailable_per_workload_fraction: float = 0.0  # extra guard, 0=off
    default_job_ttl_seconds: float = 300.0
    default_job_mode: str = "ReservationFirst"  # or EvictDirectly


@dataclasses.dataclass
class MigrationJob:
    name: str
    pod: Mapping  # pod dict: name, namespace, node, workload (owner key)
    phase: str = PENDING
    reason: str = ""
    mode: str = ""
    creation_time: float = 0.0
    reservation_name: Optional[str] = None
    reservation_bound: bool = False
    passed_arbitration: bool = False


class Arbitrator:
    """Filter + sort of pending jobs (reference ``arbitrator/``)."""

    def __init__(self, args: MigrationControllerArgs):
        self.args = args

    def arbitrate(
        self,
        pending: Sequence[MigrationJob],
        active: Sequence[MigrationJob],
    ) -> List[MigrationJob]:
        """Return the pending jobs allowed to start, ordered.  Concurrency
        caps count jobs already Running plus ones admitted this round
        (reference ``filterMaxMigratingPerNode`` :218,
        ``filterMaxMigratingPerNamespace`` :260,
        ``filterMaxMigratingOrUnavailablePerWorkload`` :291)."""
        per_node = _count_by(active, lambda j: j.pod.get("node"))
        per_ns = _count_by(active, lambda j: j.pod.get("namespace", "default"))
        per_workload = _count_by(active, lambda j: j.pod.get("workload"))
        admitted: List[MigrationJob] = []
        # oldest jobs first (stable by creation time then name)
        for job in sorted(pending, key=lambda j: (j.creation_time, j.name)):
            node = job.pod.get("node")
            ns = job.pod.get("namespace", "default")
            workload = job.pod.get("workload")
            a = self.args
            if (
                a.max_concurrent_reclaims_per_node is not None
                and node is not None
                and per_node.get(node, 0) >= a.max_concurrent_reclaims_per_node
            ):
                continue
            if (
                a.max_concurrent_reclaims_per_namespace is not None
                and per_ns.get(ns, 0) >= a.max_concurrent_reclaims_per_namespace
            ):
                continue
            if (
                a.max_concurrent_reclaims_per_workload is not None
                and workload is not None
                and per_workload.get(workload, 0) >= a.max_concurrent_reclaims_per_workload
            ):
                continue
            job.passed_arbitration = True
            admitted.append(job)
            if node is not None:
                per_node[node] = per_node.get(node, 0) + 1
            per_ns[ns] = per_ns.get(ns, 0) + 1
            if workload is not None:
                per_workload[workload] = per_workload.get(workload, 0) + 1
        return admitted


class MigrationController:
    """Reconciles jobs one tick at a time (reference ``Reconcile`` :218)."""

    def __init__(
        self,
        args: Optional[MigrationControllerArgs] = None,
        create_reservation: Optional[Callable[[MigrationJob], Optional[str]]] = None,
        reservation_bound: Optional[Callable[[str], bool]] = None,
        evict: Optional[Callable[[Mapping], bool]] = None,
    ):
        self.args = args or MigrationControllerArgs()
        self.arbitrator = Arbitrator(self.args)
        self.create_reservation = create_reservation
        self.reservation_bound = reservation_bound
        self.evict = evict or (lambda pod: True)
        self.jobs: Dict[str, MigrationJob] = {}

    def submit(self, job: MigrationJob) -> MigrationJob:
        """Idempotent: a live job with the same name wins — replanning the
        same pod next tick must not clobber an in-flight job's reservation
        state or restart its TTL."""
        existing = self.jobs.get(job.name)
        if existing is not None and existing.phase in (PENDING, RUNNING):
            return existing
        job.mode = job.mode or self.args.default_job_mode
        self.jobs[job.name] = job
        return job

    def reconcile(self, now: float = 0.0) -> None:
        """One pass: TTL-abort stale jobs, arbitrate pending, advance
        running jobs through reservation -> eviction."""
        for job in self.jobs.values():
            if job.phase in (PENDING, RUNNING) and now - job.creation_time > self.args.default_job_ttl_seconds:
                job.phase, job.reason = FAILED, REASON_TIMEOUT

        pending = [j for j in self.jobs.values() if j.phase == PENDING and not j.passed_arbitration]
        running = [j for j in self.jobs.values() if j.phase == RUNNING or (j.phase == PENDING and j.passed_arbitration)]
        for job in self.arbitrator.arbitrate(pending, running):
            job.phase = RUNNING

        for job in [j for j in self.jobs.values() if j.phase == RUNNING]:
            self._advance(job)

    def _advance(self, job: MigrationJob) -> None:
        if job.mode == "ReservationFirst":
            if job.reservation_name is None:
                if self.create_reservation is None:
                    job.phase, job.reason = FAILED, REASON_FAILED_CREATE_RESERVATION
                    return
                name = self.create_reservation(job)
                if name is None:
                    job.phase, job.reason = FAILED, REASON_FAILED_CREATE_RESERVATION
                    return
                job.reservation_name = name
            if not job.reservation_bound:
                bound = self.reservation_bound(job.reservation_name) if self.reservation_bound else True
                if not bound:
                    job.reason = REASON_WAIT_RESERVATION
                    return  # try again next tick
                job.reservation_bound = True
        if self.evict(job.pod):
            job.phase, job.reason = SUCCEEDED, ""
        else:
            job.phase, job.reason = FAILED, REASON_FAILED_EVICT

    def scavenge(self, now: float, ttl_after_done: float = 600.0) -> int:
        """Drop finished jobs older than the TTL (reference job GC)."""
        done = [
            name
            for name, j in self.jobs.items()
            if j.phase in (SUCCEEDED, FAILED, ABORTED) and now - j.creation_time > ttl_after_done
        ]
        for name in done:
            del self.jobs[name]
        return len(done)


def _count_by(jobs: Sequence[MigrationJob], key) -> Dict:
    out: Dict = {}
    for j in jobs:
        k = key(j)
        if k is None:
            continue
        out[k] = out.get(k, 0) + 1
    return out
