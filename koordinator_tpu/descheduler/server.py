"""koord-descheduler app/server: CLI, leader election, the ticking loop.

Mirrors ``cmd/koord-descheduler/app/server.go``: flags (:70), dry-run,
profiles, leader election (:182-200) gating the Descheduler loop — only
the elected leader ticks ``descheduler_once``; on losing the lease the
loop pauses, on regaining it resumes (the reference restarts the loop in
OnStartedLeading).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Mapping, Optional, Sequence

from koordinator_tpu.descheduler.evictions import PodEvictor
from koordinator_tpu.descheduler.migration import MigrationController
from koordinator_tpu.descheduler.runtime import (
    Descheduler,
    DeschedulerProfile,
    PluginSet,
)
from koordinator_tpu.httpserving import (
    HTTPLifecycle,
    format_thread_stacks,
    reply_text,
)
from koordinator_tpu.leaderelection import LeaderElector


class DeschedulerServer:
    def __init__(
        self,
        profiles: Sequence[DeschedulerProfile],
        nodes_fn: Callable[[], List[Mapping]],
        *,
        lease_path: str = "/tmp/koord-descheduler/leader.lease",
        identity: Optional[str] = None,
        descheduling_interval: float = 120.0,
        dry_run: bool = False,
        http_host: str = "127.0.0.1",
        http_port: int = 0,
        migration: Optional[MigrationController] = None,
        evictor: Optional[PodEvictor] = None,
    ):
        self.descheduler = Descheduler(
            profiles,
            nodes_fn,
            descheduling_interval=descheduling_interval,
            dry_run=dry_run,
            migration=migration,
            evictor=evictor,
        )
        self.elector = LeaderElector(
            lease_path, identity or f"{socket.gethostname()}-{os.getpid()}"
        )
        self.ticks = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/debug/stacks":
                    reply_text(self, format_thread_stacks())
                    return
                if self.path == "/healthz":
                    doc = {
                        "ok": True,
                        "leader": outer.elector.is_leader,
                        "ticks": outer.ticks,
                    }
                    data = json.dumps(doc).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                else:
                    self.send_response(404)
                    self.end_headers()

        self._httpd = ThreadingHTTPServer((http_host, http_port), Handler)
        self._http = HTTPLifecycle(self._httpd)

    @property
    def http_port(self) -> int:
        return self._httpd.server_address[1]

    def _loop(self, sleep):
        # the leader-gated tick loop: followers idle at the retry period
        while not self._stop.is_set():
            if self.elector.is_leader:
                self.descheduler.descheduler_once()
                self.ticks += 1
                interval = self.descheduler.descheduling_interval
                if interval <= 0:
                    return
                sleep(interval)
            else:
                sleep(self.elector.retry_period)

    def start(self, sleep=None) -> "DeschedulerServer":
        sleep = sleep or (lambda s: self._stop.wait(s))
        for target in (
            lambda: self.elector.run(),
            lambda: self._loop(sleep),
        ):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        self._http.start()
        return self

    def stop(self):
        self._stop.set()
        self.elector.stop()
        self._http.stop()
        for t in self._threads[:2]:
            t.join(timeout=5)


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="koord-descheduler")
    ap.add_argument(
        "--descheduling-interval", type=float, default=120.0,
        help="seconds between ticks; 0 runs once (descheduler.go:251)",
    )
    ap.add_argument("--dry-run", action="store_true")
    ap.add_argument(
        "--lease", default="/tmp/koord-descheduler/leader.lease"
    )
    ap.add_argument("--identity", default=None)
    ap.add_argument("--http-host", default="127.0.0.1")
    ap.add_argument("--http-port", type=int, default=10258)
    ap.add_argument(
        "--nodes-json", default=None,
        help="path to a JSON node list (standalone mode node source)",
    )
    return ap


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)

    def nodes_fn():
        if args.nodes_json and os.path.exists(args.nodes_json):
            with open(args.nodes_json) as fh:
                return json.load(fh)
        return []

    server = DeschedulerServer(
        [DeschedulerProfile(plugins=PluginSet(balance=["LowNodeLoad"]))],
        nodes_fn,
        lease_path=args.lease,
        identity=args.identity,
        descheduling_interval=args.descheduling_interval,
        dry_run=args.dry_run,
        http_port=args.http_port,
        http_host=args.http_host,
    ).start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
