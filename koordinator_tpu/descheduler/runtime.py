"""Profile-based descheduler runtime + main loop.

Mirrors the reference's own plugin framework (NOT upstream descheduler):

* ``DeschedulerProfile`` / plugin registry / framework instance —
  reference ``pkg/descheduler/framework/runtime/framework.go:121
  NewFramework``, plugin sets per extension point
  (``framework/types.go:80 DeschedulePlugin``, ``:85 BalancePlugin``).
* ``Framework.run_deschedule_plugins`` / ``run_balance_plugins`` —
  ``framework/runtime/framework.go:310,330`` (aggregate errors, keep
  running remaining plugins).
* ``Descheduler.descheduler_once`` — ``pkg/descheduler/descheduler.go:259``:
  ready-node gate (<= 1 node aborts the tick), eviction-limiter reset,
  ALL profiles' Deschedule plugins then ALL profiles' Balance plugins.
* ``Descheduler.start`` — ``descheduler.go:241``: non-sliding ticks at
  ``descheduling_interval``; interval 0 = run once.

Evictions flow LowNodeLoad -> MigrationController (PodMigrationJob
arbitration/reservation) -> PodEvictor, the reference's
MigrationController evictor path (``controllers/migration/controller.go``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from koordinator_tpu.descheduler.anomaly import BasicDetector
from koordinator_tpu.descheduler.evictions import PodEvictor
from koordinator_tpu.descheduler.k8s_plugins import (
    DefaultEvictorArgs,
    default_evictor_filter,
    pod_life_time,
    remove_duplicates,
    remove_failed_pods,
    remove_pods_having_too_many_restarts,
    remove_pods_violating_interpod_antiaffinity,
    remove_pods_violating_node_affinity,
    remove_pods_violating_node_taints,
    remove_pods_violating_topology_spread,
    TooManyRestartsArgs,
)
from koordinator_tpu.descheduler.lownodeload import LowNodeLoadArgs, balance
from koordinator_tpu.descheduler.migration import (
    MigrationController,
    MigrationControllerArgs,
    MigrationJob,
)


@dataclasses.dataclass
class Status:
    """framework.Status (framework/types.go:32): nil err = success."""

    err: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.err is None


@dataclasses.dataclass
class PluginSet:
    """Enabled plugin names per extension point (profile plugin sets)."""

    deschedule: Sequence[str] = ()
    balance: Sequence[str] = ()
    evict: Sequence[str] = ("MigrationController",)


@dataclasses.dataclass
class DeschedulerProfile:
    """config.DeschedulerProfile: name + plugin set + per-plugin args."""

    name: str = "default"
    plugins: PluginSet = dataclasses.field(default_factory=PluginSet)
    plugin_config: Mapping[str, object] = dataclasses.field(default_factory=dict)


class Framework:
    """One profile's instantiated plugins + shared handle state
    (framework/runtime/framework.go:121 NewFramework)."""

    def __init__(
        self,
        profile: DeschedulerProfile,
        registry: Mapping[str, Callable],
        evictor: PodEvictor,
        migration: Optional[MigrationController] = None,
        dry_run: bool = False,
    ):
        self.profile = profile
        self.evictor = evictor
        self.migration = migration
        self.dry_run = dry_run
        self.planned_only: List[Mapping] = []  # dry-run audit trail
        self.detectors: Dict[str, BasicDetector] = {}
        self._deschedule = []
        self._balance = []
        for name in profile.plugins.deschedule:
            if name not in registry:
                raise ValueError(f"unknown deschedule plugin {name!r}")
            self._deschedule.append((name, registry[name](self, profile.plugin_config.get(name))))
        for name in profile.plugins.balance:
            if name not in registry:
                raise ValueError(f"unknown balance plugin {name!r}")
            self._balance.append((name, registry[name](self, profile.plugin_config.get(name))))

    # -- Evictor handle (evictorProxy, framework.go:294): plugins call
    # this; it routes through the MigrationController when the profile's
    # evict plugin set enables it --
    def evict(self, pod: Mapping, node: str, reason: str = "") -> bool:
        if self.dry_run:
            # evictorProxy dry-run: report the decision, touch nothing
            self.planned_only.append(
                {"pod": pod.get("name"), "node": node, "reason": reason}
            )
            return True
        if (
            self.migration is not None
            and "MigrationController" in self.profile.plugins.evict
        ):
            job = self.migration.submit(
                MigrationJob(
                    name=f"mj-{pod.get('namespace', 'default')}-{pod.get('name')}",
                    pod=dict(pod, node=node),
                    reason=reason,
                    creation_time=self._now,
                )
            )
            return job is not None
        return self.evictor.evict(pod, node, reason=reason)

    _now: float = 0.0

    def run_deschedule_plugins(self, nodes: Sequence[Mapping]) -> Status:
        errs = []
        for name, fn in self._deschedule:
            try:
                fn(nodes)
            except Exception as exc:  # keep running remaining plugins
                errs.append(f"{name}: {exc}")
        return Status("; ".join(errs) or None)

    def run_balance_plugins(self, nodes: Sequence[Mapping]) -> Status:
        errs = []
        for name, fn in self._balance:
            try:
                fn(nodes)
            except Exception as exc:
                errs.append(f"{name}: {exc}")
        return Status("; ".join(errs) or None)


# ---------------------------------------------------------------------------
# Built-in plugin registry (framework/plugins/registry.go:26)
# ---------------------------------------------------------------------------


def _low_node_load(fw: Framework, args) -> Callable:
    args = args or LowNodeLoadArgs()
    evictor_args = DefaultEvictorArgs()

    def run(nodes):
        balance(
            args,
            nodes,
            # route through the framework's evictor proxy so the
            # MigrationController path applies
            _EvictorAdapter(fw),
            detectors=fw.detectors,
            pod_filter=lambda p: not default_evictor_filter(p, evictor_args),
            now=fw._now,
        )

    return run


class _EvictorAdapter:
    """PodEvictor look-alike routing evictions through Framework.evict."""

    def __init__(self, fw: Framework):
        self.fw = fw

    def evict(self, pod, node, reason=""):
        return self.fw.evict(pod, node, reason=reason)


def _deschedule_adaptor(reason: str, select):
    """Wrap the k8s-descheduler adaptor plugins (k8s_plugins.py) as
    Deschedule plugins evicting through the framework.  ``select(pods,
    nodes, args, now)`` returns the victims per node; ``reason`` names
    the plugin in the eviction audit trail.  ``now`` is the framework's
    tick clock so age gates stay fake-clock-testable."""

    def factory(fw: Framework, args):
        def run(nodes):
            for nd in nodes:
                pods = nd.get("pods", [])
                for pod in select(pods, nodes, args, fw._now):
                    fw.evict(pod, nd["name"], reason=reason)

        return run

    return factory


def _cluster_deschedule_adaptor(reason: str, select):
    """Like _deschedule_adaptor but selection sees the CLUSTER-WIDE pod
    set in one call — required for plugins whose decision is a global
    property (topology spread skew is computed across every domain; a
    per-node view would see counts like (3, 0) in a balanced cluster and
    evict from every node)."""

    def factory(fw: Framework, args):
        def run(nodes):
            node_of = {}
            all_pods = []
            for nd in nodes:
                for pod in nd.get("pods", []):
                    all_pods.append(pod)
                    node_of[id(pod)] = nd["name"]
            for pod in select(all_pods, nodes, args, fw._now):
                fw.evict(
                    pod,
                    node_of.get(id(pod), pod.get("node", "")),
                    reason=reason,
                )

        return run

    return factory


DEFAULT_REGISTRY: Dict[str, Callable] = {
    "LowNodeLoad": _low_node_load,
    "RemovePodsHavingTooManyRestarts": _deschedule_adaptor(
        "RemovePodsHavingTooManyRestarts",
        lambda pods, nodes, args, now: remove_pods_having_too_many_restarts(
            pods, args or TooManyRestartsArgs()
        ),
    ),
    "RemoveDuplicates": _deschedule_adaptor(
        "RemoveDuplicates",
        lambda pods, nodes, args, now: remove_duplicates(pods),
    ),
    "RemovePodsViolatingNodeAffinity": _deschedule_adaptor(
        "RemovePodsViolatingNodeAffinity",
        lambda pods, nodes, args, now: remove_pods_violating_node_affinity(
            pods, nodes
        ),
    ),
    "RemovePodsViolatingInterPodAntiAffinity": _deschedule_adaptor(
        "RemovePodsViolatingInterPodAntiAffinity",
        lambda pods, nodes, args, now: (
            remove_pods_violating_interpod_antiaffinity(pods)
        ),
    ),
    "RemovePodsViolatingNodeTaints": _deschedule_adaptor(
        "RemovePodsViolatingNodeTaints",
        lambda pods, nodes, args, now: remove_pods_violating_node_taints(
            pods, nodes, args
        ),
    ),
    "RemoveFailedPods": _deschedule_adaptor(
        "RemoveFailedPods",
        lambda pods, nodes, args, now: remove_failed_pods(pods, args, now=now),
    ),
    "PodLifeTime": _deschedule_adaptor(
        "PodLifeTime",
        lambda pods, nodes, args, now: pod_life_time(pods, args, now=now),
    ),
    "RemovePodsViolatingTopologySpreadConstraint": _cluster_deschedule_adaptor(
        "RemovePodsViolatingTopologySpreadConstraint",
        lambda pods, nodes, args, now: remove_pods_violating_topology_spread(
            pods, nodes, args
        ),
    ),
}


class Descheduler:
    """The ticking main loop (descheduler.go:241 Start, :259
    deschedulerOnce)."""

    def __init__(
        self,
        profiles: Sequence[DeschedulerProfile],
        nodes_fn: Callable[[], List[Mapping]],
        descheduling_interval: float = 120.0,
        node_selector: Optional[Mapping[str, str]] = None,
        evictor: Optional[PodEvictor] = None,
        migration: Optional[MigrationController] = None,
        registry: Optional[Mapping[str, Callable]] = None,
        dry_run: bool = False,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.nodes_fn = nodes_fn
        self.descheduling_interval = descheduling_interval
        self.node_selector = node_selector or {}
        self.evictor = evictor or PodEvictor()
        self.migration = migration
        self.clock = clock
        self.frameworks = [
            Framework(
                p,
                registry or DEFAULT_REGISTRY,
                self.evictor,
                migration=migration,
                dry_run=dry_run,
            )
            for p in profiles
        ]

    def _ready_nodes(self) -> List[Mapping]:
        nodes = [
            nd
            for nd in self.nodes_fn()
            if not nd.get("unschedulable")
            and not nd.get("not_ready")
            and all(
                nd.get("labels", {}).get(k) == v
                for k, v in self.node_selector.items()
            )
        ]
        return nodes

    def descheduler_once(self) -> Status:
        """descheduler.go:259: one full tick."""
        nodes = self._ready_nodes()
        if len(nodes) <= 1:
            return Status(
                "the cluster size is 0 or 1 meaning eviction causes service "
                "disruption or degradation"
            )
        now = self.clock()
        self.evictor.reset()
        for fw in self.frameworks:
            fw._now = now
            fw.planned_only.clear()  # per-tick dry-run decisions
        # ALL profiles' Deschedule plugins run before ANY Balance plugin;
        # one broken profile must not stall the others or the migration
        # reconcile (errors aggregate, like the framework's plugin loops)
        errs = []
        for fw in self.frameworks:
            status = fw.run_deschedule_plugins(nodes)
            if not status.ok:
                errs.append(status.err)
        for fw in self.frameworks:
            status = fw.run_balance_plugins(nodes)
            if not status.ok:
                errs.append(status.err)
        if self.migration is not None:
            self.migration.reconcile(now)
        return Status("; ".join(errs) or None)

    def start(self, max_ticks: Optional[int] = None, sleep=time.sleep) -> None:
        """descheduler.go:241: non-sliding until loop; interval 0 = once."""
        ticks = 0
        while True:
            self.descheduler_once()
            ticks += 1
            if self.descheduling_interval <= 0:
                return
            if max_ticks is not None and ticks >= max_ticks:
                return
            sleep(self.descheduling_interval)
