"""Descheduler analog of koord-descheduler (reference ``pkg/descheduler``).

Modules
-------
- ``anomaly``     — node anomaly circuit breaker
                    (reference ``utils/anomaly/basic_detector.go``).
- ``sorter``      — multi-key pod/node ranking (reference ``utils/sorter``).
- ``evictions``   — eviction rate limiting + the evictor seam
                    (reference ``evictions/evictions.go``, ``eviction_limiter.go``).
- ``lownodeload`` — the LowNodeLoad Balance plugin: utilization
                    classification + eviction planning (reference
                    ``framework/plugins/loadaware/low_node_load.go``).
- ``migration``   — PodMigrationJob controller state machine + arbitration
                    (reference ``controllers/migration``).
"""

from koordinator_tpu.descheduler.anomaly import BasicDetector, State  # noqa: F401
from koordinator_tpu.descheduler.lownodeload import (  # noqa: F401
    LowNodeLoadArgs,
    NodePool,
    balance,
)
