"""Trace-driven cluster simulator + replay engine (ISSUE 12, ROADMAP 5).

Every bench before this drove synthetic uniform churn; the paper's wins
(incremental rescore, pipelined dispatch) had never been measured under
the workload the reference scheduler actually schedules.  This module
generates SEEDED, REPLAYABLE multi-tenant event streams with the
structure PAPER.md names — gang arrivals respecting ``minMember``
boundaries, ElasticQuota pressure waves, node drains/resizes, and
priority churn across the koord-prod|mid|batch|free bands — and replays
them through the full serving path: the Go-shim-shaped ``ScorerClient``
(the same delta-encoding client shim go/scorerclient mirrors) over a
real UDS gRPC server, through the coalescing dispatcher, onto the
device.

One replay is simultaneously a CORRECTNESS and a PERFORMANCE gate:

* the same event stream drives the full-engine servicer AND a serial
  oracle servicer (``max_batch=1``, ``pipeline_depth=1``, memos and the
  incremental engine off), with the flat-Score reply arrays and the
  Assign assignment/status arrays digest-compared after EVERY event —
  bit parity, not statistics;
* the measured pass runs under ``analysis.retrace_guard``: the warm
  event stream must hold ZERO jit cache misses (the replay first runs
  one untimed warm-up pass over the identical stream, so every delta
  bucket/derived-column shape the trace touches is compiled before the
  guard arms);
* every RPC's client-observed latency lands in the
  ``koord_scorer_trace_cycle_ms{band, rpc}`` histogram, which the
  ``obs/slo.py`` SLO gate then judges (per-band p99 cycle latency,
  per-RPC p99) — ``bench.py --config trace`` publishes the verdicts;
* the replay also emits a per-event timeline in the flight-recorder
  dump format (``obs.validate_flight_dump`` is the schema), so a bad
  replay is diagnosable with the same tooling as a bad serving cycle.

Determinism: a :class:`Trace` is concrete — every event carries the
absolute rows it writes (plain ints, JSON-able), produced once by the
generator's own cluster model.  Replay is a dumb applier, so the same
seed replays the same bytes forever; ``Trace.digest()`` pins that.

The artificial slow stage (:func:`slow_stage`) exists for the gate's
own regression test: injecting latency into the engine's launch path
must flip the SLO verdicts to FAIL while bit parity still holds.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import PriorityClass, estimate_pod

R = res.NUM_RESOURCES
_CPU = res.RESOURCE_INDEX[res.CPU]
_MEM = res.RESOURCE_INDEX[res.MEMORY]
_PODS = res.RESOURCE_INDEX[res.PODS]

BANDS = ("koord-prod", "koord-mid", "koord-batch", "koord-free")
INFRA_BAND = "infra"
_BAND_BASE_PRIORITY = {
    "koord-prod": 9000, "koord-mid": 7000,
    "koord-batch": 5000, "koord-free": 3000,
}
RPCS = ("sync", "score", "assign", "cycle")

# the default event mix with the fused-term kinds folded in (ISSUE 15):
# a trace generated with TraceConfig(mix=TERM_MIX, accel_types=...,
# workload_classes=...) drifts throughput rows and sensitivity profiles
# on the warm delta path like any other event
TERM_MIX = (
    ("gang_arrival", 0.10),
    ("gang_partial", 0.04),
    ("pod_arrival", 0.20),
    ("pod_departure", 0.14),
    ("priority_churn", 0.10),
    ("quota_wave", 0.10),
    ("usage_tick", 0.10),
    ("node_drain", 0.04),
    ("node_restore", 0.03),
    ("node_resize", 0.03),
    ("throughput_update", 0.06),
    ("sensitivity_drift", 0.06),
)

# sparse-regime event mix (ISSUE 16, pair with TraceConfig.open_nodes
# and gangs=0): only kinds that keep every pod slot OCCUPIED.  A
# zero-request slot is feasible on every node — the sparse engine
# correctly refuses it at C < N rather than truncate — and arrivals/
# departures need (or create) empty slots, so a sparse trace churns
# priorities, quotas and nodes instead.
SPARSE_MIX = (
    ("priority_churn", 0.30),
    ("quota_wave", 0.20),
    ("usage_tick", 0.20),
    ("node_drain", 0.10),
    ("node_restore", 0.10),
    ("node_resize", 0.10),
)


class TraceParityError(AssertionError):
    """The engine servicer's reply bytes diverged from the serial
    oracle's at a named replay step."""


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Shape of one generated trace.  ``events`` counts the replayed
    mutations; every event is followed by one Score + one Assign
    cycle on both servicers."""

    seed: int = 0
    nodes: int = 32
    pod_slots: int = 128
    tenants: int = 4
    gangs: int = 6
    gang_min_member: int = 4
    events: int = 32
    top_k: int = 8
    # (kind, weight) mix the generator draws from; infra events label
    # their latency observations band="infra"
    mix: Tuple[Tuple[str, float], ...] = (
        ("gang_arrival", 0.12),
        ("gang_partial", 0.04),
        ("pod_arrival", 0.24),
        ("pod_departure", 0.16),
        ("priority_churn", 0.12),
        ("quota_wave", 0.12),
        ("usage_tick", 0.10),
        ("node_drain", 0.04),
        ("node_restore", 0.03),
        ("node_resize", 0.03),
    )
    # arrival probability per band, aligned with BANDS
    band_mix: Tuple[float, ...] = (0.35, 0.20, 0.30, 0.15)
    # fused scoring-term state (ISSUE 15): >0 gives every node an
    # accelerator type in [0, accel_types), every pod a workload class
    # in [0, workload_classes) plus a sensitivity profile, and the init
    # a [workload_classes, accel_types] throughput matrix — enabling
    # the throughput_update / sensitivity_drift event kinds (TERM_MIX
    # is the default mix with both folded in).  0 = terms off, init
    # unchanged.
    accel_types: int = 0
    workload_classes: int = 0
    # sparse-feasibility regime (ISSUE 16): >0 leaves only this many
    # nodes with pod-sized headroom — the rest start requested-to-the-
    # brim (free cpu/mem below the smallest pod ask), so every pod's
    # exact feasible count stays near ``open_nodes`` and a sparse-
    # engine replay (CycleConfig.candidate_width) serves without
    # overflow at node counts the dense oracle cannot even allocate.
    # node_resize (x1.25) can re-open a closed node mid-trace, so
    # leave width slack: open_nodes <= candidate_width / 2 is
    # comfortable.  0 = every node keeps the dense generator's 2-30%
    # load (feasibility ~N, the dense engines' regime).
    open_nodes: int = 0

    def to_doc(self) -> Dict[str, object]:
        doc = dataclasses.asdict(self)
        doc["mix"] = [list(e) for e in self.mix]
        doc["band_mix"] = list(self.band_mix)
        return doc


@dataclasses.dataclass(frozen=True)
class TraceEvent:
    """One concrete mutation: ``payload`` holds the absolute rows to
    write (plain ints/lists — the replay never recomputes them)."""

    kind: str
    band: str
    payload: Dict[str, object]

    def to_doc(self) -> Dict[str, object]:
        return {"kind": self.kind, "band": self.band,
                "payload": self.payload}


@dataclasses.dataclass(frozen=True)
class Trace:
    config: TraceConfig
    init: Dict[str, object]
    events: Tuple[TraceEvent, ...]

    def to_doc(self) -> Dict[str, object]:
        return {
            "config": self.config.to_doc(),
            "init": _jsonable_init(self.init),
            "events": [e.to_doc() for e in self.events],
        }

    def digest(self) -> str:
        # cached on the frozen instance: the full-trace JSON serialize
        # is several MB at bench scale and digest() is consulted per
        # replay pass (and once inside the measured wall clock)
        cached = self.__dict__.get("_digest")
        if cached is None:
            cached = hashlib.sha256(
                json.dumps(self.to_doc(), sort_keys=True).encode()
            ).hexdigest()
            object.__setattr__(self, "_digest", cached)
        return cached

    def bands(self) -> List[str]:
        seen: List[str] = []
        for e in self.events:
            if e.band not in seen:
                seen.append(e.band)
        return seen


def _jsonable_init(init: Dict[str, object]) -> Dict[str, object]:
    """Init tensors are held as numpy at sparse scale (ISSUE 16:
    ``TraceConfig.nodes`` accepts node counts past the dense
    allocator's reach, and a million-row ``.tolist()`` is both slow
    and several GB of python ints); JSON surfaces — export, digest —
    convert at the edge, so small traces serialize exactly as before."""
    return {
        k: (v.tolist() if isinstance(v, np.ndarray) else v)
        for k, v in init.items()
    }


def export_trace(trace: Trace) -> List[str]:
    """Serialize a trace as concrete JSON audit lines (ISSUE 14
    satellite / ROADMAP 5(a)): one ``trace_header`` line carrying the
    config + initial cluster state, then one ``trace_event`` line per
    event — the shape a real cluster's audit log drains into, so
    :func:`import_trace` is also the importer for externally captured
    streams."""
    lines = [json.dumps(
        {"event": "trace_header", "config": trace.config.to_doc(),
         "init": _jsonable_init(trace.init)},
        sort_keys=True,
    )]
    lines.extend(
        json.dumps({"event": "trace_event", **e.to_doc()}, sort_keys=True)
        for e in trace.events
    )
    return lines


def import_trace(lines) -> Trace:
    """Rebuild a :class:`Trace` from concrete JSON audit lines (strings
    or already-parsed dicts).  The result is digest-identical to the
    exported trace — every payload is absolute rows, so import is pure
    parsing — and replays through :class:`TraceReplay` unchanged.
    Unknown line shapes raise: an audit stream this module cannot
    faithfully replay must fail loudly, never replay approximately."""
    header: Optional[Dict] = None
    events: List[TraceEvent] = []
    for i, line in enumerate(lines):
        doc = json.loads(line) if isinstance(line, (str, bytes)) else line
        if not isinstance(doc, dict):
            raise ValueError(f"audit line {i} is not a JSON object")
        kind = doc.get("event")
        if kind == "trace_header":
            if header is not None:
                raise ValueError(
                    f"audit line {i}: duplicate trace_header"
                )
            header = doc
        elif kind == "trace_event":
            if header is None:
                raise ValueError(
                    f"audit line {i}: trace_event before trace_header"
                )
            events.append(TraceEvent(
                kind=str(doc["kind"]), band=str(doc["band"]),
                payload=dict(doc["payload"]),
            ))
        else:
            raise ValueError(
                f"audit line {i}: unknown event shape {kind!r}"
            )
    if header is None:
        raise ValueError("audit stream carries no trace_header line")
    cdoc = dict(header["config"])
    cdoc["mix"] = tuple(
        (str(k), float(w)) for k, w in cdoc.get("mix", ())
    )
    cdoc["band_mix"] = tuple(float(v) for v in cdoc.get("band_mix", ()))
    return Trace(
        config=TraceConfig(**cdoc),
        init=dict(header["init"]),
        events=tuple(events),
    )


class ClusterModel:
    """The mutable numpy cluster state one trace replays over — shared
    verbatim by the generator (to mint concrete payloads) and the
    replay (to apply them), so the two can never drift."""

    TENSOR_KEYS = ("nalloc", "nreq", "nuse", "preq", "pest",
                   "qrt", "quse", "qlim")

    def __init__(self, init: Dict[str, object]):
        self.nalloc = np.asarray(init["nalloc"], np.int64).copy()
        self.nreq = np.asarray(init["nreq"], np.int64).copy()
        self.nuse = np.asarray(init["nuse"], np.int64).copy()
        self.fresh = [bool(b) for b in init["fresh"]]
        self.preq = np.asarray(init["preq"], np.int64).copy()
        self.pest = np.asarray(init["pest"], np.int64).copy()
        self.priority = [int(v) for v in init["priority"]]
        self.gang_id = [int(v) for v in init["gang_id"]]
        self.quota_id = [int(v) for v in init["quota_id"]]
        self.gang_min = [int(v) for v in init["gang_min"]]
        self.qrt = np.asarray(init["qrt"], np.int64).copy()
        self.quse = np.asarray(init["quse"], np.int64).copy()
        self.qlim = np.asarray(init["qlim"], np.int64).copy()
        # fused-term state (ISSUE 15); absent keys = terms off
        self.accel = (
            [int(v) for v in init["accel"]] if "accel" in init else None
        )
        self.wclass = (
            [int(v) for v in init["wclass"]] if "wclass" in init else None
        )
        self.sens = (
            np.asarray(init["sens"], np.int64).copy()
            if "sens" in init else None
        )
        self.tput = (
            np.asarray(init["tput"], np.int64).copy()
            if "tput" in init else None
        )

    def apply(self, event: TraceEvent) -> Set[str]:
        """Apply one event's concrete payload; returns the changed
        array keys (what the replay must Sync)."""
        p = event.payload
        kind = event.kind
        if kind in ("gang_arrival", "gang_partial", "pod_arrival",
                    "pod_departure"):
            for i, slot in enumerate(p["slots"]):
                self.preq[slot] = p["requests"][i]
                self.pest[slot] = p["estimated"][i]
                self.priority[slot] = int(p["priority"][i])
            return {"preq", "pest", "priority"}
        if kind == "priority_churn":
            for slot, prio in zip(p["slots"], p["priority"]):
                self.priority[slot] = int(prio)
            return {"priority"}
        if kind == "quota_wave":
            for i, row in enumerate(p["rows"]):
                self.qrt[row] = p["runtime"][i]
                self.quse[row] = p["used"][i]
            return {"qrt", "quse"}
        if kind in ("node_drain", "node_restore", "node_resize"):
            self.nalloc[int(p["node"])] = p["allocatable"]
            return {"nalloc"}
        if kind == "usage_tick":
            for i, node in enumerate(p["nodes"]):
                self.nuse[node] = p["usage"][i]
                self.fresh[node] = bool(p["fresh"][i])
            return {"nuse", "fresh"}
        if kind == "throughput_update":
            for i, row in enumerate(p["rows"]):
                self.tput[row] = p["values"][i]
            return {"tput"}
        if kind == "sensitivity_drift":
            for i, slot in enumerate(p["slots"]):
                self.sens[slot] = p["profiles"][i]
            return {"sens"}
        raise ValueError(f"unknown trace event kind {kind!r}")


# ---- generation ----


def _pod_rows(rng, band: str, count: int) -> Tuple[List, List, List]:
    """(requests, estimated, priority) rows for ``count`` arriving pods
    of one band — all plain ints."""
    pc = PriorityClass.from_name(band)
    reqs, ests, prios = [], [], []
    for _ in range(count):
        cpu_m = int(rng.choice([250, 500, 1000, 2000]))
        mem = int(rng.choice([256, 512, 1024, 2048]))
        req = [0] * R
        req[_CPU], req[_MEM], req[_PODS] = cpu_m, mem, 1
        lim = list(req)
        lim[_CPU], lim[_MEM] = cpu_m * 2, mem * 2
        est = estimate_pod(req, lim, pc)
        reqs.append([int(v) for v in req])
        ests.append([int(v) for v in est])
        prios.append(_BAND_BASE_PRIORITY[band] + int(rng.integers(0, 900)))
    return reqs, ests, prios


def _pick_band(rng, cfg: TraceConfig) -> str:
    mix = np.asarray(cfg.band_mix, float)
    return BANDS[int(rng.choice(len(BANDS), p=mix / mix.sum()))]


def _undrained_node(rng, model: ClusterModel, st: "_GenState"):
    """A uniform-ish undrained node id WITHOUT building the O(N)
    undrained list; None after 8 drained draws (the caller skips the
    event — the generator's mix loop retries with another kind)."""
    n = model.nalloc.shape[0]
    for _ in range(8):
        node = int(rng.integers(0, n))
        if node not in st.drained:
            return node
    return None


class _GenState:
    """Generator-side occupancy bookkeeping (slots, gangs, drains)."""

    def __init__(self, cfg: TraceConfig, model: ClusterModel):
        gang_region = cfg.gangs * cfg.gang_min_member
        self.gang_slots = [
            list(range(g * cfg.gang_min_member,
                       (g + 1) * cfg.gang_min_member))
            for g in range(cfg.gangs)
        ]
        self.idle_gangs = set(range(cfg.gangs))
        self.active_gangs: Set[int] = set()
        self.free_singles = [
            s for s in range(gang_region, cfg.pod_slots)
            if not model.preq[s].any()
        ]
        self.active_singles = [
            s for s in range(gang_region, cfg.pod_slots)
            if model.preq[s].any()
        ]
        self.drained: Dict[int, List[int]] = {}


def _next_event(cfg: TraceConfig, rng, model: ClusterModel,
                st: _GenState) -> Optional[TraceEvent]:
    kinds = [k for k, _ in cfg.mix]
    weights = np.asarray([w for _, w in cfg.mix], float)
    kind = kinds[int(rng.choice(len(kinds), p=weights / weights.sum()))]

    if kind in ("gang_arrival", "gang_partial") and st.idle_gangs:
        g = sorted(st.idle_gangs)[int(rng.integers(0, len(st.idle_gangs)))]
        band = _pick_band(rng, cfg)
        slots = st.gang_slots[g]
        if kind == "gang_partial" and len(slots) > 1:
            # UNDER the minMember boundary: these members must WAIT_GANG
            # until the rest arrive (they never do in this trace — the
            # partial gang is released by the next departure draw)
            slots = slots[: int(rng.integers(1, len(slots)))]
        reqs, ests, prios = _pod_rows(rng, band, len(slots))
        st.idle_gangs.discard(g)
        st.active_gangs.add(g)
        return TraceEvent(kind, band, {
            "gang": g, "slots": [int(s) for s in slots],
            "requests": reqs, "estimated": ests, "priority": prios,
        })
    if kind == "pod_arrival" and st.free_singles:
        band = _pick_band(rng, cfg)
        n = min(len(st.free_singles), int(rng.integers(1, 5)))
        slots = [st.free_singles.pop(0) for _ in range(n)]
        st.active_singles.extend(slots)
        reqs, ests, prios = _pod_rows(rng, band, n)
        return TraceEvent(kind, band, {
            "slots": slots, "requests": reqs, "estimated": ests,
            "priority": prios,
        })
    if kind == "pod_departure":
        # departures free whole gangs first (all-or-nothing, matching
        # the arrival boundary), else a few singles
        if st.active_gangs and rng.random() < 0.4:
            g = sorted(st.active_gangs)[
                int(rng.integers(0, len(st.active_gangs)))
            ]
            slots = list(st.gang_slots[g])
            st.active_gangs.discard(g)
            st.idle_gangs.add(g)
        elif st.active_singles:
            n = min(len(st.active_singles), int(rng.integers(1, 4)))
            slots = [st.active_singles.pop(0) for _ in range(n)]
            st.free_singles.extend(slots)
        else:
            return None
        zero = [0] * R
        return TraceEvent(kind, INFRA_BAND, {
            "slots": [int(s) for s in slots],
            "requests": [zero] * len(slots),
            "estimated": [zero] * len(slots),
            "priority": [0] * len(slots),
        })
    if kind == "priority_churn" and st.active_singles:
        n = min(len(st.active_singles), int(rng.integers(1, 5)))
        picks = sorted(
            int(s) for s in rng.choice(st.active_singles, n, replace=False)
        )
        band = _pick_band(rng, cfg)
        prios = [
            _BAND_BASE_PRIORITY[band] + int(rng.integers(0, 900))
            for _ in picks
        ]
        return TraceEvent(kind, band, {"slots": picks, "priority": prios})
    if kind == "quota_wave":
        row = int(rng.integers(0, model.qrt.shape[0]))
        factor = float(rng.choice([0.5, 0.8, 1.25, 1.6]))
        runtime = np.maximum(
            (model.qrt[row].astype(float) * factor), 0
        ).astype(np.int64)
        used = (runtime.astype(float) * float(rng.uniform(0.0, 0.9))).astype(
            np.int64
        )
        return TraceEvent(kind, INFRA_BAND, {
            "rows": [row],
            "runtime": [[int(v) for v in runtime]],
            "used": [[int(v) for v in used]],
        })
    if kind == "node_drain":
        # rejection-sample instead of materializing the undrained list
        # (O(N) per event is minutes of generation at sparse-scale node
        # counts); a draw landing on a drained node 8 times in a row
        # just skips the event, which the mix loop already tolerates
        node = _undrained_node(rng, model, st)
        if node is None:
            return None
        st.drained[node] = [int(v) for v in model.nalloc[node]]
        return TraceEvent(kind, INFRA_BAND, {
            "node": int(node), "allocatable": [0] * R,
        })
    if kind == "node_restore" and st.drained:
        node = sorted(st.drained)[int(rng.integers(0, len(st.drained)))]
        row = st.drained.pop(node)
        return TraceEvent(kind, INFRA_BAND, {
            "node": int(node), "allocatable": row,
        })
    if kind == "node_resize":
        node = _undrained_node(rng, model, st)
        if node is None:
            return None
        factor = float(rng.choice([0.75, 1.25]))
        row = (model.nalloc[node].astype(float) * factor).astype(np.int64)
        row[_PODS] = model.nalloc[node][_PODS]  # pod slots don't scale
        return TraceEvent(kind, INFRA_BAND, {
            "node": int(node), "allocatable": [int(v) for v in row],
        })
    if kind == "throughput_update" and model.tput is not None:
        # one workload class's measured throughput moved (a profiling
        # round finished, a kernel regressed): concrete new row values,
        # normalized to [0, 100] like the wire contract
        row = int(rng.integers(0, model.tput.shape[0]))
        values = [
            int(v) for v in rng.integers(0, 101, model.tput.shape[1])
        ]
        return TraceEvent(kind, INFRA_BAND, {
            "rows": [row], "values": [values],
        })
    if kind == "sensitivity_drift" and model.sens is not None:
        # a few pods' CPU/mem sensitivity profiles re-estimated
        count = min(model.sens.shape[0], int(rng.integers(1, 5)))
        slots = sorted(
            int(s) for s in rng.choice(
                model.sens.shape[0], count, replace=False
            )
        )
        profiles = []
        for _ in slots:
            prof = [0] * R
            prof[_CPU] = int(rng.integers(0, 101))
            prof[_MEM] = int(rng.integers(0, 101))
            profiles.append(prof)
        return TraceEvent(kind, INFRA_BAND, {
            "slots": slots, "profiles": profiles,
        })
    if kind == "usage_tick":
        # capped at 256 rows: an uncapped N/4 tick at sparse-scale node
        # counts would put hundreds of thousands of rows in ONE event
        # payload (and its JSON line) — a usage tick is a churn sample,
        # not a full-cluster rescan
        count = max(1, min(model.nuse.shape[0] // 4, 256))
        nodes = sorted(
            int(n) for n in rng.choice(
                model.nuse.shape[0], count, replace=False
            )
        )
        usage, fresh = [], []
        for n in nodes:
            target = model.nalloc[n].astype(float) * rng.uniform(0.05, 0.7)
            drifted = (
                model.nuse[n].astype(float) * 0.5 + target * 0.5
            ).astype(np.int64)
            usage.append([int(v) for v in drifted])
            # the occasional stale koordlet: LoadAware's freshness gate
            fresh.append(bool(rng.random() > 0.05))
        return TraceEvent(kind, INFRA_BAND, {
            "nodes": nodes, "usage": usage, "fresh": fresh,
        })
    return None


def _build_init(cfg: TraceConfig, rng) -> Dict[str, object]:
    N, P, Q, G = cfg.nodes, cfg.pod_slots, cfg.tenants, cfg.gangs
    # vectorized over the node axis (ISSUE 16: a per-node python loop
    # makes sparse-scale node counts — the whole point of the knob —
    # take minutes before the first event is even drawn)
    nalloc = np.zeros((N, R), np.int64)
    nreq = np.zeros((N, R), np.int64)
    nuse = np.zeros((N, R), np.int64)
    cpu = rng.choice(np.asarray([16000, 32000, 64000], np.int64), size=N)
    mem = (cpu // 1000) * 4 * 1024  # MiB axis
    nalloc[:, _CPU], nalloc[:, _MEM], nalloc[:, _PODS] = cpu, mem, 256
    nreq[:, _CPU] = (cpu * rng.uniform(0.02, 0.3, N)).astype(np.int64)
    nreq[:, _MEM] = (mem * rng.uniform(0.02, 0.3, N)).astype(np.int64)
    nuse[:, _CPU] = (cpu * rng.uniform(0.05, 0.5, N)).astype(np.int64)
    nuse[:, _MEM] = (mem * rng.uniform(0.05, 0.5, N)).astype(np.int64)
    if cfg.open_nodes > 0:
        # sparse-feasibility regime (see TraceConfig.open_nodes): close
        # every node but the chosen few — free cpu below the 250m
        # minimum ask, free mem below the 256 MiB minimum
        closed = np.ones(N, bool)
        closed[rng.choice(N, size=min(cfg.open_nodes, N),
                          replace=False)] = False
        nreq[closed, _CPU] = nalloc[closed, _CPU] - 100
        nreq[closed, _MEM] = nalloc[closed, _MEM] - 128
    fresh = [True] * N

    gang_region = G * cfg.gang_min_member
    if gang_region >= P:
        raise ValueError(
            f"pod_slots={P} must exceed gangs*gang_min_member="
            f"{gang_region}"
        )
    preq = np.zeros((P, R), np.int64)
    pest = np.zeros((P, R), np.int64)
    priority = [0] * P
    gang_id = [-1] * P
    for g in range(G):
        for s in range(g * cfg.gang_min_member, (g + 1) * cfg.gang_min_member):
            gang_id[s] = g
    quota_id = [s % Q for s in range(P)]
    # ~40% of the single slots start occupied so departures have
    # something to drain from step one; in the sparse regime EVERY
    # slot is occupied instead — an empty (zero-request) slot is
    # feasible on all N nodes, which the sparse engine refuses at
    # C < N (pair open_nodes with SPARSE_MIX and gangs=0)
    for s in range(gang_region, P):
        if cfg.open_nodes > 0 or rng.random() < 0.4:
            band = _pick_band(rng, cfg)
            reqs, ests, prios = _pod_rows(rng, band, 1)
            preq[s], pest[s], priority[s] = reqs[0], ests[0], prios[0]

    total_cpu = int(nalloc[:, _CPU].sum())
    total_mem = int(nalloc[:, _MEM].sum())
    qrt = np.zeros((Q, R), np.int64)
    quse = np.zeros((Q, R), np.int64)
    qlim = np.zeros((Q, R), np.int64)
    for t in range(Q):
        qrt[t, _CPU] = total_cpu * 6 // 10 // Q
        qrt[t, _MEM] = total_mem * 6 // 10 // Q
        qlim[t, _CPU] = qlim[t, _MEM] = 1
    # tensor keys stay numpy (see _jsonable_init: sparse-scale node
    # counts make .tolist() the bottleneck); ClusterModel np.asarray's
    # either representation, so imported JSON traces replay unchanged
    init = {
        "nalloc": nalloc, "nreq": nreq,
        "nuse": nuse, "fresh": fresh,
        "preq": preq, "pest": pest,
        "priority": priority, "gang_id": gang_id, "quota_id": quota_id,
        "gang_min": [cfg.gang_min_member] * G,
        "qrt": qrt, "quse": quse, "qlim": qlim,
    }
    if cfg.accel_types > 0 and cfg.workload_classes > 0:
        # fused-term state (ISSUE 15): heterogeneous accelerator fleet,
        # per-pod workload classes + sensitivity profiles, and the
        # [C, A] throughput matrix — all concrete, digest-pinned like
        # every other init key
        A_, C_ = cfg.accel_types, cfg.workload_classes
        init["accel"] = [int(rng.integers(0, A_)) for _ in range(N)]
        init["wclass"] = [int(rng.integers(0, C_)) for _ in range(P)]
        sens = np.zeros((P, R), np.int64)
        sens[:, _CPU] = rng.integers(0, 101, P)
        sens[:, _MEM] = rng.integers(0, 101, P)
        init["sens"] = sens.tolist()
        init["tput"] = rng.integers(0, 101, (C_, A_)).astype(
            np.int64
        ).tolist()
    return init


def generate_trace(cfg: TraceConfig) -> Trace:
    """Deterministic per ``cfg.seed``: the generator advances its own
    :class:`ClusterModel` so every payload is concrete, then the model
    is thrown away — replay re-derives it from ``init``."""
    rng = np.random.default_rng(cfg.seed)
    init = _build_init(cfg, rng)
    model = ClusterModel(init)
    st = _GenState(cfg, model)
    events: List[TraceEvent] = []
    guard = 0
    while len(events) < cfg.events and guard < cfg.events * 20:
        guard += 1
        ev = _next_event(cfg, rng, model, st)
        if ev is None:
            continue  # mix drew a kind with nothing to act on
        model.apply(ev)
        events.append(ev)
    return Trace(config=cfg, init=init, events=tuple(events))


# ---- replay ----

# the serialized oracle: one request in the device section at a time,
# no memos, no incremental engine — the reference execution the full
# engine must match byte for byte
ORACLE_KW = dict(
    coalesce_max_batch=1,
    coalesce_window_ms=0.0,
    pipeline_depth=1,
    score_memo=False,
    score_incr=False,
)


@contextlib.contextmanager
def slow_stage(servicer, ms: float):
    """Inject an artificial slow stage into a servicer's coalesced
    launch path (the SLO gate's own regression fixture, the
    chaos.fail_next_launch idiom): every Score launch pays ``ms`` of
    extra wall before touching the device.  Replies stay bit-exact —
    only the latency distribution moves, which is exactly what the
    gate must catch."""
    dispatch = servicer.dispatch
    real = dispatch._launch_batch
    delay_s = float(ms) / 1000.0

    def slowed(batch):
        time.sleep(delay_s)
        return real(batch)

    dispatch._launch_batch = slowed
    try:
        yield
    finally:
        dispatch._launch_batch = real


@dataclasses.dataclass
class TraceReport:
    """Outcome of one measured replay.  ``registry`` is the engine
    servicer's metrics registry — the ``koord_scorer_trace_cycle_ms``
    observations the SLO gate judges live there."""

    trace: Trace
    events_replayed: int
    parity_checks: int
    retraces: int
    wall_ms: float
    registry: object
    timeline: List[Dict[str, object]]
    config_doc: Dict[str, object]

    def timeline_document(self) -> Dict[str, object]:
        """The per-replay timeline in the flight-recorder dump format
        (``obs.validate_flight_dump`` is the schema)."""
        return {
            "version": 1,
            "reason": "trace-replay",
            "dumped_at_unix": time.time(),
            "config": dict(self.config_doc),
            "dropped_cycles": 0,
            "cycles": list(self.timeline),
        }

    def quantile(self, q: float, band: Optional[str] = None,
                 rpc: str = "cycle") -> Optional[float]:
        from koordinator_tpu.obs import slo as slo_mod
        from koordinator_tpu.obs.scorer_metrics import TRACE_CYCLE

        labels = {"rpc": rpc}
        if band is not None:
            labels["band"] = band
        return slo_mod.histogram_quantile(
            self.registry, TRACE_CYCLE, q, labels
        )


def default_slo_specs(
    bands: Sequence[str],
    cycle_p99_ms: Optional[float] = None,
    rpc_p99_ms: Optional[float] = None,
) -> List:
    """The declarative gate ``bench.py --config trace`` evaluates: p99
    whole-step latency per band, plus per-RPC p99 across all bands.
    Thresholds default from ``KOORD_TRACE_SLO_P99_MS`` /
    ``KOORD_TRACE_SLO_RPC_P99_MS`` (generous CPU-container defaults —
    the gate's job in CI is catching REGRESSIONS via the injected-slow-
    stage test and hardware rounds, not flaking on a busy container)."""
    from koordinator_tpu.obs.slo import SloSpec
    from koordinator_tpu.obs.scorer_metrics import TRACE_CYCLE

    # `or`: empty env value means unset (the KOORD_* convention)
    if cycle_p99_ms is None:
        cycle_p99_ms = float(
            os.environ.get("KOORD_TRACE_SLO_P99_MS") or "2500"
        )
    if rpc_p99_ms is None:
        rpc_p99_ms = float(
            os.environ.get("KOORD_TRACE_SLO_RPC_P99_MS") or cycle_p99_ms
        )
    specs = [
        SloSpec(
            name=f"{band}-cycle-p99",
            family=TRACE_CYCLE,
            quantile=0.99,
            threshold_ms=float(cycle_p99_ms),
            labels={"band": band, "rpc": "cycle"},
        )
        for band in bands
    ]
    specs.extend(
        SloSpec(
            name=f"{rpc}-p99",
            family=TRACE_CYCLE,
            quantile=0.99,
            threshold_ms=float(rpc_p99_ms),
            labels={"rpc": rpc},
        )
        for rpc in ("sync", "score", "assign")
    )
    return specs


class TraceReplay:
    """Replay one trace through engine + serial oracle over real UDS
    gRPC transports.  ``run()`` performs an untimed warm-up pass over
    the identical stream first (compiling every shape the trace
    touches), then the measured pass under the retrace guard.

    ``slow_score_ms`` injects the artificial slow stage into the
    ENGINE's launch path during the measured pass (see
    :func:`slow_stage`)."""

    def __init__(
        self,
        trace: Trace,
        engine_kw: Optional[dict] = None,
        oracle_kw: Optional[dict] = None,
        slow_score_ms: float = 0.0,
        retrace_budget: int = 0,
        warmup: bool = True,
        trace_export: Optional[str] = None,
        oracle: bool = True,
    ):
        """``trace_export`` (ISSUE 14): directory the ENGINE side —
        servicer and client both — exports its distributed-trace spans
        to during the measured pass; the oracle stays untraced (its
        replies are the parity baseline, not part of the request
        tree).  The warm-up pass is untraced either way."""
        self.trace = trace
        self.engine_kw = dict(engine_kw or {})
        self.oracle_kw = dict(oracle_kw or ORACLE_KW)
        # the oracle must score under the ENGINE's CycleConfig (fused
        # scoring terms included, ISSUE 15) or a term-enabled replay
        # fails parity by construction; explicit oracle_kw cfg wins
        if "cfg" in self.engine_kw and "cfg" not in self.oracle_kw:
            self.oracle_kw["cfg"] = self.engine_kw["cfg"]
        self.slow_score_ms = float(slow_score_ms)
        self.retrace_budget = int(retrace_budget)
        self.warmup = bool(warmup)
        self.trace_export = trace_export
        # oracle=False drops the serial-oracle servicer entirely —
        # parity_checks stays 0 and only the engine replays.  This is
        # the sparse-scale mode (ISSUE 16): at node counts past the
        # dense allocator's reach the oracle cannot even hold its
        # [P, N] tensors, so the replay measures the sparse engine
        # alone (parity is owned by tests/test_candidates.py at scales
        # where both engines fit).
        self.oracle = bool(oracle)

    def run(self) -> TraceReport:
        from koordinator_tpu.analysis import retrace_guard

        if self.warmup:
            self._replay_once(record=False)
        t0 = time.perf_counter()
        with retrace_guard(budget=self.retrace_budget) as counter:
            report = self._replay_once(record=True)
        report.wall_ms = (time.perf_counter() - t0) * 1000.0
        report.retraces = counter.traces
        return report

    # -- one full pass --
    def _replay_once(self, record: bool) -> Optional[TraceReport]:
        from koordinator_tpu.bridge.client import ScorerClient
        from koordinator_tpu.bridge.server import ScorerServicer, make_server

        # tracing only on the MEASURED pass (warm-up stays untraced so
        # export files hold exactly the replayed stream's spans)
        export = self.trace_export if record else None
        engine_kw = dict(self.engine_kw)
        engine_kw["trace_export"] = export if export else False
        oracle_kw = dict(self.oracle_kw)
        oracle_kw.setdefault("trace_export", False)
        with tempfile.TemporaryDirectory(prefix="koord-trace-") as tmp:
            engine_sv = ScorerServicer(**engine_kw)
            sides = [("engine", engine_sv)]
            if self.oracle:
                sides.append(("oracle", ScorerServicer(**oracle_kw)))
            servers, clients = [], []
            try:
                for name, sv in sides:
                    sock = os.path.join(tmp, f"{name}.sock")
                    server = make_server(servicer=sv)
                    server.add_insecure_port(f"unix://{sock}")
                    server.start()
                    servers.append(server)
                    clients.append(ScorerClient(
                        f"unix://{sock}",
                        # False forces tracing OFF (env included) on
                        # the oracle and on untraced passes — the
                        # export dir must hold exactly the measured
                        # engine stream's spans
                        trace_export=(
                            export if name == "engine" and export
                            else False
                        ),
                    ))
                return self._drive(
                    engine_sv, clients[0],
                    clients[1] if self.oracle else None,
                    record=record,
                )
            finally:
                for client in clients:
                    client.close()
                for server in servers:
                    server.stop(0)
                # close() drains each side's background span writer and
                # unhooks the process-wide feeds: the caller assembles
                # the export directory IMMEDIATELY after run(), so the
                # servicer's tail spans must be on disk by now — and a
                # replay must not leak a writer thread per pass
                for _name, sv in sides:
                    sv.telemetry.close()

    def _drive(self, engine_sv, engine, oracle,
               record: bool) -> Optional[TraceReport]:
        trace = self.trace
        model = ClusterModel(trace.init)
        metrics = engine_sv.telemetry.metrics
        timeline: List[Dict[str, object]] = []
        parity_checks = 0
        tdig = trace.digest()[:8]

        # first Sync ships the whole cluster (names stay empty — the
        # replies are index-based, like the Go shim's)
        full_kw = dict(
            node_allocatable=model.nalloc,
            node_requested=model.nreq,
            node_usage=model.nuse,
            metric_fresh=list(model.fresh),
            pod_requests=model.preq,
            pod_estimated=model.pest,
            priority=list(model.priority),
            gang_id=list(model.gang_id),
            quota_id=list(model.quota_id),
            gang_min_member=list(model.gang_min),
            quota_runtime=model.qrt,
            quota_used=model.quse,
            quota_limited=model.qlim,
        )
        if model.tput is not None:
            full_kw.update(
                node_accel_type=list(model.accel),
                workload_class=list(model.wclass),
                pod_sensitivity=model.sens,
                throughput=model.tput,
            )
        k = trace.config.top_k
        engine.sync(**full_kw)
        if oracle is not None:
            oracle.sync(**full_kw)
        # cold Score/Assign: compiles the cold paths in the warm-up
        # pass; in the measured pass both hit the jit cache
        d_e = self._digest(engine.score_flat(top_k=k), engine.assign())
        if oracle is not None:
            d_o = self._digest(oracle.score_flat(top_k=k), oracle.assign())
            parity_checks += 1
            if d_e != d_o:
                raise TraceParityError(
                    "cold step: engine reply digest diverged from the "
                    "serial oracle"
                )

        maybe_slow = (
            slow_stage(engine_sv, self.slow_score_ms)
            if record and self.slow_score_ms > 0
            else contextlib.nullcontext()
        )
        with maybe_slow:
            for i, event in enumerate(trace.events):
                changed = model.apply(event)
                kw = self._sync_kwargs(model, changed)
                started = time.time()
                # the ENGINE's step is timed end to end with nothing
                # else interleaved; the oracle replays the same step
                # afterwards, off the clock
                t0 = time.perf_counter()
                engine.sync(**kw)
                t_sync = time.perf_counter()
                e_score = engine.score_flat(top_k=k)
                t_score = time.perf_counter()
                e_assign = engine.assign()
                t_assign = time.perf_counter()
                if oracle is not None:
                    oracle.sync(**kw)
                    digest_e = self._digest(e_score, e_assign)
                    digest_o = self._digest(
                        oracle.score_flat(top_k=k), oracle.assign()
                    )
                    parity_checks += 1
                    if digest_e != digest_o:
                        raise TraceParityError(
                            f"step {i} ({event.kind}, band {event.band}): "
                            f"engine reply digest {digest_e[:16]} != "
                            f"serial oracle {digest_o[:16]}"
                        )
                if not record:
                    continue
                sync_ms = (t_sync - t0) * 1000.0
                score_ms = (t_score - t_sync) * 1000.0
                assign_ms = (t_assign - t_score) * 1000.0
                cycle_ms = sync_ms + score_ms + assign_ms
                for rpc, ms in (("sync", sync_ms), ("score", score_ms),
                                ("assign", assign_ms),
                                ("cycle", cycle_ms)):
                    metrics.observe_trace_cycle(event.band, rpc, ms)
                timeline.append({
                    "cycle_id": f"t{tdig}-{i}",
                    "snapshot_id": engine.snapshot_id,
                    "started_unix": started,
                    "spans": [
                        {"name": "sync", "start_ms": 0.0,
                         "dur_ms": round(sync_ms, 3)},
                        {"name": "score", "start_ms": round(sync_ms, 3),
                         "dur_ms": round(score_ms, 3)},
                        {"name": "assign",
                         "start_ms": round(sync_ms + score_ms, 3),
                         "dur_ms": round(assign_ms, 3)},
                    ],
                    "notes": {
                        "event": event.kind,
                        "band": event.band,
                        "latency_ms": round(cycle_ms, 3),
                        "parity": "ok",
                    },
                    "error": None,
                })
        if not record:
            return None
        return TraceReport(
            trace=trace,
            events_replayed=len(trace.events),
            parity_checks=parity_checks,
            retraces=0,  # filled by run() from the guard
            wall_ms=0.0,
            registry=metrics.registry,
            timeline=timeline,
            config_doc={
                "trace_digest": trace.digest(),
                "seed": trace.config.seed,
                "nodes": trace.config.nodes,
                "pod_slots": trace.config.pod_slots,
                "events": len(trace.events),
            },
        )

    @staticmethod
    def _sync_kwargs(model: ClusterModel, changed: Set[str]) -> dict:
        kw: Dict[str, object] = {}
        if "nalloc" in changed:
            kw["node_allocatable"] = model.nalloc
        if "nuse" in changed:
            kw["node_usage"] = model.nuse
        if "fresh" in changed:
            kw["metric_fresh"] = list(model.fresh)
        if "preq" in changed:
            kw["pod_requests"] = model.preq
        if "pest" in changed:
            kw["pod_estimated"] = model.pest
        if "priority" in changed:
            kw["priority"] = list(model.priority)
        if "qrt" in changed:
            kw["quota_runtime"] = model.qrt
        if "quse" in changed:
            kw["quota_used"] = model.quse
        if "tput" in changed:
            kw["throughput"] = model.tput
        if "sens" in changed:
            kw["pod_sensitivity"] = model.sens
        return kw

    @staticmethod
    def _digest(score_flat, assign) -> str:
        h = hashlib.sha256()
        for arr in score_flat:
            h.update(np.ascontiguousarray(arr).tobytes())
        assignment, status, _ms, path = assign
        h.update(np.ascontiguousarray(assignment).tobytes())
        h.update(np.ascontiguousarray(status).tobytes())
        h.update(path.encode())
        return h.hexdigest()
