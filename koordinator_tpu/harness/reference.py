"""Sequential CPU reference: an exact Python mirror of the reference's Go
scoring/filter/assignment semantics, used for parity testing the device
kernels and as the CPU baseline for bench.py.

Mirrors (all reference paths under /root/reference):
* leastRequestedScore / scorer reduction —
  ``pkg/scheduler/plugins/loadaware/load_aware.go:378-397``.
* LoadAware Score composition — ``load_aware.go:269-335`` (estimator +
  assign-cache + measured usage), Filter — ``load_aware.go:173-224``.
* NodeResourcesFit LeastAllocated/MostAllocated — upstream semantics as in
  ``nodenumaresource/least_allocated.go`` / ``most_allocated.go``.
* The per-pod scheduling cycle with Reserve-time state mutation —
  assign-cache ``load_aware.go:260-267`` + NodeInfo requested accounting.

Everything is plain Python ints (arbitrary precision == int64 semantics for
these magnitudes), no numpy, so it is an independent oracle.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from koordinator_tpu.config import CycleConfig, DEFAULT_CYCLE_CONFIG, MOST_ALLOCATED
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import MAX_NODE_SCORE
from koordinator_tpu.ops.fit import NONZERO_MILLI_CPU, NONZERO_MEMORY

_CPU = res.RESOURCE_INDEX[res.CPU]
_MEM = res.RESOURCE_INDEX[res.MEMORY]


def least_requested_score(requested: int, capacity: int) -> int:
    if capacity == 0 or requested > capacity:
        return 0
    return ((capacity - requested) * MAX_NODE_SCORE) // capacity


def most_requested_score(requested: int, capacity: int) -> int:
    if capacity == 0:
        return 0
    if requested > capacity:
        requested = capacity
    return (requested * MAX_NODE_SCORE) // capacity


def weighted_score(per_res: Sequence[int], weights: Sequence[int]) -> int:
    weight_sum = sum(weights)
    if weight_sum == 0:
        return 0
    return sum(s * w for s, w in zip(per_res, weights)) // weight_sum


def usage_percent(used: int, total: int) -> int:
    """Go: int64(math.Round(float64(used)/float64(total)*100))."""
    if total == 0:
        return 0
    return int(math.floor(used / total * 100 + 0.5))


def nonzero_request(vec: Sequence[int]) -> List[int]:
    out = list(vec)
    if out[_CPU] == 0:
        out[_CPU] = NONZERO_MILLI_CPU
    if out[_MEM] == 0:
        out[_MEM] = NONZERO_MEMORY
    return out


class ReferenceCycle:
    """Sequential scheduling cycle over dense python-int state."""

    def __init__(
        self,
        node_allocatable: Sequence[Sequence[int]],
        node_requested: Sequence[Sequence[int]],
        node_usage: Sequence[Sequence[int]],
        metric_fresh: Sequence[bool],
        cfg: CycleConfig = DEFAULT_CYCLE_CONFIG,
        quota_runtime: Optional[Dict[int, List[int]]] = None,
        quota_used: Optional[Dict[int, List[int]]] = None,
        quota_limited: Optional[Dict[int, List[bool]]] = None,
        agg_usage: Optional[Sequence[Optional[Dict[str, Sequence[int]]]]] = None,
        prod_usage: Optional[Sequence[Sequence[int]]] = None,
    ):
        self.alloc = [list(v) for v in node_allocatable]
        self.requested = [list(v) for v in node_requested]
        self.usage = [list(v) for v in node_usage]
        self.estimated = [[0] * res.NUM_RESOURCES for _ in node_allocatable]
        self.fresh = list(metric_fresh)
        self.cfg = cfg
        self.quota_runtime = quota_runtime or {}
        self.quota_used = quota_used or {}
        self.quota_limited = quota_limited or {}
        self.la_weights = res.weights_vector(dict(cfg.loadaware.resource_weights))
        agg = cfg.loadaware.aggregated
        if agg is not None and dict(agg.usage_thresholds):
            thr_src = agg.usage_thresholds
        else:
            thr_src = cfg.loadaware.usage_thresholds
        self.la_thresholds = res.weights_vector(dict(thr_src))
        self.prod_thresholds = res.weights_vector(
            dict(cfg.loadaware.prod_usage_thresholds)
        )
        self.fit_weights = res.weights_vector(dict(cfg.fit_resource_weights))
        # per-node optional {"p50": vec, ...} aggregated usage and prod-pods
        # usage sum (load_aware.go:150-226,291-311)
        self.agg_usage = list(agg_usage) if agg_usage is not None else None
        self.prod_usage = (
            [list(v) for v in prod_usage] if prod_usage is not None else None
        )

    # --- Filter -----------------------------------------------------------
    def fit_ok(self, n: int, pod_req: Sequence[int]) -> bool:
        for r in range(res.NUM_RESOURCES):
            if pod_req[r] > 0 and self.requested[n][r] + pod_req[r] > self.alloc[n][r]:
                return False
        return True

    def loadaware_filter_ok(self, n: int, is_prod: bool = False) -> bool:
        # load_aware.go:150-258: prod pods with ProdUsageThresholds check
        # the prod-pods usage sum INSTEAD; aggregated profiles check the
        # selected percentile (missing aggregates pass); stale metric passes
        if not self.fresh[n]:
            return True
        if is_prod and any(self.prod_thresholds):
            # the prod branch is taken on config + pod class alone
            # (load_aware.go:151); no prod metrics -> pass
            # (filterProdUsage:227 returns nil on empty PodsMetric)
            if self.prod_usage is None:
                return True
            for r in range(res.NUM_RESOURCES):
                threshold = self.prod_thresholds[r]
                if threshold == 0 or self.alloc[n][r] == 0:
                    continue
                if (
                    usage_percent(self.prod_usage[n][r], self.alloc[n][r])
                    >= threshold
                ):
                    return False
            return True
        agg = self.cfg.loadaware.aggregated
        usage = self.usage[n]
        if (
            agg is not None
            and dict(agg.usage_thresholds)
            and agg.usage_aggregation_type
            and self.agg_usage is not None
        ):
            node_agg = self.agg_usage[n]
            if node_agg is None:
                return True  # getTargetAggregatedUsage nil -> pass
            usage = node_agg.get(agg.usage_aggregation_type)
            if usage is None:
                return True  # this percentile not reported -> pass
        for r in range(res.NUM_RESOURCES):
            threshold = self.la_thresholds[r]
            if threshold == 0 or self.alloc[n][r] == 0:
                continue
            if usage_percent(usage[r], self.alloc[n][r]) >= threshold:
                return False
        return True

    def quota_ok(self, qid: int, pod_req: Sequence[int]) -> bool:
        """Admission only on the quota's declared dimensions (elasticquota
        PreFilter checks used+request vs runtime per declared resource)."""
        if qid < 0 or qid not in self.quota_runtime:
            return True
        used = self.quota_used.setdefault(qid, [0] * res.NUM_RESOURCES)
        rt = self.quota_runtime[qid]
        # Declared-but-zero runtime dims must reject (the reference keeps
        # declared dims in the runtime list with explicit zeros; undeclared
        # dims fall open); callers pass quota_limited for that.
        limited = self.quota_limited.get(qid)
        if limited is None:
            limited = [v > 0 for v in rt]
        return all(
            used[r] + pod_req[r] <= rt[r]
            for r in range(res.NUM_RESOURCES)
            if limited[r]
        )

    # --- Score ------------------------------------------------------------
    def loadaware_score(
        self, n: int, pod_est: Sequence[int], is_prod: bool = False
    ) -> int:
        if not self.fresh[n]:
            return 0
        usage = self.usage[n]
        if (
            is_prod
            and self.cfg.loadaware.score_according_prod_usage
            and self.prod_usage is not None
        ):
            usage = self.prod_usage[n]
        else:
            agg = self.cfg.loadaware.aggregated
            if (
                agg is not None
                and agg.score_aggregation_type
                and self.agg_usage is not None
                and self.agg_usage[n] is not None
            ):
                # missing percentile -> plain NodeUsage
                usage = self.agg_usage[n].get(
                    agg.score_aggregation_type, self.usage[n]
                )
        per_res = [
            least_requested_score(
                usage[r] + self.estimated[n][r] + pod_est[r], self.alloc[n][r]
            )
            for r in range(res.NUM_RESOURCES)
        ]
        # scorer iterates only weighted resources (weight 0 excluded)
        return weighted_score(per_res, self.la_weights)

    def fit_score(self, n: int, pod_req_nonzero: Sequence[int]) -> int:
        score_fn = (
            most_requested_score
            if self.cfg.fit_scoring_strategy == MOST_ALLOCATED
            else least_requested_score
        )
        per_res = [
            score_fn(self.requested[n][r] + pod_req_nonzero[r], self.alloc[n][r])
            for r in range(res.NUM_RESOURCES)
        ]
        return weighted_score(per_res, self.fit_weights)

    def combined_score(
        self,
        n: int,
        pod_req: Sequence[int],
        pod_est: Sequence[int],
        is_prod: bool = False,
    ) -> int:
        total = 0
        if self.cfg.enable_fit_score:
            total += self.cfg.fit_plugin_weight * self.fit_score(
                n, nonzero_request(pod_req)
            )
        if self.cfg.enable_loadaware:
            total += self.cfg.loadaware_plugin_weight * self.loadaware_score(
                n, pod_est, is_prod
            )
        return total

    # --- One pod ----------------------------------------------------------
    def schedule_one(
        self,
        pod_req: Sequence[int],
        pod_est: Sequence[int],
        quota_id: int = -1,
        is_prod: bool = False,
    ) -> Tuple[int, List[int]]:
        """Filter+Score+Reserve for one pod; returns (node or -1, score row)."""
        n_nodes = len(self.alloc)
        scores = [0] * n_nodes
        best, best_score = -1, None
        quota_fits = self.quota_ok(quota_id, pod_req)
        for n in range(n_nodes):
            feasible = (
                quota_fits
                and self.fit_ok(n, pod_req)
                and (
                    not self.cfg.enable_loadaware
                    or self.loadaware_filter_ok(n, is_prod)
                )
            )
            s = self.combined_score(n, pod_req, pod_est, is_prod)
            scores[n] = s
            if feasible and (best_score is None or s > best_score):
                best, best_score = n, s
        if best >= 0:
            for r in range(res.NUM_RESOURCES):
                self.requested[best][r] += pod_req[r]
                self.estimated[best][r] += pod_est[r]
            if quota_id >= 0 and quota_id in self.quota_runtime:
                used = self.quota_used.setdefault(quota_id, [0] * res.NUM_RESOURCES)
                for r in range(res.NUM_RESOURCES):
                    used[r] += pod_req[r]
        return best, scores

    def schedule_batch(
        self,
        pod_requests: Sequence[Sequence[int]],
        pod_estimated: Sequence[Sequence[int]],
        priorities: Optional[Sequence[int]] = None,
        quota_ids: Optional[Sequence[int]] = None,
        is_prod: Optional[Sequence[bool]] = None,
    ) -> List[int]:
        """Sequential cycle over the batch in queue order (priority desc)."""
        n_pods = len(pod_requests)
        order = sorted(
            range(n_pods),
            key=lambda i: (-(priorities[i] if priorities else 0), i),
        )
        assignment = [-1] * n_pods
        for i in order:
            qid = quota_ids[i] if quota_ids else -1
            assignment[i], _ = self.schedule_one(
                pod_requests[i],
                pod_estimated[i],
                qid,
                bool(is_prod[i]) if is_prod is not None else False,
            )
        return assignment
