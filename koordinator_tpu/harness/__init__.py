from koordinator_tpu.harness import generators, reference  # noqa: F401
