"""Golden SyncRequest builder: generator output -> bridge wire message.

One canonical encoding shared by the native integration tests
(tests/test_native_bridge.py), the bench's CPU-baseline stage (bench.py)
and any host-side shim: the same bytes a real scheduler would ship over
the Score/ScoreExtensions seam (SURVEY §7.5; reference boundary
``pkg/scheduler/frameworkext/framework_extender.go:216``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.state import numpy_to_tensor
from koordinator_tpu.constraints import build_quota_table_inputs
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import PERCENTILES, PriorityClass, estimate_pod


def estimate_pods(pods: List[Dict]) -> np.ndarray:
    """LoadAware estimator output per pod (estimator lives host-side)."""
    return np.asarray(
        [
            estimate_pod(
                res.resource_vector(p["requests"]),
                res.resource_vector(p.get("limits", {})),
                PriorityClass.from_name(p.get("priority_class"))
                if p.get("priority_class") is not None
                else PriorityClass.from_priority_value(p.get("priority")),
            )
            for p in pods
        ]
    )


def build_sync_request(
    nodes: List[Dict],
    pods: List[Dict],
    gangs: List[Dict],
    quotas: List[Dict],
    node_bucket: int = 0,
    pod_bucket: int = 0,
) -> Tuple["pb2.SyncRequest", List[int]]:
    """Encode generator-style dict lists as a full SyncRequest.

    Returns (request, quota_id per pod).  Quota runtime fair division runs
    host-side (constraints.build_quota_table_inputs), mirroring where the
    reference computes runtimeQuota
    (``elasticquota/core/runtime_quota_calculator.go:126``).
    """
    pod_reqs = [res.resource_vector(p["requests"]) for p in pods]
    qidx = {q["name"]: i for i, q in enumerate(quotas)}
    qids = [qidx.get(p.get("quota"), -1) for p in pods]

    req = pb2.SyncRequest(node_bucket=node_bucket, pod_bucket=pod_bucket)
    nalloc = np.asarray([res.resource_vector(n["allocatable"]) for n in nodes])
    nuse = np.asarray(
        [res.resource_vector(n.get("usage", {})) for n in nodes]
    )
    nreq = np.asarray(
        [res.resource_vector(n.get("requested", {})) for n in nodes]
    )
    req.nodes.allocatable.CopyFrom(numpy_to_tensor(nalloc))
    req.nodes.requested.CopyFrom(numpy_to_tensor(nreq))
    req.nodes.usage.CopyFrom(numpy_to_tensor(nuse))
    req.nodes.names.extend(n["name"] for n in nodes)
    req.nodes.metric_fresh.extend(
        bool(n.get("metric_fresh", True)) for n in nodes
    )
    if any("agg_usage" in n for n in nodes):
        agg = np.zeros((len(nodes), len(PERCENTILES), res.NUM_RESOURCES), np.int64)
        agg_fresh = np.zeros((len(nodes), len(PERCENTILES)), np.int64)
        for i, n in enumerate(nodes):
            for a, pct in enumerate(PERCENTILES):
                if pct in n.get("agg_usage", {}):
                    agg[i, a] = res.resource_vector(n["agg_usage"][pct])
                    agg_fresh[i, a] = 1
        req.nodes.agg_usage.CopyFrom(numpy_to_tensor(agg))
        req.nodes.agg_fresh.CopyFrom(numpy_to_tensor(agg_fresh))
    if any("prod_usage" in n for n in nodes):
        prod = np.asarray(
            [res.resource_vector(n.get("prod_usage", {})) for n in nodes]
        )
        req.nodes.prod_usage.CopyFrom(numpy_to_tensor(prod))

    req.pods.requests.CopyFrom(numpy_to_tensor(np.asarray(pod_reqs)))
    req.pods.estimated.CopyFrom(numpy_to_tensor(estimate_pods(pods)))
    req.pods.names.extend(p["name"] for p in pods)
    req.pods.priority.extend(int(p.get("priority", 0)) for p in pods)
    req.pods.priority_class.extend(
        int(
            PriorityClass.from_name(p["priority_class"])
            if p.get("priority_class") is not None
            else PriorityClass.from_priority_value(p.get("priority"))
        )
        for p in pods
    )
    gidx = {g["name"]: i for i, g in enumerate(gangs)}
    req.pods.gang_id.extend(
        gidx.get(p.get("gang"), -1) for p in pods
    )
    req.pods.quota_id.extend(int(q) for q in qids)
    req.gangs.min_member.extend(int(g["min_member"]) for g in gangs)

    if quotas:
        total = [0] * res.NUM_RESOURCES
        for n in nodes:
            v = res.resource_vector(n["allocatable"])
            total = [a + b for a, b in zip(total, v)]
        qdicts = build_quota_table_inputs(quotas, pod_reqs, qids, total)
        qrt = np.asarray(
            [res.resource_vector(q["runtime"]) for q in qdicts]
        )
        quse = np.asarray(
            [res.resource_vector(q.get("used", {})) for q in qdicts]
        )
        qlim = np.asarray(
            [
                [
                    1 if res.RESOURCE_AXIS[r] in q["runtime"] else 0
                    for r in range(res.NUM_RESOURCES)
                ]
                for q in qdicts
            ],
            np.int64,
        )
        req.quotas.runtime.CopyFrom(numpy_to_tensor(qrt))
        req.quotas.used.CopyFrom(numpy_to_tensor(quse))
        req.quotas.limited.CopyFrom(numpy_to_tensor(qlim))
    return req, qids


def write_golden(path: str, *args, **kwargs) -> "pb2.SyncRequest":
    req, _ = build_sync_request(*args, **kwargs)
    with open(path, "wb") as f:
        f.write(req.SerializeToString())
    return req
