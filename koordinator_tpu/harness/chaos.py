"""Chaos fault-injection harness for the serving tier (ISSUE 11).

Composable faults against the replication/journal machinery, with the
invariant checkers that make a chaos run a TEST instead of a demo.
Runnable from pytest (tests/test_chaos.py drives the acceptance run)
and from ``bench.py --config failover`` (which adds the real
subprocess SIGKILL on top of the in-process faults here).

Faults (compose freely through :class:`FaultPlan` probabilities plus
the explicit methods):

* **drop / duplicate / reorder** replication frames
  (:class:`ChaosChannel` — the PR-8 fuzz channel, promoted to a shared
  home);
* **corrupt / truncate** frame BYTES (the follower must classify every
  mutation as a discontinuity, never apply it);
* **SIGKILL the leader** (:meth:`ChaosTier.crash_leader` drops the
  leader object with no cleanup — the in-process equivalent of
  ``kill -9``; the journal file keeps only what reached the OS) and
  **warm-restart** it from the journal, or **promote a follower**;
* **stall a follower** (frames buffer; delivered late, they must apply
  or drop as stale — never double-apply);
* **fail a device launch mid-batch** (:func:`fail_next_launch` poisons
  the dispatcher's next launch, exercising the error routing under
  faulted serving);
* **truncate the journal tail** (:meth:`ChaosTier.damage_journal` —
  the torn-write crash shape).

Invariants (raise AssertionError with the failing detail):

* **byte parity vs an unfaulted oracle** — leader mirrors and
  flat-Score reply bytes equal the oracle's after every converged
  step, and every caught-up follower equals the leader;
* **zero torn snapshots** — a frame that did not APPLY leaves the
  follower's observable state byte-identical to before the offer
  (checked on every delivery, not just at the end);
* **zero warm-path retraces** — ``retrace_guard`` holds the post-
  recovery warm stream at zero jit cache misses;
* **bounded recovery** — crash→serving wall time under a caller-set
  budget.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import tempfile
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

import numpy as np

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.obs.lockwitness import witness_lock
from koordinator_tpu.replication import codec
from koordinator_tpu.replication.follower import (
    APPLIED,
    RESYNC,
    FollowerServicer,
    ReplicaApplier,
)
from koordinator_tpu.replication.journal import FrameJournal

# mirror keys asserted byte-identical between replicas (the PR-8 parity
# surface, shared here so the chaos tests and test_replication.py can
# never drift on what "parity" means)
from koordinator_tpu.bridge import state as _bridge_state

MIRROR_KEYS = _bridge_state._DELTA_TENSORS + (
    "node_fresh", "pod_priority", "pod_priority_class", "pod_gang",
    "pod_quota", "gang_min",
)


@dataclasses.dataclass
class FaultPlan:
    """Per-frame fault probabilities for a :class:`ChaosChannel`."""

    drop: float = 0.0
    duplicate: float = 0.0
    reorder: float = 0.0
    corrupt: float = 0.0
    truncate: float = 0.0


class ChaosChannel:
    """Lossy/reordering/corrupting transport between a leader's frame
    stream and one follower.  Operates on encoded frame BYTES so
    corruption and truncation hit the real wire surface."""

    def __init__(self, rng, plan: FaultPlan):
        self.rng = rng
        self.plan = plan
        self.delayed: List[bytes] = []
        self.injected = {"drop": 0, "duplicate": 0, "reorder": 0,
                         "corrupt": 0, "truncate": 0}

    def _mutate(self, raw: bytes) -> bytes:
        roll = self.rng.random()
        if roll < self.plan.corrupt and len(raw) > codec.HEADER_LEN:
            self.injected["corrupt"] += 1
            i = int(self.rng.integers(0, len(raw)))
            b = bytearray(raw)
            b[i] ^= 0xFF
            return bytes(b)
        if roll < self.plan.corrupt + self.plan.truncate and len(raw) > 1:
            self.injected["truncate"] += 1
            return raw[: int(self.rng.integers(1, len(raw)))]
        return raw

    def send(self, raw: bytes) -> List[bytes]:
        out: List[bytes] = []
        roll = self.rng.random()
        if roll < self.plan.drop:
            self.injected["drop"] += 1
        elif roll < self.plan.drop + self.plan.duplicate:
            self.injected["duplicate"] += 1
            out += [self._mutate(raw), self._mutate(raw)]
        elif roll < self.plan.drop + self.plan.duplicate + self.plan.reorder:
            self.injected["reorder"] += 1
            self.delayed.append(raw)
        else:
            out.append(self._mutate(raw))
        if self.delayed and self.rng.random() < 0.6:
            out.append(self.delayed.pop(0))
        return out

    def flush(self) -> List[bytes]:
        out, self.delayed = self.delayed, []
        return out


@contextmanager
def fail_next_launch(servicer, n: int = 1,
                     exc_factory=lambda: RuntimeError("chaos: injected device launch failure")):
    """Poison the next ``n`` coalesced launches on ``servicer``: the
    dispatcher's launch callable raises before touching the device.
    The dispatcher must route the failure to the batch's callers and
    keep serving afterwards — the fault a flaky device injects
    mid-batch."""
    dispatch = servicer.dispatch
    real = dispatch._launch_batch
    remaining = [int(n)]

    def poisoned(batch):
        if remaining[0] > 0:
            remaining[0] -= 1
            raise exc_factory()
        return real(batch)

    dispatch._launch_batch = poisoned
    try:
        yield
    finally:
        dispatch._launch_batch = real


@contextmanager
def fail_next_readback(servicer, n: int = 1,
                       exc_factory=lambda: RuntimeError("chaos: injected device readback failure")):
    """Poison the next ``n`` coalesced READBACKS: the launch half
    succeeds (the program enqueues) but the readback closure raises —
    the fault surface async dispatch actually exposes, where a failing
    device program reports at ``device_get`` rather than at enqueue.
    The circuit breaker must count these exactly like launch-half
    failures (ISSUE 13 review hardening)."""
    dispatch = servicer.dispatch
    real = dispatch._launch_batch
    remaining = [int(n)]

    def poisoned(batch):
        readback = real(batch)
        if (
            readback is None
            or getattr(readback, "no_device", False)
            or remaining[0] <= 0
        ):
            return readback
        remaining[0] -= 1

        def bad_readback():
            raise exc_factory()

        return bad_readback

    dispatch._launch_batch = poisoned
    try:
        yield
    finally:
        dispatch._launch_batch = real


def flat_score_bytes(sv, sid: str, top_k: int = 8) -> bytes:
    reply = sv.score(
        pb2.ScoreRequest(snapshot_id=sid, top_k=top_k, flat=True)
    )
    return reply.flat.SerializeToString()


def state_digest(sv) -> str:
    """Order-stable digest of every replicated mirror — the cheap
    every-delivery torn-snapshot probe (flat_score_bytes is the
    expensive reply-surface check run at checkpoints)."""
    h = hashlib.sha256()
    st = sv.state
    for key in MIRROR_KEYS:
        v = getattr(st, key)
        h.update(key.encode())
        if v is None:
            h.update(b"\x00")
        else:
            a = np.asarray(v)
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    h.update(repr(st.node_names).encode())
    h.update(repr(st.pod_names).encode())
    h.update(sv.snapshot_id().encode())
    return h.hexdigest()


def assert_mirror_parity(a_sv, b_sv, ids: bool = True) -> None:
    if ids:
        assert b_sv.snapshot_id() == a_sv.snapshot_id(), (
            f"snapshot ids diverged: {a_sv.snapshot_id()} vs "
            f"{b_sv.snapshot_id()}"
        )
    a, b = a_sv.state, b_sv.state
    for key in MIRROR_KEYS:
        va, vb = getattr(a, key), getattr(b, key)
        if va is None or vb is None:
            assert va is None and vb is None, f"{key}: {va!r} vs {vb!r}"
        else:
            va, vb = np.asarray(va), np.asarray(vb)
            assert va.dtype == vb.dtype, key
            assert np.array_equal(va, vb), f"mirror {key} diverged"
    assert a.node_names == b.node_names
    assert a.pod_names == b.pod_names
    assert a.node_bucket == b.node_bucket
    assert a.pod_bucket == b.pod_bucket


class _Follower:
    __slots__ = ("servicer", "applier", "channel", "stalled", "buffer")

    def __init__(self, servicer, applier, channel):
        self.servicer = servicer
        self.applier = applier
        self.channel = channel
        self.stalled = False
        self.buffer: List[bytes] = []


class ChaosTier:
    """One in-process serving tier under fault injection: a journaled
    leader, N followers behind chaos channels, and an UNFAULTED oracle
    replaying the same Sync stream.

    The tier checks the no-torn-snapshot invariant on EVERY delivery:
    an offer that does not return APPLIED must leave the follower's
    state digest untouched.  ``converge()`` then brings every follower
    to the leader (the documented one-shot full resync where needed)
    and asserts full byte parity against leader and oracle.
    """

    def __init__(
        self,
        state_dir: str,
        followers: int = 1,
        plan: Optional[FaultPlan] = None,
        seed: int = 0,
        compact_every: int = 256,
        servicer_kw: Optional[dict] = None,
    ):
        self.state_dir = state_dir
        self.journal_path = os.path.join(state_dir, "journal.krj")
        self.compact_every = compact_every
        self.plan = plan or FaultPlan()
        self.rng = np.random.default_rng(seed)
        self.servicer_kw = dict(servicer_kw or {})
        self.servicer_kw.setdefault("score_memo", False)
        self.leader = ScorerServicer(**self.servicer_kw)
        self.journal = FrameJournal(
            self.journal_path, compact_every=compact_every
        )
        self.journal.recover(self.leader)
        self.journal.attach(self.leader)
        self._capture_frames(self.leader)
        self.oracle = ScorerServicer(**self.servicer_kw)
        self.followers: List[_Follower] = []
        for _ in range(int(followers)):
            sv = FollowerServicer(**self.servicer_kw)
            self.followers.append(_Follower(
                sv, ReplicaApplier(sv),
                ChaosChannel(self.rng, self.plan),
            ))
        self.resyncs = 0
        self.torn_checks = 0
        self.stats: Dict[str, int] = {"syncs": 0, "delivered": 0}
        for f in self.followers:
            self._resync(f)

    # -- leader plumbing --
    def _capture_frames(self, leader) -> None:
        from koordinator_tpu.bridge.client import parse_snapshot_id

        self._frames: List[bytes] = []

        def hook(req, snapshot_id, wire_bytes=None):
            epoch, gen = parse_snapshot_id(snapshot_id)
            payload = (
                wire_bytes if wire_bytes is not None
                else req.SerializeToString()
            )
            self._frames.append(codec.encode_frame(
                codec.KIND_DELTA, epoch, gen,
                int(time.time() * 1e6), payload,
            ))

        leader.replication_hook = hook

    def full_frame_bytes(self) -> bytes:
        epoch, gen, payload = self.leader.export_replication_snapshot()
        return codec.encode_frame(
            codec.KIND_FULL, epoch, gen, int(time.time() * 1e6), payload
        )

    # -- the write stream --
    def sync(self, req: "pb2.SyncRequest", oracle: bool = True) -> str:
        """One committed Sync on leader (+oracle), frames delivered to
        every follower through its chaos channel."""
        if oracle:
            self.oracle.sync(pb2.SyncRequest.FromString(
                req.SerializeToString()
            ))
        sid = self.leader.sync(req).snapshot_id
        self.stats["syncs"] += 1
        frame = self._frames[-1] if self._frames else None
        for f in self.followers:
            if frame is not None:
                self._deliver(f, self.channel_out(f, frame))
        return sid

    def channel_out(self, f: _Follower, frame: bytes) -> List[bytes]:
        out = f.channel.send(frame)
        if f.stalled:
            f.buffer.extend(out)
            return []
        return out

    def _deliver(self, f: _Follower, raws: List[bytes]) -> None:
        for raw in raws:
            self.stats["delivered"] += 1
            before = state_digest(f.servicer)
            self.torn_checks += 1
            try:
                frame = codec.decode_frame(raw)
            except codec.FrameError:
                # the transport layer's contract: counted + resync
                assert state_digest(f.servicer) == before, (
                    "TORN SNAPSHOT: a malformed frame mutated state"
                )
                self._resync(f)
                continue
            result = f.applier.offer(frame)
            after = state_digest(f.servicer)
            if result == APPLIED:
                continue
            assert after == before, (
                f"TORN SNAPSHOT: offer({result}) mutated follower state"
            )
            if result == RESYNC:
                self._resync(f)

    def _resync(self, f: _Follower) -> None:
        self.resyncs += 1
        assert f.applier.offer(
            codec.decode_frame(self.full_frame_bytes())
        ) == APPLIED

    # -- explicit faults --
    def stall_follower(self, i: int) -> None:
        self.followers[i].stalled = True

    def unstall_follower(self, i: int) -> None:
        f = self.followers[i]
        f.stalled = False
        buffered, f.buffer = f.buffer, []
        self._deliver(f, buffered)

    def crash_leader(self) -> None:
        """The in-process SIGKILL: no stop(), no flush, no close — the
        object graph just dies.  Only what the journal already wrote
        to the OS survives (FrameJournal flushes per append, exactly
        the SIGKILL durability contract)."""
        self.leader = None
        self.journal = None

    def restart_leader(self) -> dict:
        """Warm-restart from the journal; returns the replay stats.
        The restarted leader must resume the same s<epoch>-<gen> chain
        the journal holds."""
        assert self.leader is None, "crash_leader first"
        t0 = time.perf_counter()
        self.leader = ScorerServicer(**self.servicer_kw)
        self.journal = FrameJournal(
            self.journal_path, compact_every=self.compact_every
        )
        stats = self.journal.recover(self.leader)
        self.journal.attach(self.leader)
        self._capture_frames(self.leader)
        stats["recovery_ms"] = (time.perf_counter() - t0) * 1000.0
        # the subscription handshake's fallback, mirrored: a follower
        # whose position the restarted leader cannot extend (a torn
        # tail rewound the journal BEHIND the follower — the frames it
        # already applied are gone from the chain) must full-resync,
        # exactly what the leader answers a non-coverable hello with.
        # Without this a rewound leader re-mints generation numbers
        # the follower already holds with different content — the one
        # fork the epoch fence cannot see.
        from koordinator_tpu.bridge.client import parse_snapshot_id

        l_epoch, l_gen = parse_snapshot_id(self.leader.snapshot_id())
        for f in self.followers:
            f_epoch, f_gen = f.applier.position()
            if f_epoch != l_epoch or f_gen > l_gen:
                self._resync(f)
        return stats

    def promote(self, i: int) -> str:
        """Promote follower ``i`` to the writer role: it bumps its
        epoch, opens its own journal (seeded with a full-state frame)
        and takes over the frame stream; the old leader — typically
        already crashed — is forgotten."""
        f = self.followers.pop(i)
        sid = f.servicer.promote()
        self.leader = f.servicer
        self.journal = FrameJournal(
            self.journal_path + ".promoted",
            compact_every=self.compact_every,
        )
        epoch, gen, payload = self.leader.export_replication_snapshot()
        self.journal.write_base(epoch, gen, payload)
        self.journal.attach(self.leader)
        self._capture_frames(self.leader)
        # surviving followers fence on the new epoch at their next
        # frame; resync them through the documented one-shot path now
        for other in self.followers:
            self._resync(other)
        return sid

    def damage_journal(self, cut_bytes: int = 7) -> None:
        """Tear the journal tail (the mid-append crash shape)."""
        size = os.path.getsize(self.journal_path)
        with open(self.journal_path, "r+b") as fh:
            fh.truncate(max(0, size - cut_bytes))

    # -- invariants --
    def converge(self) -> None:
        """Bring every follower to the leader's exact state (the
        documented resync where the chain broke) and assert byte
        parity: follower==leader mirrors + ids, leader==oracle mirrors
        and flat-Score reply bytes."""
        for f in self.followers:
            if f.stalled:
                continue
            self._deliver(f, f.channel.flush())
            if f.servicer.snapshot_id() != self.leader.snapshot_id():
                self._resync(f)
            assert_mirror_parity(self.leader, f.servicer)
        assert_mirror_parity(self.oracle, self.leader, ids=False)
        sid = self.leader.snapshot_id()
        want = flat_score_bytes(self.oracle, self.oracle.snapshot_id())
        assert flat_score_bytes(self.leader, sid) == want, (
            "leader flat-Score bytes diverged from the unfaulted oracle"
        )
        for f in self.followers:
            if f.stalled:
                continue
            assert flat_score_bytes(f.servicer, sid) == want, (
                "follower flat-Score bytes diverged from the oracle"
            )


# ---------------------------------------------------------------------------
# chaos x trace (ISSUE 13, ROADMAP 5(c)): the two harnesses compose.
# The trace generator provides the realistic multi-tenant event stream
# (harness/trace.py), the chaos harness provides the faults, and
# obs/slo.py judges the result — robustness is MEASURED, not asserted.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ChaosTraceReport:
    """Outcome of one chaos x trace replay.  ``registry`` holds the
    ``koord_scorer_trace_cycle_ms`` observations (per-band/RPC step
    latencies plus the ``rpc="recovery"`` observation) the SLO gate
    judges; ``parity_ok`` is the post-convergence digest comparison vs
    the unfaulted oracle; ``retraces`` counts warm-path jit misses
    observed AFTER recovery."""

    events_replayed: int
    rpc_errors: int
    degraded_replies: int
    breaker_trips: int
    recovery_ms: Optional[float]
    parity_ok: bool
    parity_detail: str
    retraces: int
    shed_by_band: Dict[str, int]
    registry: object
    bands: List[str]


class ChaosTraceReplay:
    """Replay a :class:`harness.trace.Trace` through the FULL serving
    path (delta-encoding ``ScorerClient`` over real UDS gRPC into the
    coalescer) on a journaled leader while chaos injects faults
    mid-replay:

    * at event ``fail_at`` the next ``fail_n`` device launches are
      poisoned (:func:`fail_next_launch`) — the circuit breaker must
      trip, brownout must serve bounded-staleness Scores with the
      ``degraded`` flag, and a half-open probe must recover;
    * at event ``kill_at`` the leader is killed in-process
      (server stopped, object graph dropped — only OS-flushed journal
      bytes survive) and warm-restarted from the journal on the SAME
      socket; ``recovery_ms`` is the client-observed wall time from
      the kill to the next acknowledged RPC, and the post-recovery
      tail of the replay must hold ZERO warm-path jit cache misses;
    * ``unrecovered=True`` is the gate's inverse control: the launch
      poison never lifts, so the run ends with the breaker open,
      recovery unmeasured and parity broken — the SLO gate must FAIL
      on this run (tests assert it does).

    After the last event both sides converge and the engine's flat
    Score + Assign reply digests are compared against an UNFAULTED
    serialized oracle replaying the identical stream — post-convergence
    byte parity, the chaos harness's oracle contract."""

    def __init__(
        self,
        trace,
        state_dir: str,
        fail_at: Optional[int] = None,
        fail_n: int = 4,
        kill_at: Optional[int] = None,
        unrecovered: bool = False,
        servicer_kw: Optional[dict] = None,
        retry_policy=None,
        warmup: bool = True,
        trace_export: Optional[str] = None,
    ):
        """``trace_export`` (ISSUE 14): export directory for the
        distributed-trace spans of the ENGINE side — the client shim,
        the leader, AND its warm-restarted successor all append there,
        so ``obs.assemble`` over the one directory reconstructs every
        client-observed RPC across the kill (the acceptance gate in
        tests/test_chaos_trace.py).  The oracle stays untraced."""
        self.trace = trace
        self.state_dir = state_dir
        self.trace_export = trace_export
        self.fail_at = fail_at
        self.fail_n = int(fail_n)
        self.kill_at = kill_at
        self.unrecovered = bool(unrecovered)
        self.servicer_kw = dict(servicer_kw or {})
        # fast breaker recovery by default: the replay is serial, so a
        # long cooldown just stalls the stream between events
        self.servicer_kw.setdefault("breaker_cooldown_ms", 100.0)
        # the replay is write-heavy (one Sync per Score, unlike a real
        # read-dominated tier), so every faulted event ages the
        # brownout cache one generation; a wider default bound keeps
        # the brownout leg exercisable — production keeps the tight
        # default, this is a harness knob
        self.servicer_kw.setdefault("brownout_max_lag", 6)
        self.retry_policy = retry_policy
        self.warmup = bool(warmup)
        self.journal_path = os.path.join(state_dir, "journal.krj")

    # -- leader lifecycle (the in-process SIGKILL + warm restart) --
    def _start_leader(self, sock: str):
        from koordinator_tpu.bridge.server import make_server

        # each leader incarnation (including the warm restart) opens
        # its OWN export file in the shared directory; False pins
        # tracing off when the harness was not asked for it
        kw = dict(self.servicer_kw)
        kw.setdefault("trace_export", self.trace_export or False)
        sv = ScorerServicer(**kw)
        journal = FrameJournal(self.journal_path)
        journal.recover(sv)
        journal.attach(sv)
        if os.path.exists(sock):
            os.unlink(sock)
        server = make_server(servicer=sv)
        server.add_insecure_port(f"unix://{sock}")
        server.start()
        return sv, journal, server

    def run(self) -> ChaosTraceReport:
        from koordinator_tpu.analysis import retrace_guard
        from koordinator_tpu.bridge.client import ScorerClient
        from koordinator_tpu.bridge.server import make_server
        from koordinator_tpu.harness.trace import (
            BANDS,
            ClusterModel,
            INFRA_BAND,
            ORACLE_KW,
            TraceReplay,
        )
        from koordinator_tpu.obs.scorer_metrics import ScorerMetrics
        from koordinator_tpu.replication.retry import BackoffPolicy

        if self.warmup:
            # one untimed, unfaulted pass over the identical stream
            # (TraceReplay's own warm-up machinery): every delta
            # bucket/derived-column shape the trace touches compiles
            # BEFORE the measured chaos pass, so the post-recovery
            # tail can be held at zero jit cache misses
            # TraceReplay defaults the oracle's cfg from engine_kw, so
            # a term-enabled servicer_kw warms up parity-consistent
            TraceReplay(
                self.trace, engine_kw=self.servicer_kw, warmup=False
            )._replay_once(record=False)

        trace = self.trace
        metrics = ScorerMetrics()
        policy = self.retry_policy or BackoffPolicy(
            base_ms=20.0, cap_ms=250.0, deadline_ms=20_000.0
        )
        rpc_errors = 0
        degraded = 0
        recovery_ms: Optional[float] = None
        retraces = 0
        shed_by_band: Dict[str, int] = {}
        breaker_trips = 0
        poison_handle = None

        with tempfile.TemporaryDirectory(prefix="koord-chaos-trace-") as tmp:
            sock = os.path.join(tmp, "engine.sock")
            osock = os.path.join(tmp, "oracle.sock")
            leader, journal, server = self._start_leader(sock)
            # the oracle must score under the ENGINE's CycleConfig
            # (fused scoring terms included, ISSUE 15) or a term-enabled
            # chaos replay would fail parity by construction
            oracle_kw = dict(ORACLE_KW)
            if "cfg" in self.servicer_kw:
                oracle_kw["cfg"] = self.servicer_kw["cfg"]
            oracle_sv = ScorerServicer(trace_export=False, **oracle_kw)
            oracle_server = make_server(servicer=oracle_sv)
            oracle_server.add_insecure_port(f"unix://{osock}")
            oracle_server.start()
            engine = ScorerClient(
                f"unix://{sock}", retry_policy=policy,
                trace_export=self.trace_export or False,
            )
            oracle = ScorerClient(
                f"unix://{osock}", retry_policy=policy,
                trace_export=False,
            )
            try:
                model = ClusterModel(trace.init)
                full_kw = dict(
                    node_allocatable=model.nalloc,
                    node_requested=model.nreq,
                    node_usage=model.nuse,
                    metric_fresh=list(model.fresh),
                    pod_requests=model.preq,
                    pod_estimated=model.pest,
                    priority=list(model.priority),
                    gang_id=list(model.gang_id),
                    quota_id=list(model.quota_id),
                    gang_min_member=list(model.gang_min),
                    quota_runtime=model.qrt,
                    quota_used=model.quse,
                    quota_limited=model.qlim,
                )
                if model.tput is not None:
                    # fused-term state (ISSUE 15): the chaos gate
                    # exercises throughput/sensitivity drift on the
                    # warm delta path like any other event
                    full_kw.update(
                        node_accel_type=list(model.accel),
                        workload_class=list(model.wclass),
                        pod_sensitivity=model.sens,
                        throughput=model.tput,
                    )
                k = trace.config.top_k
                engine.sync(**full_kw)
                oracle.sync(**full_kw)
                engine.score_flat(top_k=k)
                engine.assign()
                oracle.score_flat(top_k=k)
                oracle.assign()

                guard_from = (
                    None if self.kill_at is None or self.unrecovered
                    else min(len(trace.events), self.kill_at + 2)
                )
                guard = None
                counter = None
                try:
                    for i, event in enumerate(trace.events):
                        if self.fail_at is not None and i == self.fail_at:
                            n = (10 ** 9 if self.unrecovered
                                 else self.fail_n)
                            poison_handle = fail_next_launch(leader, n=n)
                            poison_handle.__enter__()
                        if (
                            self.kill_at is not None
                            and not self.unrecovered
                            and i == self.kill_at
                        ):
                            # the in-process SIGKILL: stop the
                            # transport, drop the object graph; only
                            # what the journal flushed to the OS
                            # survives.  Then warm-restart on the SAME
                            # socket and measure kill -> first
                            # acknowledged client RPC.
                            # the dying leader's ladder stats must
                            # survive it (the restart zeroes them)
                            breaker_trips += leader.breaker.stats()["trips"]
                            for b, n in leader.admission.stats()[
                                "shed_by_band"
                            ].items():
                                shed_by_band[b] = (
                                    shed_by_band.get(b, 0) + n
                                )
                            degraded += leader.degraded_replies
                            t_kill = time.perf_counter()
                            server.stop(0)
                            # drain the dying leader's span exporter
                            # BEFORE dropping the object graph: every
                            # reply the client observed had its server
                            # span enqueued first, and the writer
                            # thread must not leak parked forever (a
                            # real SIGKILL loses at most the µs-old
                            # tail batch — the per-batch flush is the
                            # durability story there, not this close)
                            leader.telemetry.close()
                            leader = journal = None
                            leader, journal, server = self._start_leader(
                                sock
                            )
                            engine.score_flat(top_k=k)  # retries ride it out
                            recovery_ms = (
                                time.perf_counter() - t_kill
                            ) * 1000.0
                            metrics.observe_trace_cycle(
                                INFRA_BAND, "recovery", recovery_ms
                            )
                        if guard_from is not None and i == guard_from:
                            # count-only (the caller asserts on the
                            # report): a huge budget never raises, so
                            # teardown still runs on a faulted replay
                            guard = retrace_guard(budget=10 ** 9)
                            counter = guard.__enter__()
                        changed = model.apply(event)
                        kw = TraceReplay._sync_kwargs(model, changed)
                        engine.band = event.band if event.band in BANDS else ""
                        t0 = time.perf_counter()
                        engine.sync(**kw)
                        t_sync = time.perf_counter()
                        # client-level read retries, the production
                        # shape (a failed Score is re-issued at once —
                        # reads vastly outnumber writes on a real
                        # tier): consecutive failures are what trips
                        # the breaker, and the retry after the trip is
                        # the request the brownout cache answers with
                        # the degraded flag
                        for _ in range(4):
                            try:
                                engine.score_flat(top_k=k)
                                break
                            except Exception:  # koordlint: disable=broad-except(faulted-window RPC failures are the scenario under test: counted, replay continues)
                                rpc_errors += 1
                        t_score = time.perf_counter()
                        try:
                            engine.assign()
                        except Exception:  # koordlint: disable=broad-except(faulted-window RPC failures are the scenario under test: counted, replay continues)
                            rpc_errors += 1
                        t_assign = time.perf_counter()
                        oracle.sync(**kw)
                        sync_ms = (t_sync - t0) * 1000.0
                        score_ms = (t_score - t_sync) * 1000.0
                        assign_ms = (t_assign - t_score) * 1000.0
                        for rpc, ms in (
                            ("sync", sync_ms), ("score", score_ms),
                            ("assign", assign_ms),
                            ("cycle", sync_ms + score_ms + assign_ms),
                        ):
                            metrics.observe_trace_cycle(
                                event.band, rpc, ms
                            )
                finally:
                    if guard is not None:
                        guard.__exit__(None, None, None)
                        retraces = counter.traces
                if leader is not None:
                    breaker_trips += leader.breaker.stats()["trips"]
                    for b, n in leader.admission.stats()[
                        "shed_by_band"
                    ].items():
                        shed_by_band[b] = shed_by_band.get(b, 0) + n
                    degraded += leader.degraded_replies

                # post-convergence parity vs the unfaulted oracle:
                # flat Score + Assign reply digests must be identical
                # once the stream has drained and the breaker (pass
                # mode) has recovered
                parity_ok, parity_detail = True, ""
                try:
                    engine.band = ""
                    d_e = TraceReplay._digest(
                        engine.score_flat(top_k=k), engine.assign()
                    )
                    if engine.last_degraded:
                        parity_ok = False
                        parity_detail = (
                            "final engine reply still degraded "
                            "(breaker never recovered)"
                        )
                    d_o = TraceReplay._digest(
                        oracle.score_flat(top_k=k), oracle.assign()
                    )
                    if parity_ok and d_e != d_o:
                        parity_ok = False
                        parity_detail = (
                            f"post-convergence digest {d_e[:16]} != "
                            f"oracle {d_o[:16]}"
                        )
                except Exception as exc:  # an unconverged engine IS the failing-parity outcome this control measures
                    parity_ok = False
                    parity_detail = f"convergence probe failed: {exc!r:.200}"
            finally:
                if poison_handle is not None:
                    poison_handle.__exit__(None, None, None)
                engine.close()
                oracle.close()
                try:
                    server.stop(0)
                except Exception:  # koordlint: disable=broad-except(teardown of an already-killed server)
                    pass
                oracle_server.stop(0)
                # drain the surviving leader's (and oracle's) span
                # writers: the caller assembles the export directory
                # right after run() returns
                for sv in (leader, oracle_sv):
                    if sv is not None:
                        sv.telemetry.close()

        return ChaosTraceReport(
            events_replayed=len(trace.events),
            rpc_errors=rpc_errors,
            degraded_replies=degraded,
            breaker_trips=breaker_trips,
            recovery_ms=recovery_ms,
            parity_ok=parity_ok,
            parity_detail=parity_detail,
            retraces=retraces,
            shed_by_band=shed_by_band,
            registry=metrics.registry,
            bands=trace.bands(),
        )


def chaos_trace_slo_specs(bands, recovery_slo_ms: Optional[float] = None):
    """The chaos x trace gate's declarative spec set: the trace gate's
    per-band cycle p99s PLUS a recovery-time SLO over the
    ``rpc="recovery"`` observation (no recovery measured = no data =
    FAILED verdict — a gate that cannot see recovery is a failed
    gate)."""
    from koordinator_tpu.harness.trace import default_slo_specs
    from koordinator_tpu.obs.scorer_metrics import TRACE_CYCLE
    from koordinator_tpu.obs.slo import SloSpec

    # `or`: empty env value means unset (the KOORD_* convention)
    if recovery_slo_ms is None:
        recovery_slo_ms = float(
            os.environ.get("KOORD_CHAOS_RECOVERY_SLO_MS") or "5000"
        )
    specs = default_slo_specs(bands)
    specs.append(SloSpec(
        name="recovery-p99",
        family=TRACE_CYCLE,
        quantile=0.99,
        threshold_ms=float(recovery_slo_ms),
        labels={"rpc": "recovery"},
    ))
    return specs


def overload_band_storm(
    max_inflight: int = 3,
    free_threads: int = 4,
    prod_threads: int = 2,
    reps: int = 24,
    launch_delay_ms: float = 15.0,
    top_k: int = 4,
    nodes: int = 16,
    pods: int = 32,
) -> dict:
    """Drive a mixed-band Score storm into an admission-gated servicer
    and report what the band ladder did with it (the ISSUE 13
    acceptance surface: under overload, free-band sheds absorb the
    pressure while prod-band p99 stays within its SLO).

    Free-band clients outnumber prod clients and every launch carries
    an injected ``launch_delay_ms`` (the trace harness's slow-stage
    idiom) so the in-flight population actually reaches the ladder.
    Returns per-band client-observed p99s (estimated by the same
    obs/slo.py bucket quantiles the gate uses), shed counts by band,
    and raw success/shed tallies."""
    from koordinator_tpu.bridge.client import ScorerClient
    from koordinator_tpu.bridge.server import make_server
    from koordinator_tpu.harness.trace import (
        ClusterModel, TraceConfig, _build_init, slow_stage,
    )
    from koordinator_tpu.obs.scorer_metrics import ScorerMetrics, TRACE_CYCLE
    from koordinator_tpu.obs.slo import histogram_quantile
    from koordinator_tpu.replication.retry import BackoffPolicy

    rng = np.random.default_rng(7)
    cfg = TraceConfig(nodes=nodes, pod_slots=pods, gangs=2,
                      gang_min_member=2)
    init = _build_init(cfg, rng)
    model = ClusterModel(init)
    sv = ScorerServicer(
        max_inflight=max_inflight,
        breaker_threshold=0,  # isolate the ladder from the breaker
        score_memo=False,     # memo hits would dodge the launch delay
        score_incr=False,
    )
    metrics = ScorerMetrics()
    results = {"ok": {}, "shed": {}, "errors": 0}
    results_lock = witness_lock(
        "harness.chaos.overload_band_storm.results_lock")

    with tempfile.TemporaryDirectory(prefix="koord-band-storm-") as tmp:
        sock = os.path.join(tmp, "storm.sock")
        server = make_server(servicer=sv)
        server.add_insecure_port(f"unix://{sock}")
        server.start()
        try:
            seed = ScorerClient(f"unix://{sock}")
            seed.sync(
                node_allocatable=model.nalloc,
                node_requested=model.nreq,
                node_usage=model.nuse,
                metric_fresh=list(model.fresh),
                pod_requests=model.preq,
                pod_estimated=model.pest,
                priority=list(model.priority),
                gang_id=list(model.gang_id),
                quota_id=list(model.quota_id),
                gang_min_member=list(model.gang_min),
                quota_runtime=model.qrt,
                quota_used=model.quse,
                quota_limited=model.qlim,
            )
            sid = seed.snapshot_id
            seed.score_flat(top_k=top_k)  # compile before the clock
            seed.close()

            # one attempt, no retries: a shed must count as a shed,
            # not dissolve into a paced retry
            no_retry = BackoffPolicy(deadline_ms=0.0)

            def worker(band: str) -> None:
                client = ScorerClient(
                    f"unix://{sock}", band=band, retry_policy=no_retry
                )
                # reads only: adopting the seeded Sync's acked id is
                # all a Score needs
                client.snapshot_id = sid
                try:
                    for _ in range(reps):
                        t0 = time.perf_counter()
                        try:
                            client.score_flat(top_k=top_k)
                        except Exception as exc:  # shed replies are the measured outcome; anything else counts as an error tally
                            with results_lock:
                                if "RESOURCE_EXHAUSTED" in str(exc):
                                    results["shed"][band] = (
                                        results["shed"].get(band, 0) + 1
                                    )
                                else:
                                    results["errors"] += 1
                            continue
                        ms = (time.perf_counter() - t0) * 1000.0
                        with results_lock:
                            results["ok"][band] = (
                                results["ok"].get(band, 0) + 1
                            )
                            metrics.observe_trace_cycle(
                                band, "score", ms
                            )
                finally:
                    client.close()

            threads = [
                threading.Thread(
                    target=worker, args=("koord-free",), daemon=True
                )
                for _ in range(free_threads)
            ] + [
                threading.Thread(
                    target=worker, args=("koord-prod",), daemon=True
                )
                for _ in range(prod_threads)
            ]
            with slow_stage(sv, launch_delay_ms):
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=120.0)
        finally:
            server.stop(0)

    return {
        "band_p99_ms": {
            band: histogram_quantile(
                metrics.registry, TRACE_CYCLE, 0.99,
                {"band": band, "rpc": "score"},
            )
            for band in ("koord-prod", "koord-free")
        },
        "served": dict(results["ok"]),
        "shed_client": dict(results["shed"]),
        "shed_by_band": dict(sv.admission.stats()["shed_by_band"]),
        "errors": results["errors"],
        "registry": metrics.registry,
        "max_inflight": max_inflight,
    }
