"""Chaos fault-injection harness for the serving tier (ISSUE 11).

Composable faults against the replication/journal machinery, with the
invariant checkers that make a chaos run a TEST instead of a demo.
Runnable from pytest (tests/test_chaos.py drives the acceptance run)
and from ``bench.py --config failover`` (which adds the real
subprocess SIGKILL on top of the in-process faults here).

Faults (compose freely through :class:`FaultPlan` probabilities plus
the explicit methods):

* **drop / duplicate / reorder** replication frames
  (:class:`ChaosChannel` — the PR-8 fuzz channel, promoted to a shared
  home);
* **corrupt / truncate** frame BYTES (the follower must classify every
  mutation as a discontinuity, never apply it);
* **SIGKILL the leader** (:meth:`ChaosTier.crash_leader` drops the
  leader object with no cleanup — the in-process equivalent of
  ``kill -9``; the journal file keeps only what reached the OS) and
  **warm-restart** it from the journal, or **promote a follower**;
* **stall a follower** (frames buffer; delivered late, they must apply
  or drop as stale — never double-apply);
* **fail a device launch mid-batch** (:func:`fail_next_launch` poisons
  the dispatcher's next launch, exercising the error routing under
  faulted serving);
* **truncate the journal tail** (:meth:`ChaosTier.damage_journal` —
  the torn-write crash shape).

Invariants (raise AssertionError with the failing detail):

* **byte parity vs an unfaulted oracle** — leader mirrors and
  flat-Score reply bytes equal the oracle's after every converged
  step, and every caught-up follower equals the leader;
* **zero torn snapshots** — a frame that did not APPLY leaves the
  follower's observable state byte-identical to before the offer
  (checked on every delivery, not just at the end);
* **zero warm-path retraces** — ``retrace_guard`` holds the post-
  recovery warm stream at zero jit cache misses;
* **bounded recovery** — crash→serving wall time under a caller-set
  budget.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

import numpy as np

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.replication import codec
from koordinator_tpu.replication.follower import (
    APPLIED,
    RESYNC,
    FollowerServicer,
    ReplicaApplier,
)
from koordinator_tpu.replication.journal import FrameJournal

# mirror keys asserted byte-identical between replicas (the PR-8 parity
# surface, shared here so the chaos tests and test_replication.py can
# never drift on what "parity" means)
from koordinator_tpu.bridge import state as _bridge_state

MIRROR_KEYS = _bridge_state._DELTA_TENSORS + (
    "node_fresh", "pod_priority", "pod_priority_class", "pod_gang",
    "pod_quota", "gang_min",
)


@dataclasses.dataclass
class FaultPlan:
    """Per-frame fault probabilities for a :class:`ChaosChannel`."""

    drop: float = 0.0
    duplicate: float = 0.0
    reorder: float = 0.0
    corrupt: float = 0.0
    truncate: float = 0.0


class ChaosChannel:
    """Lossy/reordering/corrupting transport between a leader's frame
    stream and one follower.  Operates on encoded frame BYTES so
    corruption and truncation hit the real wire surface."""

    def __init__(self, rng, plan: FaultPlan):
        self.rng = rng
        self.plan = plan
        self.delayed: List[bytes] = []
        self.injected = {"drop": 0, "duplicate": 0, "reorder": 0,
                         "corrupt": 0, "truncate": 0}

    def _mutate(self, raw: bytes) -> bytes:
        roll = self.rng.random()
        if roll < self.plan.corrupt and len(raw) > codec.HEADER_LEN:
            self.injected["corrupt"] += 1
            i = int(self.rng.integers(0, len(raw)))
            b = bytearray(raw)
            b[i] ^= 0xFF
            return bytes(b)
        if roll < self.plan.corrupt + self.plan.truncate and len(raw) > 1:
            self.injected["truncate"] += 1
            return raw[: int(self.rng.integers(1, len(raw)))]
        return raw

    def send(self, raw: bytes) -> List[bytes]:
        out: List[bytes] = []
        roll = self.rng.random()
        if roll < self.plan.drop:
            self.injected["drop"] += 1
        elif roll < self.plan.drop + self.plan.duplicate:
            self.injected["duplicate"] += 1
            out += [self._mutate(raw), self._mutate(raw)]
        elif roll < self.plan.drop + self.plan.duplicate + self.plan.reorder:
            self.injected["reorder"] += 1
            self.delayed.append(raw)
        else:
            out.append(self._mutate(raw))
        if self.delayed and self.rng.random() < 0.6:
            out.append(self.delayed.pop(0))
        return out

    def flush(self) -> List[bytes]:
        out, self.delayed = self.delayed, []
        return out


@contextmanager
def fail_next_launch(servicer, n: int = 1,
                     exc_factory=lambda: RuntimeError("chaos: injected device launch failure")):
    """Poison the next ``n`` coalesced launches on ``servicer``: the
    dispatcher's launch callable raises before touching the device.
    The dispatcher must route the failure to the batch's callers and
    keep serving afterwards — the fault a flaky device injects
    mid-batch."""
    dispatch = servicer.dispatch
    real = dispatch._launch_batch
    remaining = [int(n)]

    def poisoned(batch):
        if remaining[0] > 0:
            remaining[0] -= 1
            raise exc_factory()
        return real(batch)

    dispatch._launch_batch = poisoned
    try:
        yield
    finally:
        dispatch._launch_batch = real


def flat_score_bytes(sv, sid: str, top_k: int = 8) -> bytes:
    reply = sv.score(
        pb2.ScoreRequest(snapshot_id=sid, top_k=top_k, flat=True)
    )
    return reply.flat.SerializeToString()


def state_digest(sv) -> str:
    """Order-stable digest of every replicated mirror — the cheap
    every-delivery torn-snapshot probe (flat_score_bytes is the
    expensive reply-surface check run at checkpoints)."""
    h = hashlib.sha256()
    st = sv.state
    for key in MIRROR_KEYS:
        v = getattr(st, key)
        h.update(key.encode())
        if v is None:
            h.update(b"\x00")
        else:
            a = np.asarray(v)
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    h.update(repr(st.node_names).encode())
    h.update(repr(st.pod_names).encode())
    h.update(sv.snapshot_id().encode())
    return h.hexdigest()


def assert_mirror_parity(a_sv, b_sv, ids: bool = True) -> None:
    if ids:
        assert b_sv.snapshot_id() == a_sv.snapshot_id(), (
            f"snapshot ids diverged: {a_sv.snapshot_id()} vs "
            f"{b_sv.snapshot_id()}"
        )
    a, b = a_sv.state, b_sv.state
    for key in MIRROR_KEYS:
        va, vb = getattr(a, key), getattr(b, key)
        if va is None or vb is None:
            assert va is None and vb is None, f"{key}: {va!r} vs {vb!r}"
        else:
            va, vb = np.asarray(va), np.asarray(vb)
            assert va.dtype == vb.dtype, key
            assert np.array_equal(va, vb), f"mirror {key} diverged"
    assert a.node_names == b.node_names
    assert a.pod_names == b.pod_names
    assert a.node_bucket == b.node_bucket
    assert a.pod_bucket == b.pod_bucket


class _Follower:
    __slots__ = ("servicer", "applier", "channel", "stalled", "buffer")

    def __init__(self, servicer, applier, channel):
        self.servicer = servicer
        self.applier = applier
        self.channel = channel
        self.stalled = False
        self.buffer: List[bytes] = []


class ChaosTier:
    """One in-process serving tier under fault injection: a journaled
    leader, N followers behind chaos channels, and an UNFAULTED oracle
    replaying the same Sync stream.

    The tier checks the no-torn-snapshot invariant on EVERY delivery:
    an offer that does not return APPLIED must leave the follower's
    state digest untouched.  ``converge()`` then brings every follower
    to the leader (the documented one-shot full resync where needed)
    and asserts full byte parity against leader and oracle.
    """

    def __init__(
        self,
        state_dir: str,
        followers: int = 1,
        plan: Optional[FaultPlan] = None,
        seed: int = 0,
        compact_every: int = 256,
        servicer_kw: Optional[dict] = None,
    ):
        self.state_dir = state_dir
        self.journal_path = os.path.join(state_dir, "journal.krj")
        self.compact_every = compact_every
        self.plan = plan or FaultPlan()
        self.rng = np.random.default_rng(seed)
        self.servicer_kw = dict(servicer_kw or {})
        self.servicer_kw.setdefault("score_memo", False)
        self.leader = ScorerServicer(**self.servicer_kw)
        self.journal = FrameJournal(
            self.journal_path, compact_every=compact_every
        )
        self.journal.recover(self.leader)
        self.journal.attach(self.leader)
        self._capture_frames(self.leader)
        self.oracle = ScorerServicer(**self.servicer_kw)
        self.followers: List[_Follower] = []
        for _ in range(int(followers)):
            sv = FollowerServicer(**self.servicer_kw)
            self.followers.append(_Follower(
                sv, ReplicaApplier(sv),
                ChaosChannel(self.rng, self.plan),
            ))
        self.resyncs = 0
        self.torn_checks = 0
        self.stats: Dict[str, int] = {"syncs": 0, "delivered": 0}
        for f in self.followers:
            self._resync(f)

    # -- leader plumbing --
    def _capture_frames(self, leader) -> None:
        from koordinator_tpu.bridge.client import parse_snapshot_id

        self._frames: List[bytes] = []

        def hook(req, snapshot_id, wire_bytes=None):
            epoch, gen = parse_snapshot_id(snapshot_id)
            payload = (
                wire_bytes if wire_bytes is not None
                else req.SerializeToString()
            )
            self._frames.append(codec.encode_frame(
                codec.KIND_DELTA, epoch, gen,
                int(time.time() * 1e6), payload,
            ))

        leader.replication_hook = hook

    def full_frame_bytes(self) -> bytes:
        epoch, gen, payload = self.leader.export_replication_snapshot()
        return codec.encode_frame(
            codec.KIND_FULL, epoch, gen, int(time.time() * 1e6), payload
        )

    # -- the write stream --
    def sync(self, req: "pb2.SyncRequest", oracle: bool = True) -> str:
        """One committed Sync on leader (+oracle), frames delivered to
        every follower through its chaos channel."""
        if oracle:
            self.oracle.sync(pb2.SyncRequest.FromString(
                req.SerializeToString()
            ))
        sid = self.leader.sync(req).snapshot_id
        self.stats["syncs"] += 1
        frame = self._frames[-1] if self._frames else None
        for f in self.followers:
            if frame is not None:
                self._deliver(f, self.channel_out(f, frame))
        return sid

    def channel_out(self, f: _Follower, frame: bytes) -> List[bytes]:
        out = f.channel.send(frame)
        if f.stalled:
            f.buffer.extend(out)
            return []
        return out

    def _deliver(self, f: _Follower, raws: List[bytes]) -> None:
        for raw in raws:
            self.stats["delivered"] += 1
            before = state_digest(f.servicer)
            self.torn_checks += 1
            try:
                frame = codec.decode_frame(raw)
            except codec.FrameError:
                # the transport layer's contract: counted + resync
                assert state_digest(f.servicer) == before, (
                    "TORN SNAPSHOT: a malformed frame mutated state"
                )
                self._resync(f)
                continue
            result = f.applier.offer(frame)
            after = state_digest(f.servicer)
            if result == APPLIED:
                continue
            assert after == before, (
                f"TORN SNAPSHOT: offer({result}) mutated follower state"
            )
            if result == RESYNC:
                self._resync(f)

    def _resync(self, f: _Follower) -> None:
        self.resyncs += 1
        assert f.applier.offer(
            codec.decode_frame(self.full_frame_bytes())
        ) == APPLIED

    # -- explicit faults --
    def stall_follower(self, i: int) -> None:
        self.followers[i].stalled = True

    def unstall_follower(self, i: int) -> None:
        f = self.followers[i]
        f.stalled = False
        buffered, f.buffer = f.buffer, []
        self._deliver(f, buffered)

    def crash_leader(self) -> None:
        """The in-process SIGKILL: no stop(), no flush, no close — the
        object graph just dies.  Only what the journal already wrote
        to the OS survives (FrameJournal flushes per append, exactly
        the SIGKILL durability contract)."""
        self.leader = None
        self.journal = None

    def restart_leader(self) -> dict:
        """Warm-restart from the journal; returns the replay stats.
        The restarted leader must resume the same s<epoch>-<gen> chain
        the journal holds."""
        assert self.leader is None, "crash_leader first"
        t0 = time.perf_counter()
        self.leader = ScorerServicer(**self.servicer_kw)
        self.journal = FrameJournal(
            self.journal_path, compact_every=self.compact_every
        )
        stats = self.journal.recover(self.leader)
        self.journal.attach(self.leader)
        self._capture_frames(self.leader)
        stats["recovery_ms"] = (time.perf_counter() - t0) * 1000.0
        # the subscription handshake's fallback, mirrored: a follower
        # whose position the restarted leader cannot extend (a torn
        # tail rewound the journal BEHIND the follower — the frames it
        # already applied are gone from the chain) must full-resync,
        # exactly what the leader answers a non-coverable hello with.
        # Without this a rewound leader re-mints generation numbers
        # the follower already holds with different content — the one
        # fork the epoch fence cannot see.
        from koordinator_tpu.bridge.client import parse_snapshot_id

        l_epoch, l_gen = parse_snapshot_id(self.leader.snapshot_id())
        for f in self.followers:
            f_epoch, f_gen = f.applier.position()
            if f_epoch != l_epoch or f_gen > l_gen:
                self._resync(f)
        return stats

    def promote(self, i: int) -> str:
        """Promote follower ``i`` to the writer role: it bumps its
        epoch, opens its own journal (seeded with a full-state frame)
        and takes over the frame stream; the old leader — typically
        already crashed — is forgotten."""
        f = self.followers.pop(i)
        sid = f.servicer.promote()
        self.leader = f.servicer
        self.journal = FrameJournal(
            self.journal_path + ".promoted",
            compact_every=self.compact_every,
        )
        epoch, gen, payload = self.leader.export_replication_snapshot()
        self.journal.write_base(epoch, gen, payload)
        self.journal.attach(self.leader)
        self._capture_frames(self.leader)
        # surviving followers fence on the new epoch at their next
        # frame; resync them through the documented one-shot path now
        for other in self.followers:
            self._resync(other)
        return sid

    def damage_journal(self, cut_bytes: int = 7) -> None:
        """Tear the journal tail (the mid-append crash shape)."""
        size = os.path.getsize(self.journal_path)
        with open(self.journal_path, "r+b") as fh:
            fh.truncate(max(0, size - cut_bytes))

    # -- invariants --
    def converge(self) -> None:
        """Bring every follower to the leader's exact state (the
        documented resync where the chain broke) and assert byte
        parity: follower==leader mirrors + ids, leader==oracle mirrors
        and flat-Score reply bytes."""
        for f in self.followers:
            if f.stalled:
                continue
            self._deliver(f, f.channel.flush())
            if f.servicer.snapshot_id() != self.leader.snapshot_id():
                self._resync(f)
            assert_mirror_parity(self.leader, f.servicer)
        assert_mirror_parity(self.oracle, self.leader, ids=False)
        sid = self.leader.snapshot_id()
        want = flat_score_bytes(self.oracle, self.oracle.snapshot_id())
        assert flat_score_bytes(self.leader, sid) == want, (
            "leader flat-Score bytes diverged from the unfaulted oracle"
        )
        for f in self.followers:
            if f.stalled:
                continue
            assert flat_score_bytes(f.servicer, sid) == want, (
                "follower flat-Score bytes diverged from the oracle"
            )
