"""Synthetic snapshot generators for the five BASELINE.json configs.

Each generator returns ``(nodes, pods, gangs, quotas)`` plain-dict lists
accepted by ``model.snapshot.encode_snapshot``.  Values are deterministic
per seed.  Shapes follow /root/repo/BASELINE.json:

1. ``spark_colocation``   — 3 nodes, spark-driver/executor + nginx pods
   (reference ``examples/spark-jobs``).
2. ``loadaware_joint``    — 1k pods x 200 nodes, LoadAware + Fit.
3. ``gang_batch``         — 5k pods x 500 nodes, PodGroups minMember=8.
4. ``quota_colocation``   — 10k pods x 2k nodes, LS/BE mix + quota tree.
5. rebalance reuses config 4's snapshot.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

Mi = 1024 * 1024
Gi = 1024 * Mi


def _node(name: str, cpu_milli: int, mem: int, used_cpu: int, used_mem: int, pods_cap: int = 110) -> Dict:
    return {
        "name": name,
        "allocatable": {"cpu": f"{cpu_milli}m", "memory": mem, "pods": pods_cap},
        "requested": {},
        "usage": {"cpu": f"{used_cpu}m", "memory": used_mem},
        "metric_fresh": True,
    }


def spark_colocation(seed: int = 0) -> Tuple[List, List, List, List]:
    rng = np.random.RandomState(seed)
    nodes = [
        _node(f"kind-worker-{i}", 8000, 16 * Gi, int(rng.randint(500, 2000)), int(rng.randint(1, 4) * Gi))
        for i in range(3)
    ]
    pods: List[Dict] = []
    # one nginx LS deployment + spark driver/executors as koord-batch
    for i in range(6):
        pods.append(
            {
                "name": f"nginx-{i}",
                "requests": {"cpu": "500m", "memory": 512 * Mi, "pods": 1},
                "limits": {"cpu": "1", "memory": Gi},
                "qos": "LS",
                "priority_class": "koord-prod",
                "priority": 9500,
            }
        )
    pods.append(
        {
            "name": "spark-driver",
            "requests": {"cpu": "1", "memory": Gi, "pods": 1},
            "limits": {"cpu": "1", "memory": Gi},
            "qos": "BE",
            "priority_class": "koord-batch",
            "priority": 5500,
        }
    )
    for i in range(8):
        pods.append(
            {
                "name": f"spark-exec-{i}",
                "requests": {"cpu": "1", "memory": 2 * Gi, "pods": 1},
                "limits": {"cpu": "2", "memory": 2 * Gi},
                "qos": "BE",
                "priority_class": "koord-batch",
                "priority": 5400,
            }
        )
    return nodes, pods, [], []


def _random_nodes(rng, count: int, cpu_choices=(16000, 32000, 64000), mem_per_core=4 * Gi) -> List[Dict]:
    nodes = []
    for i in range(count):
        cpu = int(rng.choice(cpu_choices))
        mem = (cpu // 1000) * mem_per_core
        used_frac = rng.uniform(0.05, 0.55)
        nodes.append(
            _node(
                f"node-{i}",
                cpu,
                mem,
                int(cpu * used_frac),
                int(mem * rng.uniform(0.05, 0.6)),
                pods_cap=256,
            )
        )
    return nodes


def _random_pods(rng, count: int, name_prefix: str = "pod") -> List[Dict]:
    pods = []
    for i in range(count):
        cpu_m = int(rng.choice([250, 500, 1000, 2000, 4000]))
        mem = int(rng.choice([256, 512, 1024, 2048, 4096])) * Mi
        be = rng.uniform() < 0.4
        pods.append(
            {
                "name": f"{name_prefix}-{i}",
                "requests": {"cpu": f"{cpu_m}m", "memory": mem, "pods": 1},
                "limits": {"cpu": f"{cpu_m * 2}m", "memory": mem * 2},
                "qos": "BE" if be else "LS",
                "priority_class": "koord-batch" if be else "koord-prod",
                "priority": int(5000 + rng.randint(0, 999)) if be else int(9000 + rng.randint(0, 999)),
            }
        )
    return pods


def loadaware_joint(seed: int = 0, pods: int = 1000, nodes: int = 200):
    rng = np.random.RandomState(seed)
    return _random_nodes(rng, nodes), _random_pods(rng, pods), [], []


def gang_batch(seed: int = 0, pods: int = 5000, nodes: int = 500, min_member: int = 8):
    rng = np.random.RandomState(seed)
    node_list = _random_nodes(rng, nodes)
    pod_list = _random_pods(rng, pods, name_prefix="member")
    gangs = []
    n_gangs = pods // min_member
    for g in range(n_gangs):
        gangs.append({"name": f"gang-{g}", "min_member": min_member})
    for i, p in enumerate(pod_list):
        if i < n_gangs * min_member:
            p["gang"] = f"gang-{i // min_member}"
    return node_list, pod_list, gangs, []


def quota_colocation(seed: int = 0, pods: int = 10000, nodes: int = 2000, tenants: int = 16):
    """LS/BE multi-tenant mix with an elastic quota group per tenant.

    Quota ``min``/``max`` are chosen so the tree's fair division matters:
    total min ~60% of cluster CPU, max twice min.
    """
    rng = np.random.RandomState(seed)
    node_list = _random_nodes(rng, nodes)
    pod_list = _random_pods(rng, pods, name_prefix="tenant-pod")
    total_cpu = sum(int(n["allocatable"]["cpu"][:-1]) for n in node_list)
    total_mem = sum(int(n["allocatable"]["memory"]) for n in node_list)
    quotas = []
    for t in range(tenants):
        quotas.append(
            {
                "name": f"tenant-{t}",
                "min": {"cpu": f"{total_cpu * 6 // 10 // tenants}m", "memory": total_mem * 6 // 10 // tenants},
                "max": {"cpu": f"{total_cpu * 12 // 10 // tenants}m", "memory": total_mem * 12 // 10 // tenants},
                "shared_weight": int(rng.randint(1, 4)),
                "used": {},
            }
        )
    for i, p in enumerate(pod_list):
        p["quota"] = f"tenant-{i % tenants}"
    return node_list, pod_list, [], quotas


CONFIGS = {
    "spark_colocation": spark_colocation,
    "loadaware_joint": loadaware_joint,
    "gang_batch": gang_batch,
    "quota_colocation": quota_colocation,
}


def quota_colocation_snapshot(
    seed: int = 0,
    pods: int = 10000,
    nodes: int = 2000,
    tenants: int = 16,
    node_bucket=None,
    pod_bucket=None,
):
    """The encoded quota_colocation snapshot — ONE recipe shared by
    bench.py, the multichip dryrun, and the parity tests so every consumer
    measures the same cluster (resource vectors, quota-id mapping, cluster
    totals, quota-table inputs).

    Returns (snapshot, node_list, pod_list, gangs, quotas, quota_dicts).
    """
    node_list, pod_list, gangs, quotas = quota_colocation(
        seed=seed, pods=pods, nodes=nodes, tenants=tenants
    )
    snap, qdicts = encode_quota_lists(
        node_list,
        pod_list,
        gangs,
        quotas,
        node_bucket=node_bucket or nodes,
        pod_bucket=pod_bucket or pods,
    )
    return snap, node_list, pod_list, gangs, quotas, qdicts


def encode_quota_lists(
    node_list, pod_list, gangs, quotas, node_bucket=None, pod_bucket=None
):
    """Encode explicit node/pod/quota lists with the ONE quota-table
    recipe (quota-id mapping by pod "quota" name, cluster totals from
    node allocatables) — shared by quota_colocation_snapshot and callers
    that mutate the lists first (bench --config extras), so the recipe
    cannot desync across call sites.  Returns (snapshot, quota_dicts)."""
    from koordinator_tpu.constraints import build_quota_table_inputs
    from koordinator_tpu.model import encode_snapshot, resources as res

    pod_reqs = [res.resource_vector(p["requests"]) for p in pod_list]
    qidx = {q["name"]: i for i, q in enumerate(quotas)}
    qids = [qidx.get(p.get("quota"), -1) for p in pod_list]
    total = [0] * res.NUM_RESOURCES
    for n in node_list:
        v = res.resource_vector(n["allocatable"])
        total = [a + b for a, b in zip(total, v)]
    qdicts = build_quota_table_inputs(quotas, pod_reqs, qids, total)
    snap = encode_snapshot(
        node_list,
        pod_list,
        gangs,
        qdicts,
        node_bucket=node_bucket,
        pod_bucket=pod_bucket,
    )
    return snap, qdicts
