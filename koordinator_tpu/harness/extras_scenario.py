"""Extras-path scenario + native interchange for the C++ baseline.

Round-4 review #4/#6: the composed extended-plugin cycle (NUMA zones,
DeviceShare, Reservation) was parity-checked only against the same-author
Python oracle.  This module gives the extras path an INDEPENDENT check:

* ``extras_scenario`` builds one deterministic cluster whose extras
  tensors exercise all three plugins (zones with a NUMA policy mix,
  GPU/RDMA minors, matched reservations);
* ``plugin_extra_tensors`` composes the real TensorPlugins through the
  FrameworkExtender (exactly what ``--config extras`` feeds the kernel);
* ``write_extras_file`` serializes the RAW subsystem tables (not the
  composed tensors!) into a simple sectioned binary that
  ``native/score_baseline.cpp`` re-derives the mask/scores from — an
  independently-written implementation of the zone fit/score
  (``nodenumaresource/scoring.go:55``), device count-fit
  (``deviceshare/device_cache.go:329-352``), and reservation nomination
  (``reservation/scoring.go:42,105,177``) semantics.

File format (little-endian): magic ``KEXT1\n``, then per section a
u32 name length, the name, u32 ndim, i64 dims, and the row-major i64
payload (bools/i32 widened to i64).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np

from koordinator_tpu.model import resources as res
from koordinator_tpu.model.device import (
    DEVICE_RESOURCE_AXIS,
    DeviceBatch,
    encode_devices,
)
from koordinator_tpu.model.reservation import (
    ReservationTable,
    encode_reservations,
)
from koordinator_tpu.model.topology import ZoneBatch, encode_zones

Gi = 1 << 30
Mi = 1 << 20

# canonical device-axis projection: C column -> snapshot resource index
DEV_AXIS = [res.RESOURCE_INDEX[n] for n in DEVICE_RESOURCE_AXIS]


def extras_scenario(
    nodes: List[Dict],
    pods: List[Dict],
    seed: int = 0,
    node_bucket: int = 0,
    pod_bucket: int = 0,
) -> Tuple[ZoneBatch, np.ndarray, DeviceBatch, ReservationTable, List[Dict], List[Dict]]:
    """Deterministic extras tables for an existing node/pod list, plus
    the MUTATED node/pod lists that make every plugin leg load-bearing
    (callers must encode the snapshot from the returned lists):

    * every node gets 2 NUMA zones splitting its FULL allocatable vector
      (cpu, memory, pods, device axes — ``zone_fit_mask`` checks every
      requested axis), with a policy mix over the node index
      (none / best-effort / restricted / single-numa-node);
    * every 4th node carries 4 GPU minors (some partially used) and one
      RDMA NIC, and advertises the device resources in its allocatable;
    * every 8th pod requests one GPU card (every 32nd two cards, every
      64th also an RDMA share), so device count-fit and scoreNode have
      real work on both implementations;
    * one reservation per 16th node, matched to every 8th pod.

    Returns ``(zones, policy, devices, rsv, nodes_out, pods_out)``.
    """
    from koordinator_tpu.model.snapshot import pad_bucket

    rng = np.random.RandomState(seed)
    N = len(nodes)
    P = len(pods)
    node_bucket = node_bucket or pad_bucket(N)
    pod_bucket = pod_bucket or pad_bucket(P)

    # device-carrying nodes advertise the resources node-level (the
    # reference's device webhook patches Node status the same way)
    nodes_out: List[Dict] = []
    for i, nd in enumerate(nodes):
        nd = dict(nd)
        if i % 4 == 0:
            alloc = dict(nd["allocatable"])
            alloc["koordinator.sh/gpu-core"] = 400
            alloc["koordinator.sh/gpu-memory"] = 4 * 16 * Gi
            alloc["koordinator.sh/gpu-memory-ratio"] = 400
            alloc["koordinator.sh/rdma"] = 100
            nd["allocatable"] = alloc
        nodes_out.append(nd)

    # every 8th pod requests a GPU card; the koordlet-side webhook fills
    # memory from ratio, so ratio+core is the canonical request shape
    pods_out: List[Dict] = []
    for p, pod in enumerate(pods):
        pod = dict(pod)
        if p % 8 == 0:
            reqs = dict(pod.get("requests", {}))
            cards = 2 if p % 32 == 0 else 1
            reqs["koordinator.sh/gpu-core"] = 100 * cards
            reqs["koordinator.sh/gpu-memory-ratio"] = 100 * cards
            if p % 64 == 0:
                reqs["koordinator.sh/rdma"] = 50
            pod["requests"] = reqs
        pods_out.append(pod)

    zone_specs = []
    for i, nd in enumerate(nodes_out):
        full = res.resource_vector(nd["allocatable"])
        used_cpu = int(rng.randint(0, max(full[res.RESOURCE_INDEX[res.CPU]] // 4, 1)))
        # axis units (cpu milli, MiB) back through format_quantity so
        # encode_zones' resource_vector round-trips them exactly
        half0 = {
            name: res.format_quantity(int(full[res.RESOURCE_INDEX[name]]) // 2, name)
            for name in res.RESOURCE_AXIS
            if full[res.RESOURCE_INDEX[name]]
        }
        half1 = {
            name: res.format_quantity(
                int(full[res.RESOURCE_INDEX[name]])
                - int(full[res.RESOURCE_INDEX[name]]) // 2,
                name,
            )
            for name in res.RESOURCE_AXIS
            if full[res.RESOURCE_INDEX[name]]
        }
        zones = [
            {"allocatable": half0, "requested": {"cpu": f"{used_cpu}m"}},
            {"allocatable": half1, "requested": {}},
        ]
        zone_specs.append({"zones": zones})
    zbatch = encode_zones(zone_specs, node_bucket=node_bucket)
    policy = np.asarray(
        [i % 4 for i in range(N)] + [0] * (node_bucket - N), np.int32
    )

    dev_specs = []
    for i in range(N):
        devs = []
        if i % 4 == 0:
            for m in range(4):
                free_core = 100 if (i + m) % 3 else 40
                devs.append(
                    {
                        "type": "gpu",
                        "minor": m,
                        "total": {
                            "koordinator.sh/gpu-core": 100,
                            "koordinator.sh/gpu-memory": 16 * Gi,
                            "koordinator.sh/gpu-memory-ratio": 100,
                        },
                        "free": {
                            "koordinator.sh/gpu-core": free_core,
                            "koordinator.sh/gpu-memory": 16 * Gi * free_core // 100,
                            "koordinator.sh/gpu-memory-ratio": free_core,
                        },
                        "topology": {"numaNode": m // 2},
                    }
                )
            devs.append(
                {
                    "type": "rdma",
                    "minor": 0,
                    "total": {"koordinator.sh/rdma": 100},
                    "free": {"koordinator.sh/rdma": 100},
                    "topology": {"numaNode": 0},
                }
            )
        dev_specs.append({"devices": devs})
    dbatch = encode_devices(dev_specs, node_bucket=node_bucket)

    # reservations match pods by owner label selector (the reference's
    # MatchReservationOwners label path); tag every 8th pod round-robin
    rsv_specs = []
    node_names = [nd["name"] for nd in nodes_out]
    n_rsv = max(1, len(range(0, N, 16)))
    for k, i in enumerate(range(0, N, 16)):
        rsv_specs.append(
            {
                "name": f"rsv-{k}",
                "node": node_names[i],
                "allocatable": {"cpu": "4000m", "memory": 8 * Gi},
                "allocated": {"cpu": "1000m", "memory": 2 * Gi},
                "allocate_policy": "Aligned" if i % 32 else "Default",
                "order": (k + 1) if i % 48 == 0 else 0,
                "owners": [{"label_selector": {"rsv-owner": f"rsv-{k}"}}],
                "labels": {
                    "reservation-type": "gold" if k % 2 == 0 else "general"
                },
            }
        )
    for p, pod in enumerate(pods_out):
        if p % 8 == 0:
            labels = dict(pod.get("labels", {}))
            labels["rsv-owner"] = f"rsv-{(p // 8) % n_rsv}"
            pod["labels"] = labels
        if p % 16 == 0 and n_rsv > 1:
            # required reservation affinity (reference exact key): these
            # pods may only land on nodes holding a matched gold-labeled
            # reservation — the affinity filter leg is load-bearing
            anns = dict(pod.get("annotations", {}))
            anns["scheduling.koordinator.sh/reservation-affinity"] = {
                "reservationSelector": {"reservation-type": "gold"}
            }
            pod["annotations"] = anns
    rsv = encode_reservations(
        rsv_specs, pods_out, node_names, pod_bucket=pod_bucket
    )
    return zbatch, policy, dbatch, rsv, nodes_out, pods_out


def plugin_extra_tensors(snapshot, zones, policy, devices, rsv, cfg=None):
    """Compose the real plugins into (extra_mask, extra_scores) — the
    exact tensors ``FrameworkExtender.run_cycle`` would feed the solver.

    The composition runs as ONE jitted program: eagerly, the [P, N, Z, R]
    zone broadcast materializes multi-GB intermediates at the 10k x 2k
    benchmark shape (and on the tunneled TPU every eager op pays a
    network round trip); fused, XLA keeps only the [P, N] outputs hot."""
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.config import DEFAULT_CYCLE_CONFIG
    from koordinator_tpu.scheduler.framework import CycleContext, FrameworkExtender
    from koordinator_tpu.scheduler.plugins import (
        DeviceSharePlugin,
        NodeNUMAResourcePlugin,
        ReservationPlugin,
    )

    cfg = cfg or DEFAULT_CYCLE_CONFIG

    @jax.jit
    def compose(snapshot, zones, policy, devices, rsv):
        ctx = CycleContext(
            snapshot=snapshot,
            cfg=cfg,
            extras={
                "zones": zones,
                "numa_policy": policy,
                "devices": devices,
                "reservations": rsv,
            },
        )
        fx = FrameworkExtender(
            plugins=[
                NodeNUMAResourcePlugin(),
                ReservationPlugin(),
                DeviceSharePlugin(),
            ]
        )
        mask, scores, _ = fx.extended_tensors(ctx)
        return mask, scores

    return compose(snapshot, zones, jnp.asarray(policy), devices, rsv)


def _section(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr, np.int64)
    head = struct.pack("<I", len(name)) + name.encode()
    head += struct.pack("<I", arr.ndim)
    head += np.asarray(arr.shape, "<i8").tobytes()
    return head + arr.astype("<i8").tobytes()


def write_extras_file(
    path: str,
    zones: ZoneBatch,
    policy: np.ndarray,
    devices: DeviceBatch,
    rsv: ReservationTable,
    fit_weights: np.ndarray,
) -> None:
    sections = {
        "fit_weights": np.asarray(fit_weights),
        "zone_alloc": np.asarray(zones.allocatable),
        "zone_req": np.asarray(zones.requested),
        "zone_valid": np.asarray(zones.valid),
        "numa_policy": np.asarray(policy),
        "dev_total": np.asarray(devices.total),
        "dev_free": np.asarray(devices.free),
        "dev_type": np.asarray(devices.dev_type),
        "dev_valid": np.asarray(devices.valid),
        "dev_axis": np.asarray(DEV_AXIS),
        "rsv_node": np.asarray(rsv.node_index),
        "rsv_allocatable": np.asarray(rsv.allocatable),
        "rsv_allocated": np.asarray(rsv.allocated),
        "rsv_declared": np.asarray(rsv.declared),
        "rsv_policy": np.asarray(rsv.allocate_policy),
        "rsv_order": np.asarray(rsv.order),
        "rsv_unschedulable": np.asarray(rsv.unschedulable),
        "rsv_valid": np.asarray(rsv.valid),
        "rsv_matched": np.asarray(rsv.matched),
        "rsv_affinity_required": (
            np.asarray(rsv.affinity_required)
            if rsv.affinity_required is not None
            else np.zeros(np.asarray(rsv.matched).shape[0], bool)
        ),
    }
    with open(path, "wb") as f:
        f.write(b"KEXT1\n")
        for name, arr in sections.items():
            f.write(_section(name, arr))
